"""Cell size-factor estimation.

Capability parity with the reference's normalisation step
(reference R/consensusClust.R:274-288): deconvolution (pooled) size factors in
the spirit of scran::calculateSumFactors (Lun et al. 2016), plus the
reference's geometric-mean stabilisation with zero/NaN repair to 0.001
(:276-285).

TPU-first design: the pooling linear system is never materialised. Pools are
contiguous windows on a ring of cells ordered by library size, so both the
pooled gene profiles and the normal-equation matvec ``A^T A x`` are rolling
window sums (cumsum differences) — O(n * n_sizes) work, solved by conjugate
gradients on device. The reference instead delegates to scran's C++ sparse QR.

All functions take counts as a dense [n_cells, n_genes] array (JAX or numpy).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

_DEFAULT_POOL_SIZES = tuple(range(21, 102, 5))  # scran's seq(21, 101, 5)
_MAX_RATIO_GENES = 4096  # cap genes used for pool median ratios (memory bound)


def libsize_factors(counts: jax.Array) -> jax.Array:
    """Library-size factors, scaled to unit mean.

    All-zero cells get factor 1 (their normalised row is all-zero either way);
    a zero factor would turn shifted_log's x/sf into 0/0 NaNs.
    """
    lib = jnp.sum(counts, axis=1)
    pos = lib > 0
    mean_pos = jnp.sum(jnp.where(pos, lib, 0.0)) / jnp.maximum(jnp.sum(pos), 1.0)
    sf = lib / jnp.maximum(mean_pos, 1e-12)
    return jnp.where(pos, sf, 1.0)


def _ring_window_sum(x: jax.Array, size: int) -> jax.Array:
    """out[i] = sum(x[i : i+size]) with wraparound, along axis 0."""
    n = x.shape[0]
    ext = jnp.concatenate([x, x[: size - 1]], axis=0) if size > 1 else x
    cs = jnp.cumsum(ext, axis=0, dtype=jnp.float32)
    zero = jnp.zeros_like(cs[:1])
    cs = jnp.concatenate([zero, cs], axis=0)
    return cs[size : size + n] - cs[:n]


def _ring_window_sum_rev(x: jax.Array, size: int) -> jax.Array:
    """out[j] = sum over windows containing j = sum(x[j-size+1 : j+1]) wrapped."""
    n = x.shape[0]
    return jnp.roll(_ring_window_sum(x, size), size - 1, axis=0)


@functools.partial(jax.jit, static_argnames=("sizes", "cg_iters"))  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
def _deconv_theta(scaled: jax.Array, sizes: tuple, cg_iters: int = 50) -> jax.Array:
    """Solve the ring-pool system for per-cell bias theta.

    scaled: [n, g_sub] count profiles divided by library size, in ring order.
    For pool P (window of the ring): sum_{j in P} theta_j ~= median_g of
    (pooled scaled counts)_g / ref_g. Least squares over all windows of all
    sizes, plus weak per-cell anchor equations for full rank.
    """
    n = scaled.shape[0]
    ref = jnp.mean(scaled, axis=0)  # pseudo-cell profile
    ref = jnp.maximum(ref, 1e-12)

    # Right-hand side: b = A^T r, accumulated size by size.
    def rhs_for_size(s):
        pooled = _ring_window_sum(scaled, s)              # [n, g_sub]
        ratios = jnp.median(pooled / ref[None, :], axis=1)  # [n]
        return _ring_window_sum_rev(ratios, s)

    # Weak anchors: theta_j ~= per-cell median ratio, weight w << 1.
    w = 0.1
    cell_ratio = jnp.median(scaled / ref[None, :], axis=1)

    atb = w * cell_ratio
    for s in sizes:
        atb = atb + rhs_for_size(s)

    def ata_mv(x):
        out = w * x
        for s in sizes:
            out = out + _ring_window_sum_rev(_ring_window_sum(x, s), s)
        return out

    x0 = jnp.ones((n,), jnp.float32)
    theta, _ = jax.scipy.sparse.linalg.cg(ata_mv, atb, x0=x0, maxiter=cg_iters)
    return theta


def deconvolution_factors(
    counts: jax.Array,
    pool_sizes: Optional[Sequence[int]] = None,
    min_mean: float = 0.1,
) -> jax.Array:
    """Pooled deconvolution size factors, scaled to unit mean.

    Mirrors the capability of scran::calculateSumFactors as used at
    reference R/consensusClust.R:275; falls back to library-size factors for
    tiny inputs where pooling is meaningless (n < 8).
    """
    counts = jnp.asarray(counts, jnp.float32)
    n = counts.shape[0]
    if n < 8:
        return libsize_factors(counts)
    if pool_sizes is not None:
        bad = [s for s in pool_sizes if not (1 < int(s) <= n)]
        if bad:
            raise ValueError(f"pool_sizes must be in (1, n_cells={n}]; got {bad}")

    lib = jnp.sum(counts, axis=1)
    lib = jnp.maximum(lib, 1e-12)

    if pool_sizes is None:
        pool_sizes = default_pool_sizes(n)
    sizes = tuple(int(s) for s in pool_sizes)

    # Filter to reasonably-expressed genes for the median ratios (scran's
    # min.mean filter), capped for memory; host-side static gene choice.
    mean_count = np.asarray(jnp.mean(counts, axis=0))
    keep = np.where(mean_count >= min_mean)[0]
    if keep.size < 50:  # degenerate ultra-sparse input: take most-expressed
        keep = np.argsort(-mean_count)[: min(counts.shape[1], _MAX_RATIO_GENES)]
    elif keep.size > _MAX_RATIO_GENES:
        keep = keep[np.argsort(-mean_count[keep])[:_MAX_RATIO_GENES]]
    keep = np.sort(keep)

    # Ring order: sort by libsize, then interleave small/large so every pool
    # mixes depths (scran orders cells this way to balance pool composition).
    order = np.asarray(jnp.argsort(lib))
    half = (n + 1) // 2
    ring = np.empty(n, dtype=np.int64)
    ring[0::2] = order[:half]
    ring[1::2] = order[half:][::-1]

    scaled = counts[jnp.asarray(ring)][:, jnp.asarray(keep)] / lib[jnp.asarray(ring), None]
    theta = _deconv_theta(scaled, sizes)
    theta = jnp.maximum(theta, 1e-8)

    sf_ring = theta * lib[jnp.asarray(ring)]
    inv = np.empty(n, dtype=np.int64)
    inv[ring] = np.arange(n)
    sf = sf_ring[jnp.asarray(inv)]
    return sf / jnp.maximum(jnp.mean(sf), 1e-12)


@functools.partial(jax.jit, static_argnames=("sizes", "n_ratio_genes"))  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
def deconvolution_factors_jit(
    counts: jax.Array,
    sizes: tuple,
    n_ratio_genes: int = 512,
) -> jax.Array:
    """Fully-traceable deconvolution size factors (unit mean).

    Same estimator as `deconvolution_factors` but with every step expressed in
    jnp so the whole pass can sit inside a jitted / vmapped program — used by
    the null-simulation pipeline, where the reference re-runs
    shifted_log_transform(size_factors="deconvolution") inside every simulated
    replicate (reference R/consensusClust.R:779). Gene selection for the pool
    ratios is a fixed-width top-k by mean count instead of the host-side
    min-mean filter.
    """
    counts = jnp.asarray(counts, jnp.float32)
    n = counts.shape[0]
    lib = jnp.maximum(jnp.sum(counts, axis=1), 1e-12)

    order = jnp.argsort(lib)
    half = (n + 1) // 2
    ring = (
        jnp.zeros((n,), jnp.int32)
        .at[0::2].set(order[:half].astype(jnp.int32))
        .at[1::2].set(order[half:][::-1].astype(jnp.int32))
    )

    g = min(int(n_ratio_genes), counts.shape[1])
    _, keep = jax.lax.top_k(jnp.mean(counts, axis=0), g)
    scaled = counts[ring][:, keep] / lib[ring, None]
    theta = jnp.maximum(_deconv_theta(scaled, sizes), 1e-8)

    sf = jnp.zeros((n,), jnp.float32).at[ring].set(theta * lib[ring])
    return sf / jnp.maximum(jnp.mean(sf), 1e-12)


def default_pool_sizes(n: int) -> tuple:
    """Host-side choice of pool window sizes for n cells (static under jit)."""
    max_size = max(3, n // 2)
    sizes = tuple(s for s in _DEFAULT_POOL_SIZES if s <= max_size)
    if not sizes:
        sizes = tuple(sorted({3, min(5, max_size), max_size}))
    return sizes


def stabilize_size_factors(sf: jax.Array) -> jax.Array:
    """Reference's repair pass (R/consensusClust.R:276-285): divide by the
    geometric mean, then replace non-finite or non-positive entries by 0.001."""
    sf = jnp.asarray(sf, jnp.float32)
    safe = jnp.where(sf > 0, sf, jnp.nan)
    log_gm = jnp.nanmean(jnp.log(safe))
    log_gm = jnp.where(jnp.isfinite(log_gm), log_gm, 0.0)
    out = sf / jnp.exp(log_gm)
    bad = ~jnp.isfinite(out) | (out <= 0)
    return jnp.where(bad, 0.001, out)


def compute_size_factors(counts: jax.Array, spec: Union[str, np.ndarray]) -> jax.Array:
    """Dispatch on the reference's `sizeFactors` parameter (string or vector).

    The geometric-mean stabilisation pass applies only to the deconvolution
    branch, matching the reference where R/consensusClust.R:274-285 sits
    inside the sizeFactors=="deconvolution" arm; libsize and user-supplied
    vectors pass through untouched.
    """
    if isinstance(spec, str):
        if spec == "deconvolution":
            return stabilize_size_factors(deconvolution_factors(counts))
        if spec == "libsize":
            return libsize_factors(counts)
        raise ValueError(f"unknown size_factors spec {spec!r}")
    return jnp.asarray(spec, jnp.float32)
