"""Sparse (CSR) preprocessing path.

The reference operates on sparse ``dgCMatrix`` counts end to end
(reference R/consensusClust.R:274-299 via Matrix/sparseMatrixStats, SURVEY
§2.2 "Matrix / sparseMatrixStats" row); densifying a full n_cells x n_genes
count matrix is untenable at the BASELINE scale configs (1M cells x 20k genes
= 80 GB float32). This module keeps scipy CSR counts sparse through the two
full-gene-set passes — size factors and deviance HVG selection — so the only
dense materialisation is the post-HVG submatrix (n_cells x n_var_features,
e.g. 1M x 2000 = 8 GB worst case, streamable).

Design: these are O(nnz) host passes over ingestion-scale data, exactly where
the reference's C++ sparse machinery lives; the device (MXU) path starts at
the dense HVG submatrix, which is where the FLOPs are.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import numpy as np
import scipy.sparse as sp
from scipy.special import xlogy

from consensusclustr_tpu.prep.sizefactors import (
    _MAX_RATIO_GENES,
    _deconv_theta,
    default_pool_sizes,
    stabilize_size_factors,
)

# Bound the dense ratio-gene submatrix the deconvolution solve holds
# (n_cells x n_ratio_genes float32).
_RATIO_SUBMATRIX_BYTES = 2e9


def is_sparse(x) -> bool:
    return sp.issparse(x)


def to_csr(x) -> sp.csr_matrix:
    """scipy CSR from scipy sparse or io.CountMatrix."""
    if sp.issparse(x):
        return x.tocsr()
    if hasattr(x, "indptr") and hasattr(x, "col") and hasattr(x, "val"):
        return sp.csr_matrix(
            (x.val, x.col, x.indptr.astype(np.int64)), shape=x.shape
        )
    raise TypeError(f"not a sparse container: {type(x)!r}")


def _cell_totals(csr: sp.csr_matrix) -> np.ndarray:
    return np.asarray(csr.sum(axis=1), np.float64).ravel()


def sparse_binomial_deviance(csr: sp.csr_matrix) -> np.ndarray:
    """Per-gene binomial deviance vs a constant-rate null, O(nnz).

    Matches prep.hvg.binomial_deviance on the densified matrix. Zero entries
    contribute ``-n_j * log(1 - pi_g)`` in closed form, so only nonzeros are
    touched: for entry (j, g) with count y,

      term = xlogy(y, y) - xlogy(y, n_j pi_g)
           + xlogy(n_j - y, n_j - y) - xlogy(n_j - y, n_j (1 - pi_g))
           + n_j log(1 - pi_g)                      (undo the zero-form term)

      dev_g = 2 * (sum_nz term  -  log(1 - pi_g) * sum_j n_j)
    """
    csc = csr.tocsc()
    n, g = csc.shape
    n_j = _cell_totals(csr)                      # [n]
    total = max(float(n_j.sum()), 1e-12)
    y_g = np.asarray(csc.sum(axis=0), np.float64).ravel()
    pi_g = np.clip(y_g / total, 1e-12, 1.0 - 1e-12)
    log1m = np.log1p(-pi_g)                      # log(1 - pi_g), [g]

    y = csc.data.astype(np.float64)
    rows = csc.indices                           # cell index per nonzero
    gene_of = np.repeat(np.arange(g), np.diff(csc.indptr))
    nj = n_j[rows]
    mu = nj * pi_g[gene_of]
    ny = nj - y
    term = (
        xlogy(y, y) - xlogy(y, mu)
        + xlogy(ny, ny) - xlogy(ny, nj * (1.0 - pi_g[gene_of]))
        + nj * log1m[gene_of]
    )
    dev = np.zeros(g, np.float64)
    np.add.at(dev, gene_of, term)
    return (2.0 * (dev - log1m * total)).astype(np.float32)


def sparse_poisson_deviance(csr: sp.csr_matrix) -> np.ndarray:
    """Per-gene Poisson deviance vs a constant-rate null, O(nnz).

    The linear terms cancel in aggregate (sum_j (y - mu) = 0 per gene under
    the pooled-rate null), leaving only the nonzero xlogy sum.
    """
    csc = csr.tocsc()
    n, g = csc.shape
    n_j = _cell_totals(csr)
    total = max(float(n_j.sum()), 1e-12)
    y_g = np.asarray(csc.sum(axis=0), np.float64).ravel()
    pi_g = y_g / total

    y = csc.data.astype(np.float64)
    rows = csc.indices
    gene_of = np.repeat(np.arange(g), np.diff(csc.indptr))
    mu = np.maximum(n_j[rows] * pi_g[gene_of], 1e-12)
    term = xlogy(y, y / mu)
    dev = np.zeros(g, np.float64)
    np.add.at(dev, gene_of, term)
    return (2.0 * dev).astype(np.float32)


def sparse_select_hvgs(
    csr: sp.csr_matrix, n_var_features: int = 2000, family: str = "binomial"
) -> np.ndarray:
    """Boolean mask of the top-`n_var_features` genes by deviance
    (reference R/consensusClust.R:295-299), computed without densifying."""
    if family not in ("binomial", "poisson"):
        raise ValueError(f"family must be 'binomial' or 'poisson'; got {family!r}")
    dev = (
        sparse_binomial_deviance(csr)
        if family == "binomial"
        else sparse_poisson_deviance(csr)
    )
    g = dev.shape[0]
    k = min(int(n_var_features), g)
    idx = np.argpartition(-dev, k - 1)[:k] if k < g else np.arange(g)
    mask = np.zeros(g, bool)
    mask[idx] = True
    return mask


def sparse_libsize_factors(csr: sp.csr_matrix) -> np.ndarray:
    """Library-size factors at unit mean; all-zero cells get 1
    (prep.sizefactors.libsize_factors contract)."""
    lib = _cell_totals(csr)
    pos = lib > 0
    mean_pos = lib[pos].mean() if pos.any() else 1.0
    sf = lib / max(mean_pos, 1e-12)
    sf[~pos] = 1.0
    return sf.astype(np.float32)


def sparse_deconvolution_factors(
    csr: sp.csr_matrix,
    pool_sizes: Optional[Sequence[int]] = None,
    min_mean: float = 0.1,
) -> np.ndarray:
    """Pooled deconvolution size factors from CSR counts.

    Same estimator as prep.sizefactors.deconvolution_factors: the only dense
    materialisation is the [n, n_ratio_genes] submatrix of well-expressed
    genes used for the pool median ratios, capped to _RATIO_SUBMATRIX_BYTES.
    """
    import jax.numpy as jnp

    n, g = csr.shape
    if n < 8:
        return sparse_libsize_factors(csr)
    if pool_sizes is not None:
        bad = [s for s in pool_sizes if not (1 < int(s) <= n)]
        if bad:
            raise ValueError(f"pool_sizes must be in (1, n_cells={n}]; got {bad}")

    lib = np.maximum(_cell_totals(csr), 1e-12)
    sizes = tuple(
        int(s) for s in (pool_sizes if pool_sizes is not None else default_pool_sizes(n))
    )

    cap = int(min(_MAX_RATIO_GENES, max(64, _RATIO_SUBMATRIX_BYTES // (4 * n))))
    mean_count = np.asarray(csr.sum(axis=0), np.float64).ravel() / n
    keep = np.where(mean_count >= min_mean)[0]
    if keep.size < 50:
        keep = np.argsort(-mean_count)[: min(g, cap)]
    elif keep.size > cap:
        keep = keep[np.argsort(-mean_count[keep])[:cap]]
    keep = np.sort(keep)

    # Ring order: interleave small/large library sizes (scran's balancing).
    # Stable sort to match the dense path's jnp.argsort tie-breaking exactly.
    order = np.argsort(lib.astype(np.float32), kind="stable")
    half = (n + 1) // 2
    ring = np.empty(n, np.int64)
    ring[0::2] = order[:half]
    ring[1::2] = order[half:][::-1]

    sub = np.asarray(csr[:, keep][ring].todense(), np.float32)
    scaled = sub / lib[ring, None].astype(np.float32)
    theta = np.asarray(_deconv_theta(jnp.asarray(scaled), sizes))
    theta = np.maximum(theta, 1e-8)

    sf = np.empty(n, np.float32)
    sf[ring] = theta * lib[ring]
    return sf / max(float(sf.mean()), 1e-12)


def compute_size_factors_sparse(
    csr: sp.csr_matrix, spec: Union[str, np.ndarray]
) -> np.ndarray:
    """Sparse mirror of prep.sizefactors.compute_size_factors: the
    geometric-mean stabilisation (reference :276-285) applies only to the
    deconvolution branch."""
    if isinstance(spec, str):
        if spec == "deconvolution":
            return np.asarray(
                stabilize_size_factors(sparse_deconvolution_factors(csr)),
                np.float32,
            )
        if spec == "libsize":
            return sparse_libsize_factors(csr)
        raise ValueError(f"unknown size_factors spec {spec!r}")
    return np.asarray(spec, np.float32)


def sparse_shifted_log(
    csr: sp.csr_matrix, size_factors: np.ndarray, pseudo_count: float = 1.0
) -> sp.csr_matrix:
    """Shifted-log transform log1p(x / (sf * pc)) on CSR counts.

    log1p(0) == 0, so the transform preserves the sparsity pattern exactly —
    the sparse analog of prep.transform.shifted_log.
    """
    sf = np.asarray(size_factors, np.float32)
    rows = np.repeat(np.arange(csr.shape[0]), np.diff(csr.indptr))
    out = csr.copy()
    out.data = np.log1p(csr.data / (sf[rows] * pseudo_count)).astype(np.float32)
    return out
