from consensusclustr_tpu.prep.sizefactors import (
    libsize_factors,
    deconvolution_factors,
    stabilize_size_factors,
    compute_size_factors,
)
from consensusclustr_tpu.prep.transform import shifted_log, normalize_counts
from consensusclustr_tpu.prep.hvg import binomial_deviance, poisson_deviance, select_hvgs
from consensusclustr_tpu.prep.regress import regress_features
