"""Highly-variable gene selection by deviance.

Equivalent of scry::devianceFeatureSelection as called at
reference R/consensusClust.R:295-299: rank genes by deviance from a
constant-rate null and keep the top `n_var_features` (default 2000, top-k by
partial sort in the reference; exact top-k here).

Closed-form per-gene binomial/Poisson deviance is one xlogy reduction pass over
the count matrix — an ideal MXU/VPU workload (SURVEY §2.2).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import xlogy


@jax.jit  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
def binomial_deviance(counts: jax.Array) -> jax.Array:
    """Per-gene binomial deviance vs. a constant-rate null (scry default).

    counts: [n_cells, n_genes]. For gene g with cell totals n_j and pooled
    rate pi_g = sum_j y_gj / sum_j n_j:
      d_g = 2 sum_j [ xlogy(y, y/(n pi)) + xlogy(n-y, (n-y)/(n (1-pi))) ]
    """
    y = jnp.asarray(counts, jnp.float32)
    n_j = jnp.sum(y, axis=1, keepdims=True)                      # [n, 1]
    total = jnp.maximum(jnp.sum(n_j), 1e-12)
    pi_g = jnp.sum(y, axis=0, keepdims=True) / total             # [1, g]
    pi_g = jnp.clip(pi_g, 1e-12, 1.0 - 1e-12)
    mu = n_j * pi_g
    term1 = xlogy(y, y) - xlogy(y, mu)
    ny = n_j - y
    term2 = xlogy(ny, ny) - xlogy(ny, n_j * (1.0 - pi_g))
    return 2.0 * jnp.sum(term1 + term2, axis=0)


@jax.jit  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
def poisson_deviance(counts: jax.Array) -> jax.Array:
    """Per-gene Poisson deviance vs. a constant-rate null."""
    y = jnp.asarray(counts, jnp.float32)
    n_j = jnp.sum(y, axis=1, keepdims=True)
    total = jnp.maximum(jnp.sum(n_j), 1e-12)
    pi_g = jnp.sum(y, axis=0, keepdims=True) / total
    mu = jnp.maximum(n_j * pi_g, 1e-12)
    return 2.0 * jnp.sum(xlogy(y, y / mu) - (y - mu), axis=0)


def select_hvgs(counts: jax.Array, n_var_features: int = 2000, family: str = "binomial") -> jax.Array:
    """Boolean mask of the top-`n_var_features` genes by deviance
    (reference R/consensusClust.R:295-299)."""
    if family not in ("binomial", "poisson"):
        raise ValueError(f"family must be 'binomial' or 'poisson'; got {family!r}")
    dev = binomial_deviance(counts) if family == "binomial" else poisson_deviance(counts)
    g = dev.shape[0]
    k = min(int(n_var_features), g)
    _, idx = jax.lax.top_k(dev, k)
    mask = jnp.zeros((g,), bool).at[idx].set(True)
    return mask
