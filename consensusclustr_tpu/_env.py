"""Jax-free environment checks shared by the package root and utils.backend.

A ``JAX_PLATFORMS=cpu`` process must never dial the accelerator plugin, but
the plugin's sitecustomize re-pins jax's config at interpreter start — so the
pin has to be re-asserted the moment the package is imported AND whenever the
backend resolver runs. Those two call sites used to carry separate copies of
the check (ADVICE r5 #3); this module is the single shared form. It imports
only ``os`` at module load (keeping ``import consensusclustr_tpu`` cheap for
non-pinned processes) and touches jax exclusively under an active cpu pin,
where doing so is hang-free by construction: the cpu branch never probes a
backend.
"""

from __future__ import annotations

import os


def cpu_env_pinned() -> bool:
    """True when $JAX_PLATFORMS pins plain "cpu" (the only hang-free pin)."""
    return os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu"


def repin_cpu_from_env() -> None:
    """If $JAX_PLATFORMS pins plain "cpu", force jax's config to match.

    The platform plugin's sitecustomize sets jax_platforms="axon,cpu" at
    interpreter start, overriding the env — so without this, a cpu-pinned
    process's first device op still dials the accelerator plugin (which
    blocks forever on a wedged link). Called at package import and from
    utils.backend.default_backend's cpu branch.
    """
    if cpu_env_pinned():
        import jax

        if jax.config.jax_platforms != "cpu":
            jax.config.update("jax_platforms", "cpu")
