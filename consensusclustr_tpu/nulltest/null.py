"""Batched null-statistic generation.

Equivalent of the reference's ``generateNullStatistic``
(reference R/consensusClust.R:759-814): simulate a null count matrix from the
fitted NB-copula model, normalise it with deconvolution size factors, optionally
regress covariates, PCA to the real data's pc_num, cluster over the hardcoded
null resolution sweep (min_size=5, :803-804), and return the mean
approx-silhouette of the chosen assignment (0 for a single cluster or a failed
PCA, :806-813).

Where the reference runs 20-60 of these pipelines as separate R worker
processes (bplapply at :933-963), here the whole simulate -> normalise -> PCA
-> cluster -> silhouette chain is ONE jitted program vmapped over a chunk of
replicates (SURVEY §2.4 null-simulation row).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from consensusclustr_tpu.config import NULL_SIM_MIN_SIZE, NULL_SIM_RES_RANGE
from consensusclustr_tpu.cluster.engine import (
    cluster_grid,
    ties_last_argmax as _ties_last_argmax,
)
from consensusclustr_tpu.cluster.metrics import mean_silhouette_score
from consensusclustr_tpu.linalg.pca import truncated_pca
from consensusclustr_tpu.nulltest.copula import CopulaModel, simulate_counts
from consensusclustr_tpu.prep.regress import lm_residuals
from consensusclustr_tpu.prep.sizefactors import (
    deconvolution_factors_jit,
    default_pool_sizes,
    stabilize_size_factors,
)
from consensusclustr_tpu.obs import maybe_span, metrics_of
from consensusclustr_tpu.parallel.pipelined import ChunkPipeline, pipeline_depth
from consensusclustr_tpu.resilience.inject import NULL_CHUNK_SITE
from consensusclustr_tpu.resilience.retry import resolve_retry_policy
from consensusclustr_tpu.prep.transform import shifted_log
from consensusclustr_tpu.utils.compile_cache import counting_jit
from consensusclustr_tpu.utils.rng import sim_key


@counting_jit(
    static_argnames=(
        "n_cells", "pc_num", "k_list", "pool_sizes", "max_clusters", "has_cov",
        "cluster_fun", "compute_dtype",
    ),
)
def _null_stat_batch(
    keys: jax.Array,                 # [chunk, 2] split per sim
    model: CopulaModel,
    covariates: jax.Array,           # [n_cells, n_cov] or dummy [n_cells, 1]
    res_list: jax.Array,             # [R]
    n_cells: int,
    pc_num: int,
    k_list: Tuple[int, ...],
    pool_sizes: Tuple[int, ...],
    max_clusters: int,
    has_cov: bool,
    cluster_fun: str = "leiden",
    compute_dtype: str = "float32",
) -> jax.Array:
    def one(key):
        k_sim, k_pca, k_clu = jax.random.split(key, 3)
        counts = simulate_counts(k_sim, model, n_cells)
        sf = stabilize_size_factors(deconvolution_factors_jit(counts, pool_sizes))
        y = shifted_log(counts, sf)
        if has_cov:
            y = lm_residuals(y, covariates)
        res = truncated_pca(y, pc_num, center=True, scale=True, key=k_pca)
        pca = res.scores
        # PCA failure -> 0 statistic (reference :788-798): scrub non-finite
        # scores so the clustering path stays NaN-free, flag for the fallback.
        pca_ok = jnp.all(jnp.isfinite(pca))
        pca = jnp.where(jnp.isfinite(pca), pca, 0.0)
        grid = cluster_grid(
            k_clu, pca, res_list, k_list,
            jnp.float32(NULL_SIM_MIN_SIZE), max_clusters=max_clusters,
            cluster_fun=cluster_fun, compute_dtype=compute_dtype,
        )
        best = _ties_last_argmax(grid.scores)
        labels = grid.labels[best]
        n_c = grid.n_clusters[best]
        sil = mean_silhouette_score(pca, labels, max_clusters)
        stat = jnp.where((n_c <= 1) | ~pca_ok, 0.0, sil)
        return jnp.where(jnp.isfinite(stat), stat, 0.0)

    return jax.vmap(one)(keys)


def generate_null_statistics(
    key: jax.Array,
    model: CopulaModel,
    n_cells: int,
    pc_num: int,
    n_sims: int = 20,
    k_num=(10, 15, 20),
    covariates: Optional[np.ndarray] = None,
    max_clusters: int = 64,
    round_id: int = 0,
    chunk: Optional[int] = None,
    cluster_fun: str = "leiden",
    res_range=None,
    compute_dtype: str = "float32",
    log=None,
    pipeline_depth_override: Optional[int] = None,
) -> np.ndarray:
    """n_sims null silhouettes, chunk-vmapped on device.

    `round_id` keys the adaptive rounds (the reference bumps RNGseed+1 for the
    extra 20-sim rounds, :944/:956 — here it folds into the PRNG tree).

    `res_range=None` keeps the reference's hardcoded null sweep
    (R/consensusClust.R:803); a sequence overrides it (the knob testSplits'
    shadowed resRange argument was presumably meant to be, :892).

    `chunk=None` auto-sizes the vmapped sim batch: 4 for small problems, 1
    above 16384 cells — a large-n sim is bandwidth-bound so vmap adds no
    throughput, but it multiplies the XLA program (measured: the 50k-cell
    chunk-4 compile ran 6m34s on CPU, which on the tunneled TPU would blow
    the ~2-min per-call watchdog that kills the worker; docs/perf.md).
    Keys are per-sim, but individual draws are NOT bit-stable across chunk
    sizes: vmap changes reduction lowering, float rounding shifts, and the
    discrete clustering inside a draw can flip — only the null DISTRIBUTION
    is chunk-independent. Reproducibility holds for a fixed (key, n, chunk
    policy), which auto-chunk keeps deterministic in n.
    """
    if chunk is None:
        chunk = 1 if n_cells > 16384 else 4
    res_list = jnp.asarray(
        NULL_SIM_RES_RANGE if res_range is None else list(res_range), jnp.float32
    )
    k_list = tuple(int(k) for k in k_num)
    pool_sizes = default_pool_sizes(n_cells)
    has_cov = covariates is not None
    cov = (
        jnp.asarray(covariates, jnp.float32)
        if has_cov
        else jnp.zeros((n_cells, 1), jnp.float32)
    )
    keys = jax.vmap(lambda s: sim_key(key, s, round_id))(jnp.arange(n_sims, dtype=jnp.int32))
    depth = pipeline_depth(pipeline_depth_override)
    mets = metrics_of(log)
    # null-chunk dispatch is a fault site (ISSUE 10): transient chunk
    # failures re-dispatch under the bounded retry policy; same keys, same
    # chunk shape -> bit-identical stats on the retried attempt
    pipe = ChunkPipeline(
        depth, metrics=mets,
        site=NULL_CHUNK_SITE, retry=resolve_retry_policy(), log=log,
    )
    out = []

    def _consume(ent):
        s, e = ent.meta
        # per-null-dataset span: at big n each chunk is minutes-to-hours, so
        # the RunRecord localizes which simulation round ate the wall clock.
        # Under pipelining the span covers the blocking fetch (where the wall
        # time goes), not the async dispatch; overlap_seconds records how
        # long the chunk ran on device while the host was elsewhere.
        with maybe_span(
            log, "null_sim_chunk", round_id=round_id, start=s, end=e
        ) as sp:
            stats = ent.fetch()
            sp.set(overlap_seconds=round(ent.overlap_seconds, 4))
            sp.value = stats
        out.append(stats)
        mets.counter("null_sims_completed").inc(e - s)
        if log:
            # hours-scale at big n: observability for long runs
            log.event("null_sims", done=e, total=n_sims, round_id=round_id)

    with maybe_span(
        log, "null_sims", round_id=round_id, n_sims=n_sims, chunk=chunk,
        pipeline_depth=depth,
    ) as nsp:
        try:
            for s in range(0, n_sims, chunk):
                e = min(s + chunk, n_sims)
                for ent in pipe.ready_for_dispatch():
                    _consume(ent)
                pipe.dispatch(
                    s,
                    lambda s=s, e=e: _null_stat_batch(
                        keys[s:e], model, cov, res_list,
                        int(n_cells), int(pc_num), k_list, pool_sizes,
                        int(max_clusters), has_cov, cluster_fun, compute_dtype,
                    ),
                    meta=(s, e),
                )
            for ent in pipe.drain():
                _consume(ent)
        except BaseException:
            pipe.abort()  # surface the original exception, not an async leak
            raise
        nsp.set(
            overlap_seconds=round(pipe.overlap_seconds, 4),
            max_inflight=pipe.max_inflight,
        )
    return np.concatenate(out)
