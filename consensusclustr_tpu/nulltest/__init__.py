from consensusclustr_tpu.nulltest.nb import fit_nb, nb_cdf, nb_quantile
from consensusclustr_tpu.nulltest.copula import (
    CopulaModel,
    fit_nb_copula,
    simulate_counts,
)
from consensusclustr_tpu.nulltest.null import generate_null_statistics
from consensusclustr_tpu.nulltest.splits import test_splits, null_p_value
