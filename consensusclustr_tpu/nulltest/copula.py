"""Gaussian copula over NB marginals: fit + simulation.

TPU-native equivalent of scDesign3's ``fit_copula(gaussian)`` /
``extract_para`` / ``simu_new`` slice used by the reference's null model
(reference R/consensusClust.R:916-921, 763-778): the gene-gene dependence of
the real counts is captured as a Gaussian copula correlation matrix, and null
datasets are drawn by sampling correlated normals and pushing them through
the per-gene NB quantile function.

Everything is one fixed-shape device program: the distributional transform is
elementwise, the correlation matrix is one [G, G] matmul, sampling is a
Cholesky matmul + quantile bisection — all vmappable over the >= 20 null
replicates (SURVEY §2.2 scDesign3 row).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.scipy.special import ndtri
from jax.scipy.stats import norm as jnorm

from consensusclustr_tpu.nulltest.nb import fit_nb, nb_cdf, nb_quantile

_U_EPS = 1e-6


class CopulaModel(NamedTuple):
    """NB marginals + Gaussian copula factor (the `extract_para` analog)."""

    mu: jax.Array     # [G] NB means
    theta: jax.Array  # [G] NB dispersions
    chol: jax.Array   # [G, G] lower Cholesky factor of the copula correlation


@functools.partial(jax.jit, static_argnames=())  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
def _copula_corr(key: jax.Array, counts: jax.Array, mu: jax.Array, theta: jax.Array,
                 shrink: jax.Array) -> jax.Array:
    """Copula correlation via the randomized distributional transform.

    For discrete marginals the probability integral transform is randomized:
    u = F(x-1) + V * (F(x) - F(x-1)), V ~ U(0,1) — without this the normal
    scores of ties collapse and correlations are biased (scDesign3 does the
    same). Shrinkage toward I keeps the matrix SPD in float32.
    """
    x = jnp.asarray(counts, jnp.float32)
    n = x.shape[0]
    hi = nb_cdf(x, mu[None, :], theta[None, :])
    lo = nb_cdf(x - 1.0, mu[None, :], theta[None, :])
    # float32-pinned draw: the default dtype widens to float64 on an
    # x64-enabled host, changing the drawn bits (parity_audit x64:x32)
    v = jax.random.uniform(key, x.shape, jnp.float32)
    u = jnp.clip(lo + v * (hi - lo), _U_EPS, 1.0 - _U_EPS)
    z = ndtri(u)
    z = (z - jnp.mean(z, axis=0)) / jnp.maximum(jnp.std(z, axis=0), 1e-6)
    corr = (z.T @ z) / n
    g = corr.shape[0]
    eye = jnp.eye(g, dtype=corr.dtype)
    corr = (1.0 - shrink) * corr + shrink * eye
    return 0.5 * (corr + corr.T)


def fit_nb_copula(
    key: jax.Array,
    counts: jax.Array,
    shrink: float = 0.05,
    n_iters: int = 30,
) -> CopulaModel:
    """Fit the full null generative model to real counts [n_cells, n_genes].

    Mirrors the reference's construct_data -> fit_marginal -> fit_copula ->
    extract_para chain (R/consensusClust.R:909-921) as two device passes:
    vmapped NB MLE, then one correlation matmul + Cholesky.
    """
    counts = jnp.asarray(counts, jnp.float32)
    mu, theta = fit_nb(counts, n_iters=n_iters)
    corr = _copula_corr(key, counts, mu, theta, jnp.float32(shrink))
    chol = jnp.linalg.cholesky(corr)
    # float32 SPD safety net: if Cholesky failed, retreat to independence.
    ok = jnp.all(jnp.isfinite(chol))
    chol = jnp.where(ok, chol, jnp.eye(corr.shape[0], dtype=corr.dtype))
    return CopulaModel(mu=mu, theta=theta, chol=chol)


@functools.partial(jax.jit, static_argnames=("n_cells",))  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
def simulate_counts(key: jax.Array, model: CopulaModel, n_cells: int) -> jax.Array:
    """Draw one null count matrix [n_cells, G] (the `simu_new` analog,
    reference R/consensusClust.R:763-778): correlated normals -> uniforms ->
    NB quantiles."""
    g = model.mu.shape[0]
    eps = jax.random.normal(key, (n_cells, g), jnp.float32)
    z = eps @ model.chol.T
    u = jnp.clip(jnorm.cdf(z), _U_EPS, 1.0 - _U_EPS)
    return nb_quantile(u, model.mu[None, :], model.theta[None, :])
