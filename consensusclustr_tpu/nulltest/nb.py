"""Per-gene negative-binomial statistics: MLE, CDF, quantile.

TPU-native equivalent of the scDesign3 marginal machinery the reference's
null model delegates to (reference R/consensusClust.R:913-915:
``fit_marginal(mu_formula="1", sigma_formula="1", family="nb")``, and the
NB quantile inversion inside ``simu_new`` at :763-778): every gene g gets an
intercept-only NB(mu_g, theta_g) fit. Where scDesign3 runs one mgcv/gamlss
fit per gene in R, this is a single vmapped fixed-iteration Newton solve over
all genes at once (SURVEY §2.2 scDesign3 row) — gradients and curvature come
from autodiff of the NB log-likelihood, so the update is exactly Newton on
log(theta) with no hand-derived digamma algebra to get wrong.

Numerical stance (SURVEY §7.3 hard part 5): theta is solved in log space with
clamped steps; sparse / low-variance genes fall back to the Poisson limit
(theta -> THETA_MAX) instead of diverging.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.scipy.special import betainc, gammaln

THETA_MIN = 1e-3
THETA_MAX = 1e6


def _nb_loglik(eta: jax.Array, x: jax.Array, mu: jax.Array) -> jax.Array:
    """Mean NB log-likelihood of one gene's counts x [cells] at theta=exp(eta)."""
    th = jnp.exp(eta)
    return jnp.mean(
        gammaln(x + th)
        - gammaln(th)
        - gammaln(x + 1.0)
        + th * (eta - jnp.log(th + mu))
        + x * (jnp.log(mu) - jnp.log(th + mu))
    )


@functools.partial(jax.jit, static_argnames=("n_iters",))  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
def fit_nb(counts: jax.Array, n_iters: int = 30):
    """Intercept-only NB MLE per gene.

    counts: [n_cells, n_genes]. Returns (mu [G], theta [G]) float32.
    mu is the exact MLE (the sample mean); theta is a Newton solve on
    eta = log(theta), initialised at the method-of-moments estimate.
    """
    x = jnp.asarray(counts, jnp.float32)
    mu = jnp.maximum(jnp.mean(x, axis=0), 1e-8)
    # The intercept-only model is the degenerate regression case: a constant
    # per-cell mean. Under mu = sample mean, fit_theta_given_mu's moments
    # init and Poisson-limit fallback reduce exactly to the var-vs-mean ones.
    theta = fit_theta_given_mu(x, jnp.broadcast_to(mu[None, :], x.shape), n_iters=n_iters)
    return mu, theta


@functools.partial(jax.jit, static_argnames=("n_iters",))  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
def fit_theta_given_mu(counts: jax.Array, mu: jax.Array, n_iters: int = 30) -> jax.Array:
    """Per-gene NB theta MLE with a fixed per-cell mean matrix.

    counts, mu: [n_cells, n_genes]. Returns theta [G] float32.

    The regression case of `fit_nb`: mu varies per cell (fitted by a GLM,
    reference R/consensusClust.R:846-856) instead of being the intercept-only
    sample mean. Same clamped Newton on eta = log(theta) — `_nb_loglik`
    broadcasts a per-cell mu vector unchanged — initialised at the
    method-of-moments estimate from the excess variance over the fitted means.
    Genes with no overdispersion signal fall back to the Poisson limit.
    """
    x = jnp.asarray(counts, jnp.float32)
    mu = jnp.maximum(jnp.asarray(mu, jnp.float32), 1e-8)
    excess = jnp.mean((x - mu) ** 2 - mu, axis=0)
    mu2 = jnp.mean(mu * mu, axis=0)
    eta0 = jnp.log(jnp.clip(mu2 / jnp.maximum(excess, 1e-8), THETA_MIN, THETA_MAX))

    grad = jax.grad(_nb_loglik)
    hess = jax.grad(grad)

    def one_gene(eta, xg, mug):
        def body(_, e):
            g = grad(e, xg, mug)
            h = hess(e, xg, mug)
            step = jnp.where(h < -1e-8, -g / h, jnp.sign(g) * 0.5)
            step = jnp.clip(step, -2.0, 2.0)
            return jnp.clip(e + step, jnp.log(THETA_MIN), jnp.log(THETA_MAX))

        return jax.lax.fori_loop(0, n_iters, body, eta)

    eta = jax.vmap(one_gene, in_axes=(0, 1, 1))(eta0, x, mu)
    eta = jnp.where(excess <= 0.0, jnp.log(THETA_MAX), eta)
    return jnp.exp(eta)


def nb_cdf(k: jax.Array, mu: jax.Array, theta: jax.Array) -> jax.Array:
    """P(X <= k) for NB(mu, theta), k >= 0 integer-valued (float array ok).

    Uses the regularized incomplete beta identity
    cdf(k) = I_p(theta, k+1) with p = theta / (theta + mu).
    """
    p = theta / (theta + mu)
    c = betainc(theta, jnp.maximum(k, 0.0) + 1.0, p)
    return jnp.where(k < 0, 0.0, c)


@functools.partial(jax.jit, static_argnames=("n_bits",))  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
def nb_quantile(u: jax.Array, mu: jax.Array, theta: jax.Array, n_bits: int = 26) -> jax.Array:
    """Smallest integer k with cdf(k) >= u, by fixed-iteration bisection.

    All args broadcast. The search window is mu + 12 sd + 32, which covers
    u <= 1 - 1e-7 for any NB; beyond-window quantiles clamp to the window top.
    2^26 bisection steps cover windows up to ~6.7e7 counts.
    """
    u = jnp.asarray(u, jnp.float32)
    sd = jnp.sqrt(mu + mu * mu / theta)
    hi0 = jnp.ceil(mu + 12.0 * sd + 32.0)
    lo = jnp.zeros_like(u * hi0)
    hi = jnp.broadcast_to(hi0, lo.shape)

    def body(_, lohi):
        lo, hi = lohi
        mid = jnp.floor((lo + hi) * 0.5)
        ge = nb_cdf(mid, mu, theta) >= u
        return jnp.where(ge, lo, mid + 1.0), jnp.where(ge, mid, hi)

    lo, hi = jax.lax.fori_loop(0, n_bits, body, (lo, hi))
    return hi
