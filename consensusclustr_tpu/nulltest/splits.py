"""Statistical testing of clusterings against the NB-copula null.

Equivalent of the reference's ``testSplits``
(reference R/consensusClust.R:891-1037): fit the null generative model to the
(HVG) counts, simulate >= 20 null datasets, cluster each, fit a normal to the
null silhouettes and compute p = 1 - Phi(silhouette_real); clusterings (or
individual dendrogram splits) whose silhouette is not significantly better
than the null are rejected.

Division of labor (SURVEY §7.1): all statistics run on device in batched form
(`fit_nb_copula`, `generate_null_statistics`); this module is the irregular
host control — the adaptive 20/20/20 simulation rounds (:933-964) and the
`test_splits_seperately` dendrogram walk (:894-905, 966-1036).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from consensusclustr_tpu.config import TEST_SPLITS_RES_RANGE
from consensusclustr_tpu.cluster.metrics import mean_silhouette_score
from consensusclustr_tpu.hierarchy.dendro import Dendrogram, determine_hierarchy
from consensusclustr_tpu.linalg.distance import euclidean_distance_matrix as _euclidean
from consensusclustr_tpu.nulltest.copula import fit_nb_copula
from consensusclustr_tpu.nulltest.null import generate_null_statistics
from consensusclustr_tpu.obs import maybe_span
from consensusclustr_tpu.utils.log import LevelLog
from consensusclustr_tpu.utils.rng import cluster_key, root_key


def null_p_value(silhouette: float, null_stats: np.ndarray) -> float:
    """Normal-MLE fit to the null silhouettes + upper-tail p-value
    (reference :939-940: MASS::fitdistr 'normal', p = 1 - pnorm)."""
    m = float(np.mean(null_stats))
    sd = float(np.std(null_stats))  # MLE (ddof=0), matching fitdistr
    if sd < 1e-12:
        return 0.0 if silhouette > m else 1.0
    z = (silhouette - m) / sd
    return 0.5 * math.erfc(z / math.sqrt(2.0))


def _codes(labels: np.ndarray) -> np.ndarray:
    uniq, codes = np.unique(np.asarray(labels, dtype=str), return_inverse=True)
    return codes.astype(np.int32)


def labelled_silhouette(
    pca: np.ndarray, labels: np.ndarray, max_clusters: int
) -> float:
    """Mean approx-silhouette of string/object labels on a PCA matrix.

    Public helper shared by the dendrogram walk here and the significance
    gate in api.py (reference :518's approxSilhouette-on-labels pattern)."""
    codes = _codes(labels)
    mc = max(int(max_clusters), int(codes.max()) + 1)
    return float(
        mean_silhouette_score(jnp.asarray(pca, jnp.float32), jnp.asarray(codes), mc)
    )


_silhouette = labelled_silhouette  # internal callers / backward compat


def _clustering_rejected(
    key: jax.Array,
    counts: np.ndarray,
    silhouette: float,
    pc_num: int,
    *,
    alpha: float,
    k_num,
    covariates,
    n_sims: int,
    max_clusters: int,
    log: Optional[LevelLog],
    cluster_fun: str = "leiden",
    res_range=None,
    compute_dtype: str = "float32",
) -> tuple:
    """One full adaptive null test.

    Returns (rejected, null_stats): rejected == True means the clustering is
    not significant; null_stats is returned so callers can re-test merged
    variants against the SAME null fit, as the reference's failed-split loop
    does (:998 computes new p-values from the existing `fit`)."""
    with maybe_span(log, "null_test", n_cells=counts.shape[0]):
        n_cells = counts.shape[0]
        model = fit_nb_copula(cluster_key(key, "copula_fit"), jnp.asarray(counts, jnp.float32))

        stats = generate_null_statistics(
            key, model, n_cells, pc_num, n_sims=n_sims, k_num=k_num,
            covariates=covariates, max_clusters=max_clusters, round_id=0, log=log,
            cluster_fun=cluster_fun, res_range=res_range,
            compute_dtype=compute_dtype,
        )
        p = null_p_value(silhouette, stats)
        # Adaptive refinement near the boundary (reference :943-964): +20 sims if
        # p in [0.05, 0.1), then +20 more if still in [0.05, 0.075).
        if 0.05 <= p < 0.1:
            stats = np.concatenate([
                stats,
                generate_null_statistics(
                    key, model, n_cells, pc_num, n_sims=n_sims, k_num=k_num,
                    covariates=covariates, max_clusters=max_clusters, round_id=1, log=log,
                    cluster_fun=cluster_fun, res_range=res_range,
                    compute_dtype=compute_dtype,
                ),
            ])
            p = null_p_value(silhouette, stats)
        if 0.05 <= p < 0.075:
            stats = np.concatenate([
                stats,
                generate_null_statistics(
                    key, model, n_cells, pc_num, n_sims=n_sims, k_num=k_num,
                    covariates=covariates, max_clusters=max_clusters, round_id=2, log=log,
                    cluster_fun=cluster_fun, res_range=res_range,
                    compute_dtype=compute_dtype,
                ),
            ])
            p = null_p_value(silhouette, stats)
        if log:
            log.event(
                "null_test", silhouette=silhouette, p_value=p,
                null_mean=float(np.mean(stats)), null_sd=float(np.std(stats)),
                n_sims=len(stats),
            )
        return p >= alpha, stats


def test_splits(
    counts: np.ndarray,
    pca: np.ndarray,
    dend: Optional[Dendrogram],
    assignments: Sequence,
    *,
    pc_num: Optional[int] = None,
    k_num=(10, 15, 20),
    alpha: float = 0.05,
    silhouette_thresh: float = 0.45,
    covariates: Optional[np.ndarray] = None,
    n_sims: int = 20,
    seed: int = 123,
    key: Optional[jax.Array] = None,
    test_separately: bool = False,
    max_clusters: int = 64,
    log: Optional[LevelLog] = None,
    cluster_fun: str = "leiden",
    res_range=None,
    compute_dtype: str = "float32",
) -> np.ndarray:
    """Public API mirroring the reference export (NAMESPACE:6; :891).

    `cluster_fun` flows into the null-sim clusterings, as the reference's
    clusterFun does via testSplits' `...` (:536-537 -> :935 -> :803).
    `res_range` mirrors the reference signature's resRange (:892). In the
    reference that parameter is never consumed — generateNullStatistic
    hardcodes its own sweep (:803), and forwarding resRange through `...`
    would be a duplicate-argument error — so None (default) reproduces
    reference behavior; a sequence actually overrides the null-sim sweep, and
    the string "signature" resolves to the reference signature's documented
    default seq(0.1, 3.4, 0.15) (config.TEST_SPLITS_RES_RANGE) — both
    intent-fixes, docs/quirks.md.

    counts: [n_cells, n_hvg] raw counts (the reference builds an SCE of HVG
    counts, :526-531). pca: [n_cells, d]. assignments: per-cell labels.
    Returns the surviving assignments — unchanged, fully merged to "1"
    (test_separately=False, :967-970), or with individual failed splits
    collapsed (test_separately=True).
    """
    if isinstance(res_range, str):
        if res_range != "signature":
            raise ValueError(
                f"res_range must be None, 'signature' or a sequence; got {res_range!r}"
            )
        res_range = TEST_SPLITS_RES_RANGE
    assignments = np.asarray(assignments, dtype=object)
    n = len(assignments)
    if key is None:
        key = root_key(seed)
    counts = np.asarray(counts, dtype=np.float32)
    pca = np.asarray(pca, dtype=np.float32)
    if pc_num is None:
        pc_num = pca.shape[1]

    if len(set(assignments.tolist())) <= 1:
        return assignments

    if not test_separately or dend is None or dend.n_leaves <= 1:
        sil = _silhouette(pca, assignments, max_clusters)
        if sil > silhouette_thresh:
            # reference :907 — confident clusterings skip the null test
            return assignments
        rejected, _ = _clustering_rejected(
            key, counts, sil, pc_num,
            alpha=alpha, k_num=k_num, covariates=covariates,
            n_sims=n_sims, max_clusters=max_clusters, log=log,
            cluster_fun=cluster_fun, res_range=res_range,
            compute_dtype=compute_dtype,
        )
        if rejected:
            return np.full(n, "1", dtype=object)
        return assignments

    return _test_tree(
        key, counts, pca, dend, assignments,
        pc_num=pc_num, k_num=k_num, alpha=alpha,
        silhouette_thresh=silhouette_thresh, covariates=covariates,
        n_sims=n_sims, max_clusters=max_clusters, log=log, depth=0,
        cluster_fun=cluster_fun, res_range=res_range,
        compute_dtype=compute_dtype,
    )


def _branch_structures(pca, dend, labels, max_clusters):
    """Cut the tree at its first split and derive (h, memberships-per-leaf,
    per-cell branch codes, branch-level silhouette) — the reference's
    :894-905 preamble, also recomputed after each merge step (:984-998)."""
    h = dend.first_split_height()
    memb = dend.cut_memberships(h)
    branch_of = {leaf: int(b) for leaf, b in zip(dend.labels, memb)}
    branch_codes = np.asarray([branch_of.get(l, 1) for l in labels])
    sil = (
        _silhouette(pca, branch_codes, max_clusters)
        if len(np.unique(branch_codes)) > 1
        else 1.0
    )
    return h, branch_of, branch_codes, sil


def _test_tree(
    key: jax.Array,
    counts: np.ndarray,
    pca: np.ndarray,
    dend: Dendrogram,
    assignments: np.ndarray,
    *,
    pc_num: int,
    k_num,
    alpha: float,
    silhouette_thresh: float,
    covariates,
    n_sims: int,
    max_clusters: int,
    log: Optional[LevelLog],
    depth: int,
    cluster_fun: str = "leiden",
    res_range=None,
    compute_dtype: str = "float32",
) -> np.ndarray:
    """Per-split walk (reference :894-905, 966-1036): test this subtree's top
    split; on failure, softly merge the majority cluster of each branch and
    re-test the rebuilt tree against the SAME null fit until a split survives
    or one cluster remains (:971-1001); then recurse into the surviving
    branches with subset counts/pca (:1003-1034)."""
    labels = assignments.copy()
    if dend.n_leaves <= 1 or len(set(labels.tolist())) <= 1:
        return labels

    h, branch_of, branch_codes, sil = _branch_structures(
        pca, dend, labels, max_clusters
    )
    if len(np.unique(branch_codes)) <= 1:
        return labels

    if sil <= silhouette_thresh:
        rejected, null_stats = _clustering_rejected(
            cluster_key(key, f"split_{depth}"), counts, sil, pc_num,
            alpha=alpha, k_num=k_num, covariates=covariates,
            n_sims=n_sims, max_clusters=max_clusters, log=log,
            cluster_fun=cluster_fun, res_range=res_range,
            compute_dtype=compute_dtype,
        )
        # Failed split: merge the majority cluster of each branch into one
        # cluster, rebuild the dendrogram from Euclidean PCA distances, and
        # re-test the new top split against the existing null fit — the
        # reference's while loop at :971-1001.
        while rejected and len(set(labels.tolist())) > 1:
            reps = []
            for b in sorted(set(branch_of.values())):
                in_branch = [l for l in set(labels.tolist()) if branch_of.get(l) == b]
                if not in_branch:
                    continue
                sizes = {l: int(np.sum(labels == l)) for l in in_branch}
                reps.append(max(sizes, key=sizes.get))
            if len(reps) < 2:
                break
            labels[np.isin(labels, np.asarray(reps, dtype=object))] = reps[0]
            if len(set(labels.tolist())) <= 1:
                break
            dend = determine_hierarchy(_euclidean(pca), labels)
            if dend.n_leaves <= 1:
                break
            h, branch_of, branch_codes, sil = _branch_structures(
                pca, dend, labels, max_clusters
            )
            p = null_p_value(sil, null_stats)
            if log:
                log.event("split_retest", silhouette=sil, p_value=p, depth=depth)
            rejected = p >= alpha
        if len(set(labels.tolist())) <= 1:
            return labels

    # surviving split: test each branch's own sub-splits on its cells
    # (reference :1003-1034 — only subtrees whose leaves still exist recurse)
    for sub in dend.subtrees(h):
        live = [l for l in sub.labels if l in set(labels.tolist())]
        if sub.n_leaves <= 1 or len(live) <= 1:
            continue
        mask = np.isin(labels, np.asarray(live, dtype=object))
        if mask.sum() < 2:
            continue
        cov_sub = covariates[mask] if covariates is not None else None
        labels[mask] = _test_tree(
            cluster_key(key, f"sub_{depth}_{sub.labels[0]}"),
            counts[mask], pca[mask], sub.restrict(live), labels[mask],
            pc_num=pc_num, k_num=k_num, alpha=alpha,
            silhouette_thresh=silhouette_thresh, covariates=cov_sub,
            n_sims=n_sims, max_clusters=max_clusters, log=log, depth=depth + 1,
            cluster_fun=cluster_fun, res_range=res_range,
            compute_dtype=compute_dtype,
        )
    return labels
