"""Fleet assembly: build N replicas behind a FleetRouter (ISSUE 18).

The router (serve/router.py) is deliberately ignorant of how replicas are
made — it takes ready services plus a *spawn template*. This module is the
template factory: :func:`build_fleet` resolves the replica count
(explicit arg > ``ClusterConfig.fleet_replicas`` > ``CCTPU_FLEET_REPLICAS``
> 2), captures the AssignmentService construction kwargs once, and hands
the router a ``spawn(reference)`` callable it reuses for failover revival
and for :meth:`FleetRouter.swap_reference` standbys — so a revived or
swapped-in replica is configured exactly like the originals.

Quick start (also in README)::

    from consensusclustr_tpu.serve import build_fleet

    fleet = build_fleet(artifact, 2, queue_depth=16, max_batch=64)
    try:
        labels = fleet.assign(counts).labels
        fleet.swap_reference(artifact_v2)     # zero-downtime version swap
        record = fleet.fleet_record()         # merged fleet trace (ISSUE 19)
    finally:
        fleet.close()

Every admitted request carries a router-minted ``trace_id`` whose hop chain
(initial route, failover re-route, revival) lands in ``fleet_record()`` —
the schema-v11 merged artifact obs/fleetobs.py serializes and
tools/timeline.py folds into a causal incident timeline.
"""

from __future__ import annotations

import os
from typing import Optional

from consensusclustr_tpu.serve.control import ControlPolicy
from consensusclustr_tpu.serve.router import FleetRouter
from consensusclustr_tpu.serve.service import AssignmentService

DEFAULT_FLEET_REPLICAS = 2


def fleet_replicas(requested: Optional[int] = None, config=None) -> int:
    """Replica count: explicit arg > ``ClusterConfig.fleet_replicas`` >
    ``CCTPU_FLEET_REPLICAS`` env > 2. Must be >= 1."""
    if requested is None:
        cfg_val = getattr(config, "fleet_replicas", None)
        if cfg_val is not None:
            requested = int(cfg_val)
        else:
            env = os.environ.get("CCTPU_FLEET_REPLICAS", "").strip()
            requested = int(env) if env else DEFAULT_FLEET_REPLICAS
    n = int(requested)
    if n < 1:
        raise ValueError(f"fleet needs at least 1 replica; got {n}")
    return n


def build_fleet(
    reference,
    n_replicas: Optional[int] = None,
    *,
    config=None,
    control: Optional[bool] = None,
    **svc_kwargs,
) -> FleetRouter:
    """Build ``n_replicas`` AssignmentService replicas behind a FleetRouter.

    ``svc_kwargs`` pass through to every AssignmentService (and to every
    future revival/standby — the spawn template captures them), e.g.
    ``queue_depth``, ``max_batch``, ``buckets``, ``mode``, ``warmup``.
    ``control`` arms the adaptive ControlPolicy (resolution: arg >
    ``config.fleet_control`` > ``CCTPU_FLEET_CONTROL`` > off; the off
    state is pinned bit-identical to a routerless service).
    """
    n = fleet_replicas(n_replicas, config)
    policy = ControlPolicy(control, config=config)

    def spawn(ref, name: str = "") -> AssignmentService:
        # replica_name at CONSTRUCTION: a permanently-faulted worker can
        # _fail_all before the router gets a chance to stamp the name, and
        # the post-mortem must still say which replica died
        return AssignmentService(
            ref, config=config, replica_name=name, **svc_kwargs
        )

    services = [spawn(reference, f"r{i}") for i in range(n)]
    return FleetRouter(services, control=policy, spawn=spawn)
