"""Alert-driven adaptive serving control (ISSUE 18 tentpole, part c).

The PR 14 alert engine raises ``serve_p99_high`` / ``slo_burn_rate_high``
but nothing *acts* on them — the queue is the only actuator, and it only
acts by rejecting. This module closes the ROADMAP O3 loop: a
:class:`ControlPolicy` reads a replica's health scrape (the same
``/healthz`` body the router scores) plus its live ``queue_wait_seconds``
histogram and decides, per replica, how the serving worker should batch and
whether the router should still admit:

  * **latency pressure** (``serve_p99_high`` firing, or queue-wait p99 past
    ``QUEUE_WAIT_BOUND_S``): flush immediately — batch-gather deadline
    drops to 0 and the micro-batch row cap halves, so the worker forms
    smaller batches that land in smaller pad buckets and drain faster.
    Throughput is deliberately sacrificed for the tail.
  * **burn pressure** (``slo_burn_rate_high`` firing — rejections eating
    the error budget): batch harder — the gather deadline stretches to
    ``BURN_DEADLINE_FACTOR``x so each dispatch carries more rows — and,
    once the queue passes ``SHED_OCCUPANCY``, the router sheds at the door
    (a ``RetryableRejection`` with a drain-rate hint) instead of letting
    the queue overflow reject with no warning.
  * **calm**: the small base gather deadline
    (``CCTPU_FLEET_CONTROL_DEADLINE_MS``) — a bounded wait that trades
    microseconds of latency for fuller buckets.

Strictly opt-in (``CCTPU_FLEET_CONTROL`` / ``ClusterConfig.fleet_control``,
default OFF), PR 8/14/16 style: when off, :meth:`ControlPolicy.decide`
returns the inert :data:`NO_CONTROL` decision, the router applies nothing,
and the worker's batch path is bit-identical to a build without this module
(pinned in tests/test_fleet.py — identical labels AND identical work
ledger). Why off by default: adaptive batching changes *which requests
share a micro-batch*, which changes nothing about any single result (the
assign path is row-independent) but does change latency decomposition and
bucket choice — exactly the class of behavior a reproducible benchmark
must not have silently enabled. See docs/quirks.md "Observability schema
v9 -> v10".

Import-light: no jax — the router and the config validator import this
module without touching a backend.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

from consensusclustr_tpu.obs.alerts import BURN_ALERT, P99_ALERT
from consensusclustr_tpu.obs.metrics import MetricsRegistry

# Armed-control tuning constants. Deliberately few and deliberately not all
# env knobs: the two that matter operationally (arming, base deadline) are;
# the shed/bound constants are policy shape, pinned by tests.
DEFAULT_CONTROL_DEADLINE_MS = 2.0
SHED_OCCUPANCY = 0.8          # queue fill fraction where burn pressure sheds
QUEUE_WAIT_BOUND_S = 1.0      # queue-wait p99 treated as latency pressure
BURN_DEADLINE_FACTOR = 4.0    # gather-deadline stretch under burn pressure
_MIN_WAIT_COUNT = 20          # queue-wait observations before p99 is trusted


def fleet_control_enabled(
    requested: Optional[bool] = None, config=None
) -> bool:
    """Explicit arg > ``ClusterConfig.fleet_control`` > truthy
    ``CCTPU_FLEET_CONTROL`` env > OFF (the default — off is pinned free)."""
    if requested is not None:
        return bool(requested)
    cfg_val = getattr(config, "fleet_control", None)
    if cfg_val is not None:
        return bool(cfg_val)
    env = os.environ.get("CCTPU_FLEET_CONTROL", "").strip().lower()
    return env not in ("", "0", "off", "false", "none")


def control_deadline_s(requested_ms: Optional[float] = None) -> float:
    """Armed base gather deadline in seconds: explicit arg >
    ``CCTPU_FLEET_CONTROL_DEADLINE_MS`` > 2 ms."""
    if requested_ms is None:
        env = os.environ.get("CCTPU_FLEET_CONTROL_DEADLINE_MS", "").strip()
        requested_ms = float(env) if env else DEFAULT_CONTROL_DEADLINE_MS
    ms = float(requested_ms)
    if ms < 0:
        raise ValueError(f"control deadline must be >= 0 ms; got {ms}")
    return ms / 1000.0


@dataclasses.dataclass(frozen=True)
class ControlDecision:
    """What one replica's worker + the router door should do right now.

    ``batch_deadline_s`` / ``batch_rows_cap`` map 1:1 onto the
    AssignmentService attributes of the same names (worker-side batching);
    ``admit`` gates the router door; ``reason`` is the pressure class
    ("latency" / "burn" / "" when calm) — transitions are what the router
    counts and events.
    """

    batch_deadline_s: float = 0.0
    batch_rows_cap: Optional[int] = None
    admit: bool = True
    reason: str = ""


# The disarmed decision: exactly the AssignmentService defaults, so applying
# it is indistinguishable from never applying anything.
NO_CONTROL = ControlDecision()


class ControlPolicy:
    """Per-replica adaptive decisions off the live alert + queue-wait state.

    Stateless across calls (the router owns per-replica transition
    memory): ``decide`` is a pure function of the scrape, so tests can pin
    the policy table directly.
    """

    def __init__(
        self,
        enabled: Optional[bool] = None,
        *,
        config=None,
        deadline_ms: Optional[float] = None,
    ) -> None:
        self.enabled = fleet_control_enabled(enabled, config)
        self.deadline_s = control_deadline_s(deadline_ms)

    def _queue_wait_p99(self, metrics: Optional[MetricsRegistry]):
        if metrics is None:
            return None
        h = metrics.histograms.get("queue_wait_seconds")
        if h is None or h.count < _MIN_WAIT_COUNT:
            return None
        try:
            return h.quantile(0.99)
        except Exception:  # graftlint: noqa[GL007] quantile on a malformed/empty histogram just means "no latency signal yet" — control degrades to the calm decision
            return None

    def decide(
        self,
        health: dict,
        queue_capacity: int,
        metrics: Optional[MetricsRegistry] = None,
    ) -> ControlDecision:
        """One replica's decision from its health scrape.

        ``health`` is the AssignmentService.health() dict (``queue_depth``
        there is *occupancy*); ``queue_capacity`` is the service's
        configured depth; ``metrics`` the replica's registry for the
        queue-wait histogram. Disarmed -> :data:`NO_CONTROL`, always.
        """
        if not self.enabled:
            return NO_CONTROL
        active = set(health.get("alerts_active") or ())
        wait_p99 = self._queue_wait_p99(metrics)
        latency = P99_ALERT in active or (
            wait_p99 is not None and wait_p99 > QUEUE_WAIT_BOUND_S
        )
        burn = BURN_ALERT in active
        occupancy = (
            float(health.get("queue_depth", 0)) / queue_capacity
            if queue_capacity > 0
            else 0.0
        )
        if latency:
            # flush now, batch small: smaller pad buckets drain faster
            cap = max(1, int(health.get("max_batch", 0) or 0) // 2) or None
            return ControlDecision(0.0, cap, True, "latency")
        if burn:
            # batch harder for throughput; past SHED_OCCUPANCY shed at the
            # door (with a hint) before the queue overflows (without one)
            return ControlDecision(
                self.deadline_s * BURN_DEADLINE_FACTOR,
                None,
                occupancy < SHED_OCCUPANCY,
                "burn",
            )
        return ControlDecision(self.deadline_s, None, True, "calm")
