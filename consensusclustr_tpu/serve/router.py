"""Multi-replica admission router (ISSUE 18 tentpole, part a).

A :class:`FleetRouter` owns N :class:`~consensusclustr_tpu.serve.service.
AssignmentService` replicas and routes each submit by the same signals a
real fleet's load balancer scrapes from ``/healthz`` — here read in-process
from :meth:`AssignmentService.health`:

  * ``status``            — anything but "ok" (draining / closed / a worker
    past its restart budget) takes the replica out of rotation and counts
    ``fleet_replica_unhealthy``;
  * ``alerts_active``     — a replica firing ``serve_p99_high`` or
    ``slo_burn_rate_high`` is *degraded*: still admitting, but only chosen
    when every clean replica rejected;
  * ``queue_depth`` / ``in_flight`` — least-loaded admission among equals;
  * drain rate            — each replica's ``retry_after_hint()`` is the
    backoff the fleet-wide rejection carries.

The router raises :class:`RetryableRejection` only when EVERY replica
rejected (fleet saturation); a single full replica just routes elsewhere.
Each accepted request gets a *router future* chained onto the replica
future, and the chain is also the self-healing path: when a replica dies
mid-request (the supervisor's give-up ``_fail_all``), its accepted
requests are not lost — they re-queue as orphans, a failover thread
re-routes them to a healthy replica (reviving dead slots from the spawn
template when none is left), and the original caller's future completes
as if nothing happened. tools/chaos_audit.py's ``fleet_replica_death``
preset pins exactly this: a ``serve_worker`` fault kills a replica
mid-ladder, no accepted request is lost, and the post-mortem names the
dead replica.

Fleet-level observability rides the router's own tracer: the
``fleet_*`` metrics registered in obs/schema.py (v10), a fleet
``serve_latency_seconds`` histogram (observed per completed request
*before* the router future resolves, so a client that saw a result is
already counted), a fleet ``serve_rejections`` counter — which means the
PR 14 alert rules evaluate unchanged one level up — and ``fleet_*``
events for swaps, failovers and control transitions.

Hot-swap (:meth:`swap_reference`, ISSUE 18 part b) lives here because the
flip is an admission decision: standby replicas for the new artifact warm
from the PR 13 AOT caches (in-process registry first, disk second — zero
compiles when the version was ever served before), the replica list swaps
under the lock in one assignment (atomic for every concurrent submit
snapshot), and the old replicas drain via ``close()`` — every accepted
request completes, so a loadgen run straddling the swap shows 0 failures
and 0 swap-time ``executable_compiles``.
"""

from __future__ import annotations

import inspect
import itertools
import os
import queue
import threading
import time
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Sequence

from consensusclustr_tpu.obs.alerts import BURN_ALERT, P99_ALERT, attach_alerts
from consensusclustr_tpu.obs.flight import attach_flight
from consensusclustr_tpu.obs.metrics import global_metrics
from consensusclustr_tpu.obs.record import RunRecord
from consensusclustr_tpu.obs.tracer import Tracer
from consensusclustr_tpu.serve.control import NO_CONTROL, ControlPolicy
from consensusclustr_tpu.serve.service import (
    AssignmentService,
    AssignResult,
    RetryableRejection,
)

# Orphan failover pacing: capped linear backoff between re-route attempts
# while no replica is healthy (a planted permanent fault keeps killing
# revived replicas until the chaos harness clears it).
_ORPHAN_BACKOFF_S = 0.05
_ORPHAN_BACKOFF_MAX_S = 1.0
_ORPHAN_ATTEMPT_LIMIT = 400
_FAILOVER_POLL_S = 0.1
# Idle-poll revival pacing: a planted permanent fault (chaos presets) kills
# every revived replica instantly; retrying a full respawn+warmup on every
# 100 ms poll would be churn, so revival attempts are rate-limited.
_REVIVE_INTERVAL_S = 0.5
_SENTINEL = object()

# Degraded-routing alert set: a replica firing either is only chosen when
# every clean replica rejected.
_DEGRADED_ALERTS = frozenset({P99_ALERT, BURN_ALERT})

# Admission-path scrape cadence: a full health() scrape evaluates every
# alert rule (~100 us on a slow core), which at saturation rates would burn
# a double-digit share of one core on scrapes alone. The router therefore
# scrapes each replica at most every _HEALTH_TTL_S and routes on the cached
# verdict plus a live (cheap) in-flight read. Staleness is safe, not just
# tolerable: a replica that dies inside the TTL window fails its submit
# with RuntimeError, which marks it unhealthy and drops the cache on the
# spot — the stale "ok" never strands a request.
_HEALTH_TTL_S = 0.05

# Fleet distributed tracing (ISSUE 19): hop-chain retention cap, the fleet
# analogue of service.LIFECYCLE_RECORD_CAP — trace_ids past it still mint
# and still serve, but their chains are not retained (fleet_traces_dropped
# counts them), so a long-lived router stays bounded.
DEFAULT_FLEET_TRACE_CAP = 100_000


def fleet_trace_cap(requested: Optional[int] = None) -> int:
    """Explicit arg > $CCTPU_FLEET_TRACE_CAP > 100_000 (docs/quirks.md)."""
    if requested is None:
        requested = int(
            os.environ.get("CCTPU_FLEET_TRACE_CAP", DEFAULT_FLEET_TRACE_CAP)
        )
    v = int(requested)
    if v < 0:
        raise ValueError(f"fleet trace cap must be >= 0; got {v}")
    return v


class _Replica:
    """One owned service + the router's per-replica bookkeeping."""

    __slots__ = ("name", "svc", "routed", "control_reason", "score",
                 "score_at", "admit")

    def __init__(self, name: str, svc: AssignmentService) -> None:
        self.name = name
        self.svc = svc
        self.routed = 0
        self.control_reason = ""
        # cached (healthy, degraded, load, health) + scrape time + control
        # admit verdict — refreshed by FleetRouter._scored on TTL expiry
        self.score = None
        self.score_at = -1e9
        self.admit = True
        svc.replica_name = name


class _Orphan:
    """An accepted request whose replica died before completing it."""

    __slots__ = ("future", "counts", "mode", "attempts", "last_error", "t0",
                 "trace")

    def __init__(self, future, counts, mode, t0, trace=None) -> None:
        self.future = future
        self.counts = counts
        self.mode = mode
        self.attempts = 0
        self.last_error: Optional[BaseException] = None
        self.t0 = t0
        self.trace = trace  # the hop chain follows the request, not the replica


class FleetRouter:
    """Health-keyed admission over N AssignmentService replicas.

    Duck-types the single-service surface tools/loadgen.py drives
    (``submit`` / ``assign`` / ``max_batch`` / ``metrics`` / ``tracer`` /
    ``health`` / ``retry_after_hint`` / ``close`` / context manager), so
    ``--target fleet`` and the bench ``fleet_slo`` rung reuse the open-loop
    machinery unchanged.
    """

    def __init__(
        self,
        services: Sequence[AssignmentService],
        *,
        control: Optional[ControlPolicy] = None,
        spawn: Optional[Callable[[object], AssignmentService]] = None,
        tracer: Optional[Tracer] = None,
    ) -> None:
        if not services:
            raise ValueError("FleetRouter needs at least one replica")
        self.tracer = tracer if tracer is not None else Tracer()
        self.metrics = self.tracer.metrics
        attach_flight(self.tracer)
        self._alerts = attach_alerts(self.tracer)
        self.control = control if control is not None else ControlPolicy()
        self._spawn = spawn
        # Does the spawn template accept a replica-name argument?
        # (serve.fleet.build_fleet's does — naming at construction means a
        # worker that dies inside the ctor still post-mortems by name.)
        self._spawn_takes_name = False
        if spawn is not None:
            try:
                self._spawn_takes_name = (
                    len(inspect.signature(spawn).parameters) >= 2
                )
            except (TypeError, ValueError):  # builtins / odd callables
                self._spawn_takes_name = False
        self._lock = threading.RLock()
        self._gen = 0
        self._replicas: List[_Replica] = [
            _Replica(f"r{i}", svc) for i, svc in enumerate(services)
        ]
        self.reference = services[0].reference
        self._closing = False
        self._closed = False
        self._accepted = 0
        self._completed = 0
        self._orphans: "queue.Queue" = queue.Queue()
        self._last_revive = 0.0
        self._revivals = 0
        # fleet distributed tracing (ISSUE 19): router-minted trace ids —
        # minted HERE, not in the replica, because a replica can die before
        # it would mint anything and only the router sees every hop of a
        # request that crosses replicas. Hop chains are retained per
        # trace_id up to the cap; retired replicas (revival-replaced or
        # swap-drained) are kept so the merged FleetRecord still has the
        # dead lane's spans and events.
        self._trace_ids = itertools.count(1)
        self._trace_cap = fleet_trace_cap()
        self._traces: Dict[int, dict] = {}
        self._retired: List[_Replica] = []
        self._failover = threading.Thread(
            target=self._failover_loop, name="cctpu-fleet-failover",
            daemon=True,
        )
        # admission hot path: resolve metric handles once (a registry lookup
        # per routed request is measurable at saturation rates), and pace the
        # fleet-level alert sweep like the health scrapes
        self._c_routed = self.metrics.counter("fleet_requests_routed")
        self._c_unhealthy = self.metrics.counter("fleet_replica_unhealthy")
        self._c_fleet_rej = self.metrics.counter("fleet_rejections")
        self._c_serve_rej = self.metrics.counter("serve_rejections")
        self._h_latency = self.metrics.histogram("serve_latency_seconds")
        self._g_queue_depth = self.metrics.gauge("fleet_replica_queue_depth")
        self._g_inflight = self.metrics.gauge("fleet_replica_inflight")
        self._c_trace_drops = self.metrics.counter("fleet_traces_dropped")
        self._last_alert_eval = -1e9
        self._failover.start()
        self.metrics.gauge("fleet_replicas").set(len(self._replicas))
        self.tracer.event(
            "fleet_start",
            replicas=[r.name for r in self._replicas],
            control=self.control.enabled,
        )

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        """Stop intake, drain every replica (all accepted requests
        complete), stop the failover thread."""
        if self._closed:
            return
        self._closing = True
        self._orphans.put(_SENTINEL)
        self._failover.join()
        with self._lock:
            reps = list(self._replicas)
        for rep in reps:
            try:
                rep.svc.close()
            except Exception:  # graftlint: noqa[GL007] a replica that cannot drain must not block the fleet's shutdown of its siblings
                pass
        self._closed = True
        self.tracer.event("fleet_drain", routed=self.routed_per_replica())

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- single-service duck type --------------------------------------------

    @property
    def max_batch(self) -> int:
        with self._lock:
            return min(r.svc.max_batch for r in self._replicas)

    @property
    def replicas(self) -> List[AssignmentService]:
        with self._lock:
            return [r.svc for r in self._replicas]

    @property
    def generation(self) -> int:
        return self._gen

    def routed_per_replica(self) -> Dict[str, int]:
        """{replica name: requests routed there} — the bench rung's split."""
        with self._lock:
            return {r.name: r.routed for r in self._replicas}

    def retry_after_hint(self) -> Optional[float]:
        """The most optimistic replica drain hint (a fleet retry should wait
        for the FIRST slot anywhere, not the slowest)."""
        hints = []
        with self._lock:
            reps = list(self._replicas)
        for rep in reps:
            try:
                h = rep.svc.retry_after_hint()
            except Exception:  # graftlint: noqa[GL007] best-effort backoff hint; a hintless rejection is the documented degrade (hint stays None)
                h = None
            if h is not None:
                hints.append(h)
        return min(hints) if hints else None

    # -- admission -----------------------------------------------------------

    def _score(self, rep: _Replica):
        """(healthy, degraded, load, health-dict) for one replica. Unhealthy
        replicas return healthy=False and are skipped by routing."""
        try:
            h = rep.svc.health()
        except Exception as e:  # graftlint: noqa[GL007] probe failure IS the signal — the caller records it via the fleet_replica_down event in _mark_unhealthy
            return (False, True, 0, {"status": f"error:{type(e).__name__}"})
        healthy = h.get("status") == "ok"
        degraded = bool(_DEGRADED_ALERTS & set(h.get("alerts_active") or ()))
        load = int(h.get("in_flight", 0))
        return (healthy, degraded, load, h)

    def _apply_control(self, rep: _Replica, health: dict) -> bool:
        """Apply the ControlPolicy decision to one replica's worker knobs;
        returns its admit verdict. Disarmed control touches nothing."""
        if not self.control.enabled:
            return True
        dec = self.control.decide(
            health, rep.svc.queue_depth, rep.svc.metrics
        )
        if dec is NO_CONTROL:
            return True
        rep.svc.batch_deadline_s = dec.batch_deadline_s
        rep.svc.batch_rows_cap = dec.batch_rows_cap
        if dec.reason != rep.control_reason:
            rep.control_reason = dec.reason
            self.metrics.counter("fleet_control_decisions").inc()
            self.tracer.event(
                "fleet_control",
                replica=rep.name,
                reason=dec.reason,
                deadline_s=dec.batch_deadline_s,
                rows_cap=dec.batch_rows_cap,
            )
        return dec.admit

    def _mark_unhealthy(self, rep: _Replica, status: str) -> None:
        self._c_unhealthy.inc()
        self.tracer.event(
            "fleet_replica_down", replica=rep.name, status=status
        )

    def _scored(self, rep: _Replica, now: float):
        """Routing signals for one replica: ``(healthy, degraded, load,
        health, admit)``. The full scrape (alert evaluation, control
        decision, snapshot gauges) runs at most once per ``_HEALTH_TTL_S``;
        between scrapes the hot path reuses the cached verdict with a live
        in-flight read, so admission cost stays flat as the offered rate
        climbs."""
        cached = rep.score
        if cached is None or now - rep.score_at >= _HEALTH_TTL_S:
            cached = self._score(rep)
            rep.score = cached
            rep.score_at = now
            healthy, _, load, h = cached
            rep.admit = self._apply_control(rep, h) if healthy else True
            self._g_queue_depth.set(int(h.get("queue_depth", 0)))
            self._g_inflight.set(load)
        healthy, degraded, _, h = cached
        return healthy, degraded, int(rep.svc.in_flight), h, rep.admit

    # -- trace context (ISSUE 19) --------------------------------------------

    def _mint_trace(self, t0: Optional[float] = None) -> dict:
        """Mint the fleet-scoped trace context for one admission: the
        trace_id plus an (initially empty) ordered hop chain. ``t_admit``
        is on the router tracer's timeline (the merged-trace clock);
        ``_t0`` is the perf_counter admission instant every hop's ``t``
        is relative to (underscore keys never serialize); the caller
        passes its own admission clock read so the chain and the fleet
        latency share one origin exactly."""
        tid = next(self._trace_ids)
        trace = {
            "trace_id": tid,
            "t_admit": self.tracer.elapsed(),
            "hops": [],
            "_t0": t0 if t0 is not None else time.perf_counter(),
        }
        if tid <= self._trace_cap:
            self._traces[tid] = trace
        else:
            self._c_trace_drops.inc()
        return trace

    def _drop_trace(self, trace: Optional[dict]) -> None:
        """Forget a minted trace whose admission was rejected fleet-wide
        (nothing was enqueued anywhere — there is no request to trace)."""
        if trace is not None:
            self._traces.pop(trace["trace_id"], None)

    def _hop_for(self, trace: dict, rep: _Replica) -> dict:
        """The next hop record for ``trace``: initial route, failover
        re-route, or a re-route onto a revival slot (``~`` names). The
        replica stamps ``req_id`` into this dict on accept — and
        refines ``t`` to its own submit-entry clock read (the ``_t0``
        passed along here), closing the preemption window between this
        stamp and the submit call so hop parity is exact; the router
        stamps ``outcome`` when the hop ends."""
        k = len(trace["hops"])
        kind = (
            "route" if k == 0
            else "revival" if "~" in rep.name
            else "failover"
        )
        return {
            "trace_id": trace["trace_id"],
            "hop": k,
            "replica": rep.name,
            "kind": kind,
            "t": round(time.perf_counter() - trace["_t0"], 6),
            "_t0": trace["_t0"],
        }

    def trace_table(self) -> dict:
        """Snapshot of every retained hop chain (obs/fleetobs.py merges
        this into the FleetRecord ``trace`` block)."""
        traces = []
        for tr in list(self._traces.values()):
            snap = {k: v for k, v in tr.items() if not k.startswith("_")}
            snap["hops"] = [dict(h) for h in tr["hops"]]
            traces.append(snap)
        return {
            "cap": self._trace_cap,
            "retained": len(traces),
            "dropped": int(self._c_trace_drops.value),
            "traces": traces,
        }

    def replica_records(self) -> list:
        """Every replica this router ever owned as ``(name, service,
        retired)`` — current rotation first, then retired slots (revival-
        replaced or swap-drained), whose tracers still hold the dead lane's
        spans/events for the merged FleetRecord."""
        with self._lock:
            cur = list(self._replicas)
            old = list(self._retired)
        return (
            [(r.name, r.svc, False) for r in cur]
            + [(r.name, r.svc, True) for r in old]
        )

    # -- admission (continued) -----------------------------------------------

    def _route_once(self, counts, mode, trace: Optional[dict] = None):
        """One admission pass over the current replica snapshot. Returns
        (replica, replica-future) or raises RetryableRejection when every
        admitting replica rejected. Returns (None, None) when no replica is
        even admitting (all unhealthy/shed) — the caller decides whether
        that is a shed, a retry, or an orphan requeue. A successful pass
        appends one hop to ``trace`` (rejected/raced attempts append
        nothing — the chain records where the request actually landed)."""
        with self._lock:
            reps = list(self._replicas)
        now = time.perf_counter()
        scored = []
        shed = False
        for rep in reps:
            healthy, degraded, load, h, admit = self._scored(rep, now)
            if not healthy:
                self._mark_unhealthy(rep, str(h.get("status")))
                continue
            if not admit:
                shed = True
                continue
            # routed-count tie-break: equal-load replicas alternate instead
            # of pinning to whichever sorts first
            scored.append((degraded, load, rep.routed, id(rep), rep, h))
        if not scored:
            if shed:
                self.metrics.counter("fleet_control_sheds").inc()
                self._c_serve_rej.inc()
                raise RetryableRejection(
                    "fleet control shed: every replica past its shed "
                    "occupancy under burn pressure",
                    retry_after_s=self.retry_after_hint(),
                )
            return None, None
        scored.sort(key=lambda t: t[:3])
        rejected = 0
        for degraded, load, _, _, rep, h in scored:
            hop = self._hop_for(trace, rep) if trace is not None else None
            try:
                fut = rep.svc.submit(counts, mode=mode, trace=hop)
            except RetryableRejection:
                rejected += 1
                continue
            except RuntimeError:
                # shut down between scrape and submit (a swap drain or a
                # dying worker closing intake): out of rotation this pass,
                # and the cached "ok" is void — rescrape next pass
                rep.score = None
                self._mark_unhealthy(rep, "shutdown")
                continue
            if hop is not None:
                trace["hops"].append(hop)  # req_id already stamped by submit
            rep.routed += 1
            self._c_routed.inc()
            return rep, fut
        if rejected:
            # every admitting replica rejected: fleet saturation
            self._c_fleet_rej.inc()
            self._c_serve_rej.inc()
            raise RetryableRejection(
                f"all {len(scored)} admitting replicas rejected "
                "(fleet saturated); retry",
                retry_after_s=self.retry_after_hint(),
            )
        return None, None

    def submit(self, counts, mode: Optional[str] = None) -> Future:
        """Route one request; returns a Future of AssignResult.

        Raises :class:`RetryableRejection` only when every replica rejected
        or control shed fleet-wide; RuntimeError when the fleet is shut
        down or no replica is in rotation at all.
        """
        if self._closing or self._closed:
            raise RuntimeError("FleetRouter is shut down")
        t0 = time.perf_counter()
        # mint the fleet-scoped trace identity at admission — before
        # routing, so even a request that never lands anywhere had one
        trace = self._mint_trace(t0)
        # two passes: a swap can atomically replace the replica list between
        # the snapshot and the submit — the refreshed snapshot sees the new
        # generation
        try:
            for attempt in (0, 1):
                rep, fut = self._route_once(counts, mode, trace)
                if rep is not None:
                    break
            else:  # pragma: no cover - defensive; the loop always breaks or falls through with rep=None
                rep, fut = None, None
        except RetryableRejection:
            self._drop_trace(trace)  # nothing enqueued: no request to trace
            raise
        if rep is None:
            self._drop_trace(trace)
            raise RuntimeError(
                "no replica in rotation (all unhealthy or draining)"
            )
        self._accepted += 1
        router_future: Future = Future()
        self._chain(router_future, rep, fut, counts, mode, t0, trace)
        return router_future

    def assign(
        self, counts, mode: Optional[str] = None, timeout=None
    ) -> AssignResult:
        """Synchronous submit + wait."""
        return self.submit(counts, mode=mode).result(timeout=timeout)

    # -- completion + failover -----------------------------------------------

    def _chain(self, router_future, rep, replica_future, counts, mode, t0,
               trace=None):
        def _done(fut):
            err = fut.exception()
            if err is None:
                # observe BEFORE resolving: a caller that saw its result is
                # already in the fleet histogram (loadgen metrics parity)
                self._observe(t0)
                result = fut.result()
                if trace is not None:
                    self._finish_trace(trace, result, t0)
                router_future.set_result(result)
                return
            # replica-death classification: the give-up path fails futures
            # AND closes intake, so a not-"ok" status means the error was
            # the replica dying, not this request failing on its merits
            try:
                dead = rep.svc.health().get("status") != "ok"
            except Exception:  # graftlint: noqa[GL007] probe failure IS the signal (replica gone) — recorded just below via the fleet_failover event
                dead = True
            if dead and not self._closing:
                self.metrics.counter("fleet_failovers").inc()
                if trace is not None and trace["hops"]:
                    trace["hops"][-1]["outcome"] = "failover"
                    trace["hops"][-1]["error"] = type(err).__name__
                self.tracer.event(
                    "fleet_failover",
                    replica=rep.name,
                    error=type(err).__name__,
                    trace_id=trace["trace_id"] if trace is not None else None,
                )
                self._orphans.put(
                    _Orphan(router_future, counts, mode, t0, trace)
                )
                return
            self._completed += 1
            if trace is not None and trace["hops"]:
                trace["hops"][-1]["outcome"] = "error"
                trace["hops"][-1]["error"] = type(err).__name__
            router_future.set_exception(err)

        replica_future.add_done_callback(_done)

    def _finish_trace(self, trace: dict, result: AssignResult, t0) -> None:
        """Close the hop chain on completion and ride the whole chain back
        to the caller on ``AssignResult.timing["trace"]``. The per-request
        invariant tools/loadgen.py audits (``hop_parity``): the final hop's
        admission-relative ``t`` plus its replica-measured latency equals
        the client-observed fleet latency within PHASE_PARITY_TOL — all
        hops, backoffs and re-route gaps accounted for."""
        # the replica's absolute resolution instant (same process, same
        # perf_counter clock): both the chain's latency endpoint and the
        # final hop's serve span end on it, so hop-parity is exact by
        # construction — resolved_s covers submit-entry -> resolution, the
        # hop ``t`` was stamped immediately before that submit entry, and
        # callback-scheduling jitter cancels out of the identity
        t_res = result.timing.pop("_t_resolved", None)
        hops = trace["hops"]
        if hops:
            hops[-1]["outcome"] = "ok"
            # resolved_s, not latency_s: latency_s ends at the batch's
            # shared t_done (exact three-interval decomposition), while the
            # hop chain must cover the replica's per-request host work too
            hops[-1]["serve_latency_s"] = round(
                float(
                    result.timing.get("resolved_s")
                    or result.timing.get("latency_s")
                    or 0.0
                ),
                6,
            )
        trace["fleet_latency_s"] = round(
            (t_res if t_res is not None else time.perf_counter()) - t0, 6
        )
        result.timing["trace"] = {
            "trace_id": trace["trace_id"],
            "fleet_latency_s": trace["fleet_latency_s"],
            "hops": [dict(h) for h in hops],
        }

    def _observe(self, t0: float) -> None:
        self._completed += 1
        now = time.perf_counter()
        self._h_latency.observe(now - t0)
        # full-rule alert sweep paced like the health scrapes — per-request
        # evaluation at saturation rates is pure overhead (the engine's own
        # sampling window is far coarser than _HEALTH_TTL_S anyway)
        if (
            self._alerts is not None
            and now - self._last_alert_eval >= _HEALTH_TTL_S
        ):
            self._last_alert_eval = now
            self._alerts.evaluate()  # never raises

    def _spawn_named(self, reference, name: str) -> AssignmentService:
        """Spawn a replacement/standby replica, stamping its name at
        construction when the template supports it (so even a
        dies-in-the-ctor worker post-mortems by name)."""
        if self._spawn_takes_name:
            return self._spawn(reference, name)
        svc = self._spawn(reference)
        svc.replica_name = name
        return svc

    def _revive_dead(self, *, force: bool = True) -> int:
        """Replace dead replicas from the spawn template (when one was
        given). Returns how many came back. ``force=False`` (the idle-poll
        path) rate-limits attempts to one per ``_REVIVE_INTERVAL_S``."""
        if self._spawn is None or self._closing:
            return 0
        now = time.monotonic()
        if not force and now - self._last_revive < _REVIVE_INTERVAL_S:
            return 0
        self._last_revive = now
        revived = 0
        with self._lock:
            reps = list(self._replicas)
            for i, rep in enumerate(reps):
                try:
                    ok = rep.svc.health().get("status") == "ok"
                except Exception:  # graftlint: noqa[GL007] probe failure IS the signal (dead slot) — the revival it triggers is recorded via fleet_replica_revived
                    ok = False
                if ok:
                    continue
                base = rep.name.split("~", 1)[0]
                fresh_name = f"{base}~{self._revivals + 1}"
                try:
                    svc = self._spawn_named(self.reference, fresh_name)
                except Exception:  # graftlint: noqa[GL007] a failed revive (fault still planted) retries on the next failover pass instead of killing the thread
                    continue
                self._revivals += 1
                fresh = _Replica(fresh_name, svc)
                # retire, don't drop: the dead slot's tracer holds the
                # spans/events the merged FleetRecord renders as its lane
                self._retired.append(rep)
                self._replicas[i] = fresh
                revived += 1
                self.tracer.event(
                    "fleet_replica_revived", replica=fresh.name
                )
        if revived:
            self.metrics.gauge("fleet_replicas").set(len(self._replicas))
        return revived

    def _failover_loop(self) -> None:
        """Drain the orphan queue: re-route accepted requests off dead
        replicas so no caller's future is lost to a crash. Runs until
        close() sends the sentinel, then fails any stragglers loudly."""
        while True:
            try:
                item = self._orphans.get(timeout=_FAILOVER_POLL_S)
            except queue.Empty:
                # self-healing even with nothing orphaned: a replica that
                # died between requests (rate-limited — see above)
                self._revive_dead(force=False)
                continue
            if item is _SENTINEL:
                break
            orphan: _Orphan = item
            if orphan.future.done():
                continue
            orphan.attempts += 1
            try:
                rep, fut = self._route_once(
                    orphan.counts, orphan.mode, orphan.trace
                )
            except RetryableRejection as e:
                orphan.last_error = e
                rep, fut = None, None
            if rep is not None:
                self._chain(
                    orphan.future, rep, fut, orphan.counts, orphan.mode,
                    orphan.t0, orphan.trace,
                )
                continue
            if orphan.attempts >= _ORPHAN_ATTEMPT_LIMIT or self._closing:
                self._completed += 1
                orphan.future.set_exception(
                    orphan.last_error
                    or RuntimeError(
                        "fleet failover exhausted: no healthy replica"
                    )
                )
                continue
            self._revive_dead()
            time.sleep(
                min(
                    _ORPHAN_BACKOFF_S * orphan.attempts,
                    _ORPHAN_BACKOFF_MAX_S,
                )
            )
            self._orphans.put(orphan)
        # closing: anything still orphaned cannot be re-routed
        while True:
            try:
                item = self._orphans.get_nowait()
            except queue.Empty:
                break
            if item is _SENTINEL or item.future.done():
                continue
            self._completed += 1
            item.future.set_exception(
                RuntimeError("FleetRouter closed with orphaned requests")
            )

    # -- hot swap ------------------------------------------------------------

    def swap_reference(self, reference, *, replicas: Optional[int] = None) -> dict:
        """Zero-downtime version swap (ISSUE 18 part b).

        Pre-builds the new artifact's per-bucket executables on standby
        replicas (AssignmentService.warmup -> in-process AOT registry, then
        the PR 13 disk cache — zero fresh compiles when this version was
        ever served before), atomically flips admission to the standbys,
        then drains the old generation: ``close()`` completes every
        accepted request, so a loadgen run straddling the swap sees 0
        failures. Returns a swap report with the compile delta measured
        over the whole swap window (the pinned number)."""
        if self._spawn is None:
            raise RuntimeError(
                "swap_reference needs the spawn template "
                "(build the router via serve.fleet.build_fleet)"
            )
        if self._closing or self._closed:
            raise RuntimeError("FleetRouter is shut down")
        t0 = time.perf_counter()
        compiles = global_metrics().counter("executable_compiles")
        compiles0 = compiles.value
        with self.tracer.span("fleet_swap") as sp:
            with self._lock:
                n = replicas if replicas is not None else len(self._replicas)
                gen = self._gen + 1
            standby = [
                _Replica(
                    f"r{i}.v{gen}",
                    self._spawn_named(reference, f"r{i}.v{gen}"),
                )
                for i in range(n)
            ]
            with self._lock:
                old, self._replicas = self._replicas, standby
                self._gen = gen
                self.reference = reference
            drained = 0
            for rep in old:
                before = rep.svc.health()
                rep.svc.close()  # drains: every accepted request completes
                drained += int(before.get("in_flight", 0))
            with self._lock:
                # retired, not dropped: the drained generation's lanes stay
                # renderable in the merged FleetRecord (drain handoffs)
                self._retired.extend(old)
            swap_compiles = int(compiles.value - compiles0)
            wall_s = round(time.perf_counter() - t0, 4)
            self.metrics.counter("fleet_swaps").inc()
            if swap_compiles:
                self.metrics.counter("fleet_swap_compiles").inc(swap_compiles)
            self.metrics.gauge("fleet_replicas").set(n)
            sp.set(
                generation=gen, replicas=n, swap_compiles=swap_compiles,
                drained_in_flight=drained,
            )
        self.tracer.event(
            "fleet_swap",
            generation=gen,
            replicas=n,
            swap_compiles=swap_compiles,
            wall_s=wall_s,
        )
        return {
            "generation": gen,
            "replicas": n,
            "swap_compiles": swap_compiles,
            "drained_in_flight": drained,
            "wall_s": wall_s,
        }

    # -- introspection -------------------------------------------------------

    def health(self) -> dict:
        """Fleet-level /healthz: per-replica scrapes under their router
        names, the routed split, and the fleet alert state (evaluated over
        the router's own registry — rejections and latency one level up)."""
        with self._lock:
            reps = list(self._replicas)
            gen = self._gen
        replica_health = {}
        for rep in reps:
            try:
                replica_health[rep.name] = rep.svc.health()
            except Exception as e:  # graftlint: noqa[GL007] probe failure IS the signal — recorded in the returned scrape as the replica's error status
                replica_health[rep.name] = {
                    "status": f"error:{type(e).__name__}"
                }
        status = (
            "closed" if self._closed else "draining" if self._closing
            else "ok" if any(
                h.get("status") == "ok" for h in replica_health.values()
            ) else "degraded"  # router alive, zero replicas in rotation
        )
        alerts_active: dict = {}
        last_alert = None
        if self._alerts is not None:
            alerts_active = self._alerts.evaluate()
            last_alert = self._alerts.last_alert
        return {
            "status": status,
            "generation": gen,
            "replicas": replica_health,
            "routed": self.routed_per_replica(),
            "accepted": self._accepted,
            "completed": self._completed,
            "in_flight": self._accepted - self._completed,
            "alerts_active": sorted(alerts_active),
            "last_alert": dict(last_alert) if last_alert else None,
        }

    def run_record(self, config=None) -> RunRecord:
        """Snapshot the router's spans/metrics as a RunRecord (for
        tools/report.py's "== fleet ==" table)."""
        from consensusclustr_tpu.utils.backend import default_backend

        return RunRecord.from_tracer(
            self.tracer, config=config, backend=default_backend(),
            include_global_metrics=False,
        )

    def fleet_record(self, config=None):
        """Merge this router's record, every replica's (live and retired)
        record, and the retained hop chains into one schema-v11
        :class:`~consensusclustr_tpu.obs.fleetobs.FleetRecord` — the fleet
        incident artifact tools/timeline.py and the Perfetto fleet export
        render."""
        from consensusclustr_tpu.obs.fleetobs import FleetRecord

        return FleetRecord.from_router(self, config=config)
