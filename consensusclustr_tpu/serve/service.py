"""AssignmentService: a bounded-queue, micro-batching front end for online
cluster assignment.

Serving mechanics (the TPU-shaped half of the ISSUE 3 tentpole):

  * **bounded request queue** — ``serve_queue_depth`` slots; a full queue
    rejects the submit with :class:`RetryableRejection` (retryable by
    contract: the caller backs off and resubmits, nothing was enqueued).
    Unbounded queues turn overload into unbounded latency; a bounded queue
    turns it into explicit backpressure.
  * **micro-batching** — one worker thread drains whole requests greedily up
    to ``serve_max_batch`` rows and runs them as a single device program.
    Batching amortises dispatch overhead; padding the batch to the next
    power-of-two bucket (serve/assign.resolve_buckets) means XLA compiles
    one executable per bucket, reused across every request size.
  * **warm-up at load** — each bucket shape is dispatched once with zero
    rows before traffic arrives (and the persistent XLA compile cache is
    enabled first, utils/compile_cache), so no request ever pays a compile.
  * **graceful drain** — ``close()`` stops intake, processes everything
    already queued, then joins the worker; pending futures always resolve.

Observability (names registered in obs/schema.py):

  * ``serve_latency_seconds`` histogram — submit→result per request;
  * ``queue_depth`` gauge — queue occupancy at the last submit/dequeue;
  * ``batch_occupancy`` gauge — rows/bucket of the last micro-batch (how
    much of each compiled shape real traffic fills);
  * ``serve_compile`` counter — bucket-shape first dispatches (compiles);
  * ``serve_rejections`` counter — backpressure rejections.

Request lifecycle (ISSUE 7 tentpole): every accepted request carries a
monotonically issued ``req_id`` and four timestamps — submit (enqueue),
worker dequeue, batch dispatch, batch complete — decomposed into three
histograms whose per-request sum equals ``serve_latency_seconds`` exactly
(same clock reads, no independent measurement):

  * ``queue_wait_seconds``  — submit → dequeue (time in the bounded queue);
  * ``batch_wait_seconds``  — dequeue → dispatch (batch-formation wait,
    including host-side concatenation);
  * ``device_seconds``      — dispatch → results on host (device + transfer;
    one value per batch, observed once per request so counts line up).

Each micro-batch additionally closes a ``serve_batch`` span (worker thread;
the tracer's span stacks are thread-local) carrying the batch's request-id
list, bucket, rows and queue-age-at-dispatch attrs, and each accepted submit
emits a ``serve_request`` instant event — obs/export.py turns the pair into
Perfetto flow events so a request's submit instant visually links to the
batch span that served it. Per-request records stop after
``LIFECYCLE_RECORD_CAP`` requests (histograms and counters continue
unbounded — only the trace-visualization stream is capped, docs/quirks.md).
The same decomposition rides each result as ``AssignResult.timing`` so
clients (tools/loadgen.py) can parity-check the sum without scraping.

Scrape endpoint (ISSUE 4): when ``serve_metrics_port`` /
``CCTPU_SERVE_METRICS_PORT`` names a port (0 = ephemeral; default OFF), a
stdlib ``http.server`` daemon thread serves ``/metrics`` (Prometheus text via
``MetricsRegistry.to_prom_text`` — latency quantiles come from the bucketed
``serve_latency_seconds`` histogram) and ``/healthz`` (queue depth, in-flight
count, drain state as JSON) on localhost. The exporter starts with
``start()``, survives the drain, and closes with ``close()``.

Resource profiling (ISSUE 6): when ``resource_sample_ms`` /
``ClusterConfig.resource_sample_ms`` / ``CCTPU_RESOURCE_SAMPLE_MS`` names an
interval (default OFF), an obs/resource.py ``ResourceSampler`` attached to
the service tracer samples host RSS + device memory for the service's whole
lifetime — it starts with ``start()``, keeps ticking through the drain (a
scrape mid-shutdown sees live ``host_rss_bytes`` / ``host_peak_rss_bytes``
gauges on ``/metrics``), and stops last in ``close()`` so the final sample
is the service's closing watermark.

Resilience (ISSUE 10): warm-up and micro-batch device execution are fault
sites (``serve_warmup`` / ``serve_batch``, obs/schema.py::FAULT_SITES)
wrapped in the bounded-backoff retry policy — a transient dispatch failure
re-runs the pure batch function bit-identically; exhaustion falls through to
*poisoned-batch isolation* (only that batch's futures fail, everything else
keeps serving). The worker thread itself is supervised: an unexpected death
(``serve_worker`` site) increments ``serve_worker_restarts``, emits a
``serve_worker_restart`` event, and restarts the loop over the SAME pending
deque so no accepted request is stranded; past the restart limit
(``CCTPU_SERVE_WORKER_RESTARTS``, default 16) the service fails everything
pending loudly rather than crash-loop. Rejections carry a ``retry_after_s``
hint derived from the observed batch drain rate (see
:class:`RetryableRejection`).

Knob resolution follows the package's env-override pattern
(parallel/pipelined.pipeline_depth): explicit argument >
``ClusterConfig.serve_*`` field > ``CCTPU_SERVE_*`` env var > default.
Defaults are documented in docs/quirks.md.
"""

from __future__ import annotations

import itertools
import os
import queue
import threading
import time
from concurrent.futures import Future
from typing import Optional, Sequence, Tuple

import numpy as np

from consensusclustr_tpu.obs import RunRecord, Tracer
from consensusclustr_tpu.serve.artifact import ReferenceArtifact
from consensusclustr_tpu.serve.assign import (
    AssignResult,
    CompileTracker,
    DEFAULT_K,
    DEFAULT_SNAP_EPS,
    _labels_from_codes,
    assign_bucketed,
    bucket_for,
    resolve_buckets,
    resolve_max_batch,
    subset_to_hvg,
)

DEFAULT_QUEUE_DEPTH = 64

# Per-request trace records (serve_request events + serve_batch spans) stop
# after this many requests so a long-lived service's tracer stays bounded;
# the lifecycle histograms and counters keep going forever (docs/quirks.md).
LIFECYCLE_RECORD_CAP = 100_000

# Supervision (ISSUE 10): how many unexpected worker deaths the supervisor
# absorbs before declaring the service dead (failing everything pending and
# refusing new submits). A restart preserves the pending deque — no accepted
# request is lost to a worker crash. CCTPU_SERVE_WORKER_RESTARTS overrides.
DEFAULT_WORKER_RESTART_LIMIT = 16

# Completed-batch window the retry_after_s hint derives from: enough batches
# to smooth one noisy dispatch, small enough to track a regime change.
_DRAIN_WINDOW = 32

_SENTINEL = None


class RetryableRejection(RuntimeError):
    """Queue-full backpressure: nothing was enqueued; back off and retry.

    ``retry_after_s`` (ISSUE 10) is the service's own backoff hint — the
    current queue depth divided by the drain rate observed over the last few
    completed batches, i.e. roughly when a queue slot should free up. None
    until the service has completed enough batches to know its rate. Purely
    advisory: tools/loadgen.py records it but never acts on it (the
    generator stays open-loop by design)."""

    def __init__(self, message: str = "", retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


def serve_queue_depth(requested: Optional[int] = None) -> int:
    """Explicit arg > $CCTPU_SERVE_QUEUE_DEPTH > 64 (see docs/quirks.md)."""
    if requested is None:
        requested = int(
            os.environ.get("CCTPU_SERVE_QUEUE_DEPTH", DEFAULT_QUEUE_DEPTH)
        )
    v = int(requested)
    if v < 1:
        raise ValueError(f"serve_queue_depth must be >= 1; got {v}")
    return v


def worker_restart_limit(requested: Optional[int] = None) -> int:
    """Explicit arg > $CCTPU_SERVE_WORKER_RESTARTS > 16."""
    if requested is None:
        requested = int(
            os.environ.get(
                "CCTPU_SERVE_WORKER_RESTARTS", DEFAULT_WORKER_RESTART_LIMIT
            )
        )
    v = int(requested)
    if v < 0:
        raise ValueError(f"worker restart limit must be >= 0; got {v}")
    return v


def serve_metrics_port(requested: Optional[int] = None) -> Optional[int]:
    """Explicit arg > $CCTPU_SERVE_METRICS_PORT > off (None).

    None means "do not open a socket" — the scrape endpoint is strictly
    opt-in (docs/quirks.md). 0 binds an ephemeral port (read it back from
    ``AssignmentService.metrics_port``).
    """
    if requested is None:
        env = os.environ.get("CCTPU_SERVE_METRICS_PORT", "").strip().lower()
        if env in ("", "off", "none"):
            return None
        requested = env
    v = int(requested)
    if not (0 <= v <= 65535):
        raise ValueError(
            f"serve_metrics_port must be in [0, 65535] (0 = ephemeral); got {v}"
        )
    return v


class _MetricsHTTPServer:
    """Stdlib-only /metrics (Prometheus text) + /healthz (JSON) exporter.

    One daemon thread around ``http.server.ThreadingHTTPServer``, bound to
    localhost only — operators front it with their own ingress. Handlers read
    live service state (the registry snapshot is lock-guarded); nothing here
    touches the device, so a scrape can never stall the worker loop.
    """

    def __init__(self, service: "AssignmentService", port: int) -> None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        svc = service

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args) -> None:  # quiet: obs, not stderr
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self) -> None:
                from consensusclustr_tpu.obs.export import PROM_CONTENT_TYPE

                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        self._send(
                            200, svc.metrics.to_prom_text().encode(),
                            PROM_CONTENT_TYPE,
                        )
                    elif path == "/healthz":
                        import json as _json

                        self._send(
                            200, (_json.dumps(svc.health()) + "\n").encode(),
                            "application/json",
                        )
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except BrokenPipeError:
                    pass

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="cctpu-metrics-http", daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join()


class _Request:
    __slots__ = (
        "counts_hvg", "mode", "future", "req_id",
        "t_submit", "t_dequeue", "rows", "trace", "t_enter",
    )

    def __init__(
        self, counts_hvg: np.ndarray, mode: str, req_id: int,
        trace: Optional[dict] = None,
        t_enter: Optional[float] = None,
    ) -> None:
        self.counts_hvg = counts_hvg
        self.mode = mode
        self.future: Future = Future()
        self.req_id = req_id
        self.t_submit = time.perf_counter()   # enqueue instant
        # submit()-call entry (before HVG subsetting): the client-observed
        # start the ISSUE 19 hop chain measures from — a fleet hop is
        # stamped immediately before the submit call, so resolved_s from
        # here makes the hop-parity identity exact (no unattributed
        # pre-enqueue host work)
        self.t_enter = t_enter if t_enter is not None else self.t_submit
        self.t_dequeue: Optional[float] = None  # worker pop (queue_wait end)
        self.rows = int(counts_hvg.shape[0])
        # fleet trace context (ISSUE 19): the router-minted hop dict —
        # carries trace_id/hop in, gets this replica's req_id stamped back
        self.trace = trace


class AssignmentService:
    """Micro-batched online assignment against one ReferenceArtifact.

    Usage::

        with AssignmentService(artifact) as svc:
            fut = svc.submit(query_counts)          # -> concurrent Future
            result = fut.result()                   # AssignResult
            result = svc.assign(query_counts)       # sync convenience

    Thread model: submits may come from any thread; all device work runs on
    the single worker thread (the package's host control is single-threaded
    by design, SURVEY §7.1 — one worker keeps that true for serving too).
    """

    def __init__(
        self,
        reference: ReferenceArtifact,
        *,
        config=None,
        queue_depth: Optional[int] = None,
        max_batch: Optional[int] = None,
        buckets: Optional[Sequence[int]] = None,
        k: int = DEFAULT_K,
        mode: str = "robust",
        snap_eps: float = DEFAULT_SNAP_EPS,
        warmup: bool = True,
        start: bool = True,
        tracer: Optional[Tracer] = None,
        metrics_port: Optional[int] = None,
        resource_sample_ms: Optional[int] = None,
        retry_attempts: Optional[int] = None,
        replica_name: str = "",
    ) -> None:
        if mode not in ("robust", "granular"):
            raise ValueError(f"mode must be 'robust' or 'granular'; got {mode!r}")
        self.reference = reference
        cfg = config
        self.queue_depth = serve_queue_depth(
            queue_depth
            if queue_depth is not None
            else getattr(cfg, "serve_queue_depth", None)
        )
        self.max_batch = resolve_max_batch(
            max_batch
            if max_batch is not None
            else getattr(cfg, "serve_max_batch", None)
        )
        self.buckets: Tuple[int, ...] = resolve_buckets(
            buckets
            if buckets is not None
            else getattr(cfg, "serve_buckets", None),
            self.max_batch,
        )
        self.k = int(k)
        self.mode = mode
        self.snap_eps = float(snap_eps)
        self.tracer = tracer if tracer is not None else Tracer()
        self.metrics = self.tracer.metrics
        # Failure observability (ISSUE 14): flight recorder rings on the
        # service tracer (dumps on _fail_all / crash), the SLO alert engine
        # evaluated once per micro-batch and on every health() scrape —
        # /healthz carries alerts_active + last_alert so a router can drain
        # a sick replica (ROADMAP O3). CCTPU_NO_FLIGHT=1 disarms the
        # recorder + watchdog; the alert engine is passive arithmetic.
        from consensusclustr_tpu.obs.alerts import attach_alerts
        from consensusclustr_tpu.obs.flight import attach_flight

        attach_flight(self.tracer)
        self._alerts = attach_alerts(self.tracer)
        self._stall_floor_s = getattr(cfg, "stall_floor_s", None)
        # ISSUE 18 (fleet): the adaptive-control surface. An armed
        # ControlPolicy (serve/control.py) sets these through the router;
        # the defaults reproduce the pre-fleet worker exactly — no timed
        # gather, no row cap (the off-is-free pin in tests/test_fleet.py).
        # replica_name is stamped by FleetRouter — at CONSTRUCTION when the
        # router spawns the replica (a worker with a permanent fault can
        # _fail_all before the ctor even returns, and the post-mortem must
        # still name the dead replica) or post-hoc for adopted services.
        self.batch_deadline_s: float = 0.0
        self.batch_rows_cap: Optional[int] = None
        self.replica_name: str = str(replica_name)
        self._tracker = CompileTracker()
        self._queue: "queue.Queue" = queue.Queue(maxsize=self.queue_depth)
        self._thread: Optional[threading.Thread] = None
        self._closing = False
        self._closed = False
        # Resilience (ISSUE 10): bounded retries around warm-up and
        # micro-batch device execution, and a supervised worker — requests
        # pulled off the queue live in self._pending so a worker restart
        # resumes them instead of stranding their futures.
        from collections import deque as _deque

        from consensusclustr_tpu.resilience.retry import resolve_retry_policy

        self._retry = resolve_retry_policy(
            retry_attempts
            if retry_attempts is not None
            else getattr(cfg, "retry_attempts", None)
        )
        self._pending: "_deque[_Request]" = _deque()
        self._drained = False
        self._worker_restarts = 0
        self._restart_limit = worker_restart_limit()
        self._drain_window: "_deque[Tuple[float, int]]" = _deque(
            maxlen=_DRAIN_WINDOW
        )
        self._metrics_port_req = serve_metrics_port(
            metrics_port
            if metrics_port is not None
            else getattr(cfg, "serve_metrics_port", None)
        )
        self._http: Optional[_MetricsHTTPServer] = None
        self.metrics_port: Optional[int] = None  # bound port once started
        # Resource sampler (obs/resource.py): inert when the resolved
        # interval is 0 (the default) — no thread, no samples, no gauges.
        from consensusclustr_tpu.obs.resource import ResourceSampler

        self.resource_sampler = ResourceSampler(
            resource_sample_ms
            if resource_sample_ms is not None
            else getattr(cfg, "resource_sample_ms", None),
            epoch=self.tracer.epoch,
        )
        if self.resource_sampler.enabled:
            self.resource_sampler.attach(self.tracer)
        self._accepted = 0
        self._completed = 0
        # monotonically issued request ids (next() is GIL-atomic; submits may
        # come from any thread)
        self._req_ids = itertools.count(1)
        if warmup:
            self.warmup()
        if start:
            self.start()

    # -- lifecycle -----------------------------------------------------------

    def warmup(self) -> None:
        """Ready every bucket shape before traffic arrives.

        Calls utils/compile_cache.enable_persistent_cache unconditionally
        (idempotent; ISSUE 3 satellite), then per bucket (ISSUE 13): try the
        cross-process AOT executable cache first — a hit deserializes the
        fully compiled program (zero traces, the warm start) — else compile
        it ahead of time and serialize it back for the next process. Either
        way the executable lands in the serve/assign registry, and one
        all-zero batch per bucket is pushed through the real assign path.
        ``CCTPU_NO_AOT_CACHE`` disables the disk cache (in-process AOT
        compile + registry still run); a present-but-unloadable entry warns
        and falls back to trace (utils/compile_cache.aot_load).
        """
        from consensusclustr_tpu.utils.compile_cache import (
            aot_key,
            aot_load,
            aot_save,
            enable_persistent_cache,
        )

        from consensusclustr_tpu.resilience.inject import SERVE_WARMUP_SITE
        from consensusclustr_tpu.resilience.retry import retry_call
        from consensusclustr_tpu.serve.assign import (
            aot_executable_for,
            artifact_sha,
            prepare_assign_executable,
            register_aot_executable,
        )

        enable_persistent_cache()
        g = self.reference.n_hvg
        n_classes = len(self.reference.leaf_table)
        use_disk = not os.environ.get("CCTPU_NO_AOT_CACHE")
        sha = artifact_sha(self.reference)
        aot_hits = aot_saved = 0
        with self.tracer.span(
            "serve_warmup", buckets=list(self.buckets), n_hvg=g
        ) as sp:
            for b in self.buckets:
                if aot_executable_for(
                    self.reference, b, g, self.k, n_classes
                ) is None:
                    key = aot_key(
                        sha, b, genes=g, k=int(self.k), n_classes=n_classes
                    )
                    exe = aot_load(key) if use_disk else None
                    if exe is not None:
                        aot_hits += 1
                    else:
                        try:
                            exe = prepare_assign_executable(
                                self.reference, b, k=self.k,
                                snap_eps=self.snap_eps,
                            )
                        except Exception:  # graftlint: noqa[GL007] AOT warm-up probe: failure falls back to the jit path and shows up in the aot_fallbacks counter
                            exe = None  # the jit path below still compiles it
                        if exe is not None and use_disk and aot_save(key, exe):
                            aot_saved += 1
                    if exe is not None:
                        register_aot_executable(
                            self.reference, b, g, self.k, n_classes, exe
                        )
                # per-bucket warm-up dispatch under the retry policy: a
                # transient compile/dispatch failure must not abort the
                # whole service load
                codes, _, _, _ = retry_call(
                    lambda b=b: assign_bucketed(
                        self.reference,
                        np.zeros((b, g), np.float32),
                        k=self.k,
                        buckets=(b,),
                        snap_eps=self.snap_eps,
                        metrics=self.metrics,
                        compile_tracker=self._tracker,
                    ),
                    site=SERVE_WARMUP_SITE, policy=self._retry,
                    metrics=self.metrics, log=self.tracer,
                )
                assert codes.shape == (b,)
            sp.set(
                compiles=self._tracker.count,
                aot_hits=aot_hits,
                aot_saved=aot_saved,
            )
        self.tracer.event(
            "aot_warm_start",
            hits=aot_hits, saved=aot_saved, buckets=list(self.buckets),
            disk=bool(use_disk),
        )

    def start(self) -> None:
        if self._closed:
            raise RuntimeError("AssignmentService already closed")
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._worker, name="cctpu-assign-service", daemon=True
            )
            self._thread.start()
            self.tracer.event(
                "serve_start",
                queue_depth=self.queue_depth,
                max_batch=self.max_batch,
                buckets=list(self.buckets),
            )
        if self._metrics_port_req is not None and self._http is None:
            self._http = _MetricsHTTPServer(self, self._metrics_port_req)
            self.metrics_port = self._http.port
            self.tracer.event("serve_metrics", port=self.metrics_port)
        self.resource_sampler.start()  # no-op when sampling is off

    def close(self) -> None:
        """Stop intake, drain everything queued, join the worker."""
        if self._closed:
            return
        self._closing = True
        if self._thread is not None:
            self._queue.put(_SENTINEL)  # lands after all accepted requests
            self._thread.join()
            self._thread = None
        else:
            # never started: fail queued futures rather than strand callers
            while True:
                try:
                    req = self._queue.get_nowait()
                except queue.Empty:
                    break
                if req is not _SENTINEL:
                    req.future.set_exception(
                        RuntimeError("AssignmentService closed before start")
                    )
        self._closed = True
        self.tracer.event("serve_drain")
        # the exporter outlives the drain (a scrape during shutdown must see
        # final numbers), then closes with the service
        if self._http is not None:
            self._http.close()
            self._http = None
        # the sampler outlives both the drain AND the exporter: its closing
        # sample is the service's final memory watermark
        self.resource_sampler.stop()

    def __enter__(self) -> "AssignmentService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- client side ---------------------------------------------------------

    def submit(
        self, counts, mode: Optional[str] = None,
        trace: Optional[dict] = None,
    ) -> Future:
        """Enqueue one request; returns a Future of AssignResult.

        Raises :class:`RetryableRejection` when the queue is full (nothing
        enqueued — back off and retry) and ValueError for batches larger
        than ``serve_max_batch`` (split them client-side).

        ``trace`` (ISSUE 19) is the FleetRouter's hop dict for this
        admission — a mutable contract: the router supplies
        ``trace_id``/``hop``/``replica``, this service stamps ``req_id``
        back into it once the request is actually accepted (a rejected
        submit leaves it unstamped), and the id pair rides the
        ``serve_request`` event, the ``serve_batch`` span and
        ``AssignResult.timing`` so one fleet-scoped identity links the
        per-replica fragments.
        """
        t_enter = time.perf_counter()
        if self._closing or self._closed:
            raise RuntimeError("AssignmentService is shut down")
        mode = self.mode if mode is None else mode
        if mode not in ("robust", "granular"):
            raise ValueError(f"mode must be 'robust' or 'granular'; got {mode!r}")
        counts_hvg = subset_to_hvg(self.reference, counts)
        if counts_hvg.shape[0] > self.max_batch:
            raise ValueError(
                f"request of {counts_hvg.shape[0]} rows exceeds "
                f"serve_max_batch={self.max_batch}; split it client-side"
            )
        req = _Request(
            counts_hvg, mode, next(self._req_ids), trace=trace,
            t_enter=t_enter,
        )
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            self.metrics.counter("serve_rejections").inc()
            hint = self.retry_after_hint()
            raise RetryableRejection(
                f"queue full ({self.queue_depth} requests in flight); retry"
                + (f" after ~{hint}s" if hint is not None else ""),
                retry_after_s=hint,
            ) from None
        self._accepted += 1
        if trace is not None:
            # accepted: stamp this replica's req_id into the router's hop
            # record — the trace_id <-> req_id join key for merged traces
            trace["req_id"] = req.req_id
            # refine the hop's route stamp to THIS submit call's entry
            # clock read (the router stamped it just before calling us):
            # resolved_s below measures from the same t_enter, so the
            # hop-parity identity carries no unattributed gap
            t0h = trace.pop("_t0", None)
            if t0h is not None:
                trace["t"] = round(t_enter - t0h, 6)
        self.metrics.gauge("queue_depth").set(self._queue.qsize())
        if req.req_id <= LIFECYCLE_RECORD_CAP:
            # the request's flow-event anchor: obs/export.py links this
            # instant to the serve_batch span that carries req_id
            if trace is not None:
                self.tracer.event(
                    "serve_request", req_id=req.req_id, rows=req.rows,
                    trace_id=trace.get("trace_id"),
                )
            else:
                self.tracer.event(
                    "serve_request", req_id=req.req_id, rows=req.rows
                )
        return req.future

    def assign(self, counts, mode: Optional[str] = None, timeout=None) -> AssignResult:
        """Synchronous submit + wait."""
        return self.submit(counts, mode=mode).result(timeout=timeout)

    # -- worker side ---------------------------------------------------------

    def _worker(self) -> None:
        """Supervised worker (ISSUE 10): ``_loop`` does the serving; an
        unexpected death (anything escaping the per-batch isolation — a bug
        in the loop scaffolding, an injected ``serve_worker`` fault) is
        counted, evented, and the loop restarts over the SAME pending deque,
        so no accepted request's future is lost to a crash. Past the restart
        limit the supervisor gives up loudly: everything pending or queued
        fails, intake closes."""
        while True:
            try:
                self._loop()
                return  # clean exit: drained after close()
            except BaseException as e:
                if self._closed:
                    return
                self._worker_restarts += 1
                self.metrics.counter("serve_worker_restarts").inc()
                self.tracer.event(
                    "serve_worker_restart",
                    error=type(e).__name__,
                    restarts=self._worker_restarts,
                )
                if self._worker_restarts > self._restart_limit:
                    self._fail_all(
                        RuntimeError(
                            f"serve worker exceeded restart limit "
                            f"({self._restart_limit}); last error: "
                            f"{type(e).__name__}: {e}"
                        )
                    )
                    return

    def _fail_all(self, err: BaseException) -> None:
        """Give-up path: close intake and fail every pending/queued future
        rather than strand callers on a dead worker. Dumps the flight
        recorder first — this is the serving layer's black-box moment: the
        dump's tail events carry the worker-restart trail that led here."""
        from consensusclustr_tpu.obs.flight import (
            FAIL_ALL_FLIGHT,
            dump_on_failure,
        )

        dump_on_failure(
            FAIL_ALL_FLIGHT, log=self.tracer,
            error=type(err).__name__, message=str(err)[:500],
            worker_restarts=self._worker_restarts,
            replica=self.replica_name,
        )
        self._closing = True
        while self._pending:
            req = self._pending.popleft()
            if not req.future.done():
                req.future.set_exception(err)
                self._completed += 1
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is not _SENTINEL and not req.future.done():
                req.future.set_exception(err)
                self._completed += 1

    def _loop(self) -> None:
        from consensusclustr_tpu.resilience.inject import (
            SERVE_WORKER_SITE,
            maybe_fail,
        )

        pending = self._pending  # survives worker restarts (supervision)
        while True:
            # fault site: the worker loop itself — a planted fault here
            # lands OUTSIDE the per-batch isolation, so it exercises the
            # supervisor's restart path (no request may be lost)
            maybe_fail(SERVE_WORKER_SITE, self.metrics)
            if not pending:
                if self._drained:
                    return
                item = self._queue.get()
                if item is _SENTINEL:
                    return
                item.t_dequeue = time.perf_counter()  # queue_wait ends here
                pending.append(item)
            # opportunistic non-blocking drain: batch whatever has piled up
            while not self._drained:
                try:
                    item = self._queue.get_nowait()
                except queue.Empty:
                    break
                if item is _SENTINEL:
                    self._drained = True
                    break
                item.t_dequeue = time.perf_counter()
                pending.append(item)
            self.metrics.gauge("queue_depth").set(self._queue.qsize())
            # ISSUE 18 control surface: an armed ControlPolicy may set a
            # bounded gather deadline (wait briefly for fuller batches) and
            # a per-micro-batch row cap (smaller pad buckets under latency
            # pressure). The defaults — 0.0 / None — skip both branches, so
            # the disarmed worker is the pre-fleet worker verbatim.
            cap = min(int(self.batch_rows_cap or self.max_batch),
                      self.max_batch)
            deadline_s = self.batch_deadline_s
            if deadline_s > 0.0 and not self._drained:
                have = sum(r.rows for r in pending)
                t_end = time.perf_counter() + deadline_s
                while have < cap:
                    remaining = t_end - time.perf_counter()
                    if remaining <= 0.0:
                        break
                    try:
                        item = self._queue.get(timeout=remaining)
                    except queue.Empty:
                        break
                    if item is _SENTINEL:
                        self._drained = True
                        break
                    item.t_dequeue = time.perf_counter()
                    pending.append(item)
                    have += item.rows
                self.metrics.gauge("queue_depth").set(self._queue.qsize())
            batch, rows = [], 0
            # ``not batch or`` guarantees progress when a request alone
            # exceeds a control row cap (submit() already bounds rows to
            # max_batch, so with cap == max_batch this is the old condition)
            while pending and (not batch or rows + pending[0].rows <= cap):
                req = pending.popleft()
                batch.append(req)
                rows += req.rows
            self._run_batch(batch, rows)

    def _batch_span(self, batch, rows: int):
        """serve_batch span for this micro-batch — or an inert detached span
        once LIFECYCLE_RECORD_CAP batches of records have accumulated, so a
        long-lived service's tracer stays bounded (histograms continue)."""
        from consensusclustr_tpu.obs.tracer import _null_span

        attrs = dict(
            request_ids=[r.req_id for r in batch],
            n_requests=len(batch),
            rows=rows,
        )
        trace_ids = [
            r.trace["trace_id"] for r in batch
            if r.trace is not None and "trace_id" in r.trace
        ]
        if trace_ids:
            attrs["trace_ids"] = trace_ids
        if batch[0].req_id > LIFECYCLE_RECORD_CAP:
            return _null_span("serve_batch", **attrs)
        return self.tracer.span("serve_batch", **attrs)

    def _run_batch(self, batch, rows: int) -> None:
        # Per-batch stall deadline (ISSUE 14): armed only while a batch is
        # actually in flight (an idle service parks nothing on the
        # watchdog), tuned from the live serve_latency_seconds histogram
        # with the 120 s floor — the tunnel's own kill horizon. Expiry
        # dumps all-thread stacks; it never kills the batch.
        from consensusclustr_tpu.obs.flight import stall_watch

        with self._batch_span(batch, rows) as sp, stall_watch(
            self.tracer, "serve_batch",
            hist=self.metrics.histograms.get("serve_latency_seconds"),
            floor_s=self._stall_floor_s,
        ):
            try:
                bucket = bucket_for(rows, self.buckets)
                self.metrics.gauge("batch_occupancy").set(rows / bucket)
                counts = (
                    batch[0].counts_hvg
                    if len(batch) == 1
                    else np.concatenate([r.counts_hvg for r in batch], axis=0)
                )
                # batch formation (incl. the concat above) ends, device
                # work begins: the batch_wait / device_seconds boundary
                t_dispatch = time.perf_counter()
                ages = [t_dispatch - r.t_submit for r in batch]
                sp.set(
                    bucket=bucket,
                    queue_age_max_s=round(max(ages), 6),
                    queue_age_mean_s=round(sum(ages) / len(ages), 6),
                )
                # micro-batch device execution under the retry policy
                # (ISSUE 10): a transient failure re-dispatches (pure
                # function of the batch — bit-identical on the retried
                # attempt); exhaustion falls through to the poisoned-batch
                # isolation below, failing only THIS batch's futures.
                from consensusclustr_tpu.resilience.inject import (
                    SERVE_BATCH_SITE,
                )
                from consensusclustr_tpu.resilience.retry import retry_call

                codes, frac, stab, dist = retry_call(
                    lambda: assign_bucketed(
                        self.reference, counts, k=self.k, buckets=self.buckets,
                        snap_eps=self.snap_eps, metrics=self.metrics,
                        compile_tracker=self._tracker,
                    ),
                    site=SERVE_BATCH_SITE, policy=self._retry,
                    metrics=self.metrics, log=self.tracer,
                )
                t_done = time.perf_counter()
                device_s = t_done - t_dispatch
                s = 0
                for req in batch:
                    e = s + req.rows
                    labels, levels = _labels_from_codes(
                        self.reference, codes[s:e], req.mode == "granular"
                    )
                    # the decomposition: three disjoint intervals over the
                    # same clock, so their sum IS the end-to-end latency
                    t_deq = req.t_dequeue if req.t_dequeue is not None \
                        else req.t_submit
                    queue_wait = t_deq - req.t_submit
                    batch_wait = t_dispatch - t_deq
                    latency = t_done - req.t_submit
                    result = AssignResult(
                        labels=labels,
                        confidence=frac[s:e],
                        neighbor_stability=stab[s:e],
                        nearest_distance=dist[s:e],
                        levels=levels,
                        timing={
                            "req_id": req.req_id,
                            "queue_wait_s": queue_wait,
                            "batch_wait_s": batch_wait,
                            "device_s": device_s,
                            "latency_s": latency,
                            "bucket": bucket,
                            "batch_rows": rows,
                            "batch_requests": len(batch),
                            # fleet trace context when routed (ISSUE 19);
                            # the router replaces these with the full hop
                            # chain under timing["trace"] on completion
                            **(
                                {
                                    "trace_id": req.trace.get("trace_id"),
                                    "hop": req.trace.get("hop"),
                                }
                                if req.trace is not None
                                else {}
                            ),
                        },
                    )
                    self.metrics.histogram("serve_latency_seconds").observe(
                        latency
                    )
                    self.metrics.histogram("queue_wait_seconds").observe(
                        queue_wait
                    )
                    self.metrics.histogram("batch_wait_seconds").observe(
                        batch_wait
                    )
                    self.metrics.histogram("device_seconds").observe(device_s)
                    # submit-entry -> resolution wall, stamped LAST: unlike
                    # latency_s (which runs t_submit -> the shared t_done so
                    # the three-interval decomposition stays exact), this
                    # covers HVG subsetting before the enqueue AND the
                    # per-request host assembly above — what a caller of
                    # submit() actually observes (ISSUE 19 hop parity)
                    t_res = time.perf_counter()
                    result.timing["resolved_s"] = t_res - req.t_enter
                    if req.trace is not None:
                        # absolute resolution instant for the router's
                        # _finish_trace (same process, same perf_counter
                        # clock) — popped there, never serialized
                        result.timing["_t_resolved"] = t_res
                    req.future.set_result(result)
                    self._completed += 1
                    s = e
            except BaseException as e:  # fail the whole batch, keep serving  # graftlint: noqa[GL007] failure recorded on the span and propagated to every request future
                sp.set(failed=True, error=type(e).__name__)
                for req in batch:
                    if not req.future.done():
                        req.future.set_exception(e)
                        self._completed += 1
            finally:
                # drain-rate observation (retry_after_s hint): a batch —
                # served or failed — freed its queue slots at this instant
                self._drain_window.append((time.perf_counter(), len(batch)))
                if self._alerts is not None:
                    self._alerts.evaluate()  # never raises

    # -- introspection -------------------------------------------------------

    def retry_after_hint(self) -> Optional[float]:
        """Advisory backoff for a rejected submit: current queue occupancy
        over the request drain rate observed across the last completed
        batches — roughly when a slot should free. None until at least two
        batches have completed (no rate to observe). Lock-free: the window
        is appended by the worker only; a racy read costs at most one stale
        batch."""
        window = list(self._drain_window)
        if len(window) < 2:
            return None
        span = window[-1][0] - window[0][0]
        served = sum(n for _, n in window[1:])
        if span <= 0.0 or served <= 0:
            return None
        rate = served / span
        waiting = self._queue.qsize() + 1  # +1: the rejected request itself
        return round(min(max(waiting / rate, 0.001), 30.0), 4)

    @property
    def in_flight(self) -> int:
        """Requests accepted but not yet resolved — the cheap live load
        signal FleetRouter reads on every admission (plain counter
        subtraction; the full :meth:`health` scrape evaluates alert rules
        and is paced to a TTL on the router's hot path)."""
        return self._accepted - self._completed

    @property
    def worker_restarts(self) -> int:
        return self._worker_restarts

    @property
    def bucket_compiles(self) -> int:
        return self._tracker.count

    def stats(self) -> dict:
        return self.metrics.snapshot()

    def health(self) -> dict:
        """Liveness/drain snapshot (the /healthz body): queue depth, requests
        in flight, the compiled-shape inventory, and — the ROADMAP O3
        routing signal (ISSUE 14) — the live SLO alert state: a scrape-time
        evaluation so ``alerts_active``/``last_alert`` reflect NOW, not the
        last batch."""
        status = (
            "closed" if self._closed else "draining" if self._closing else "ok"
        )
        alerts_active: dict = {}
        last_alert = None
        if self._alerts is not None:
            alerts_active = self._alerts.evaluate()  # never raises
            last_alert = self._alerts.last_alert
        return {
            "status": status,
            "queue_depth": self._queue.qsize(),
            "in_flight": self._accepted - self._completed,
            "accepted": self._accepted,
            "completed": self._completed,
            "max_batch": self.max_batch,
            "buckets": list(self.buckets),
            "bucket_compiles": self.bucket_compiles,
            "worker_restarts": self._worker_restarts,
            "alerts_active": sorted(alerts_active),
            "last_alert": dict(last_alert) if last_alert else None,
        }

    def run_record(self, config=None) -> RunRecord:
        """Snapshot the service's spans/metrics as a RunRecord (for
        tools/report.py's "== serving ==" table)."""
        from consensusclustr_tpu.utils.backend import default_backend

        return RunRecord.from_tracer(
            self.tracer, config=config, backend=default_backend(),
            include_global_metrics=False,
        )
