"""Versioned reference-model artifacts: export a fitted consensus clustering
as a servable bundle.

A ``ReferenceArtifact`` freezes the minimal state a query cell needs to be
mapped onto a fitted reference (the Azimuth/scArches "frozen reference"
contract): the HVG gene subset, the serving normalization constants, the PCA
components with their centring/scaling statistics, the reference cell×PC
embedding, per-level consensus labels, and per-cluster bootstrap stability.

On disk a bundle is a directory of two files:

    <path>/arrays.npz      every array, saved uncompressed (bit-exact round trip)
    <path>/manifest.json   schema version, sha256 of arrays.npz, label tables,
                           shape summary, config fingerprint

Loading fails LOUDLY on an unknown schema version (``ArtifactSchemaError``)
or a checksum mismatch (``ArtifactChecksumError``) — a serving process must
never silently assign against a half-written or incompatible model.

Frozen-normalization semantics (documented deviation from the offline fit):
the offline pipeline computes *deconvolution* size factors, which need the
whole cohort; a query cell arrives alone. Serving therefore freezes the
library-size ratio rule ``sf = rowsum(counts_hvg) / libsize_mean`` (the
reference cohort's mean HVG library size), and ``export`` re-embeds the
reference's own cells through that exact frozen path — so reference and
query geometry agree by construction, and a reference cell re-submitted as a
query lands on (numerically at) its own stored embedding point. Labels are
never recomputed; they are the offline consensus assignments.

This module is jax-free at import: artifact IO runs anywhere (report hosts,
CI) without touching a backend.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
from typing import Any, List, Optional, Tuple

import numpy as np

SERVE_SCHEMA_VERSION = 1
KNOWN_SCHEMAS = (1,)

_ARRAYS = "arrays.npz"
_MANIFEST = "manifest.json"


class ArtifactError(RuntimeError):
    """Base class for artifact load/export failures."""


class ArtifactSchemaError(ArtifactError):
    """Manifest declares a schema version this build does not understand."""


class ArtifactChecksumError(ArtifactError):
    """Stored arrays do not match the manifest checksum (corruption/tamper)."""


def leaf_label_table(labels: np.ndarray) -> List[str]:
    """Sorted unique label strings — THE canonical leaf-cluster order.

    Every stability/score array aligned to leaf clusters (api capture,
    artifact arrays, assign results) uses this order; sharing the helper is
    what keeps them aligned.
    """
    return sorted({str(l) for l in np.asarray(labels).tolist()})


def level_tables(labels: np.ndarray) -> Tuple[np.ndarray, List[List[str]]]:
    """Per-level label codes from lineage strings ("2", "2_1", "2_1_3", ...).

    Level ℓ truncates each label to its first ℓ underscore-separated parts; a
    cell whose lineage is shallower than ℓ keeps its full label (its cluster
    simply never split further). Level L (the deepest) therefore reproduces
    the full assignment strings. Returns (codes [L, n] int32, one sorted
    string table per level).
    """
    labels = [str(l) for l in np.asarray(labels).tolist()]
    parts = [l.split("_") for l in labels]
    n_levels = max(len(p) for p in parts)
    codes = np.empty((n_levels, len(labels)), np.int32)
    tables: List[List[str]] = []
    for lvl in range(1, n_levels + 1):
        strs = ["_".join(p[: min(lvl, len(p))]) for p in parts]
        table = sorted(set(strs))
        code_of = {s: i for i, s in enumerate(table)}
        codes[lvl - 1] = [code_of[s] for s in strs]
        tables.append(table)
    return codes, tables


@dataclasses.dataclass
class ReferenceFit:
    """In-memory serving state captured by api.consensus_clust (depth 1).

    ``embedding`` is the reference re-embedded through the FROZEN serving
    path (libsize-ratio size factors → log1p → standardize → project), not
    the offline PCA scores — see the module docstring. Arrays are numpy,
    host-side, small (no counts retained).
    """

    embedding: np.ndarray             # [n, d] float32, frozen-path embedding
    mu: np.ndarray                    # [g_hvg] PCA centring vector
    sigma: np.ndarray                 # [g_hvg] PCA scaling vector
    loadings: np.ndarray              # [g_hvg, d] PCA components
    libsize_mean: float               # mean reference HVG library size
    pc_num: int
    hvg_indices: Optional[np.ndarray] = None   # int64 into the full gene space
    gene_names: Optional[np.ndarray] = None    # HVG-subset gene names
    stability: Optional[np.ndarray] = None     # [C_leaf] per-cluster bootstrap
    #                                            stability, leaf_label_table order
    n_genes_full: Optional[int] = None         # width of the full gene space
    # How the stability diagonal was derived (ISSUE 9): "boot_rand" = the
    # per-boot pairwise-Rand stability matrix diagonal (dense/blockwise
    # regimes), "cocluster_restricted" = mean within-cluster candidate-pair
    # co-clustering rate from the sparse_knn regime's restricted counts.
    # None on legacy captures; recorded in the bundle manifest so a serving
    # operator can tell which estimator a model's confidences come from.
    stability_source: Optional[str] = None


@dataclasses.dataclass
class ReferenceArtifact:
    """A loaded (or about-to-be-saved) reference model."""

    embedding: np.ndarray             # [n, d] float32
    mu: np.ndarray                    # [g] float32
    sigma: np.ndarray                 # [g] float32
    loadings: np.ndarray              # [g, d] float32
    libsize_mean: float
    level_codes: np.ndarray           # [L, n] int32
    level_tables: List[List[str]]     # one sorted string table per level
    stability: np.ndarray             # [C_leaf] float32, leaf-table order
    pc_num: int
    hvg_indices: Optional[np.ndarray] = None
    gene_names: Optional[np.ndarray] = None
    n_genes_full: Optional[int] = None
    stability_source: Optional[str] = None  # see ReferenceFit.stability_source
    manifest: dict = dataclasses.field(default_factory=dict)

    # -- shape views ---------------------------------------------------------

    @property
    def n_cells(self) -> int:
        return int(self.embedding.shape[0])

    @property
    def n_hvg(self) -> int:
        return int(self.mu.shape[0])

    @property
    def n_levels(self) -> int:
        return int(self.level_codes.shape[0])

    @property
    def leaf_codes(self) -> np.ndarray:
        return self.level_codes[-1]

    @property
    def leaf_table(self) -> List[str]:
        return self.level_tables[-1]

    def labels(self, level: Optional[int] = None) -> np.ndarray:
        """Reference label strings at ``level`` (1-based; default = leaf)."""
        lvl = self.n_levels if level is None else int(level)
        if not (1 <= lvl <= self.n_levels):
            raise ValueError(f"level must be in [1, {self.n_levels}]; got {lvl}")
        table = np.asarray(self.level_tables[lvl - 1], dtype=object)
        return table[self.level_codes[lvl - 1]]

    # -- persistence ---------------------------------------------------------

    def _array_payload(self) -> dict:
        payload = {
            "embedding": np.asarray(self.embedding, np.float32),
            "mu": np.asarray(self.mu, np.float32),
            "sigma": np.asarray(self.sigma, np.float32),
            "loadings": np.asarray(self.loadings, np.float32),
            "libsize_mean": np.asarray(self.libsize_mean, np.float32),
            "level_codes": np.asarray(self.level_codes, np.int32),
            "stability": np.asarray(self.stability, np.float32),
            "pc_num": np.asarray(self.pc_num, np.int32),
        }
        if self.hvg_indices is not None:
            payload["hvg_indices"] = np.asarray(self.hvg_indices, np.int64)
        if self.gene_names is not None:
            payload["gene_names"] = np.asarray(self.gene_names, np.str_)
        if self.n_genes_full is not None:
            payload["n_genes_full"] = np.asarray(self.n_genes_full, np.int64)
        return payload

    def save(self, path: str, config: Any = None) -> str:
        """Write the bundle directory; returns ``path``.

        Files land atomically (tmp + os.replace) so a crashed export never
        leaves a loadable-looking half bundle: the manifest — written LAST —
        is what load() requires first.
        """
        os.makedirs(path, exist_ok=True)
        arrays_path = os.path.join(path, _ARRAYS)
        tmp = arrays_path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **self._array_payload())
        os.replace(tmp, arrays_path)
        with open(arrays_path, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()

        # config snapshot/fingerprint via obs.record (jax-free module)
        fingerprint = snapshot = None
        if config is not None:
            from consensusclustr_tpu.obs.record import (
                _config_dict,
                config_fingerprint,
            )

            fingerprint = config_fingerprint(config)
            snapshot = _config_dict(config)

        manifest = {
            "schema": SERVE_SCHEMA_VERSION,
            "checksum_sha256": digest,
            "n_cells": self.n_cells,
            "n_hvg": self.n_hvg,
            "pc_num": int(self.pc_num),
            "n_levels": self.n_levels,
            "n_leaf_clusters": len(self.leaf_table),
            "level_tables": self.level_tables,
            "libsize_mean": float(self.libsize_mean),
            "stability_source": self.stability_source,
            "created_unix": time.time(),  # graftlint: noqa[GL006] deliberate provenance timestamp in the export manifest, never read back into numerics
            "config_fingerprint": fingerprint,
            "config": snapshot,
        }
        tmp = os.path.join(path, _MANIFEST + ".tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
        os.replace(tmp, os.path.join(path, _MANIFEST))
        self.manifest = manifest
        return path

    @classmethod
    def load(cls, path: str) -> "ReferenceArtifact":
        """Validate and load a bundle; fails loudly on schema/checksum."""
        manifest_path = os.path.join(path, _MANIFEST)
        arrays_path = os.path.join(path, _ARRAYS)
        if not os.path.isfile(manifest_path):
            raise ArtifactError(f"{path}: no {_MANIFEST} (not a reference bundle)")
        with open(manifest_path) as f:
            manifest = json.load(f)
        schema = manifest.get("schema")
        if schema not in KNOWN_SCHEMAS:
            raise ArtifactSchemaError(
                f"{path}: artifact schema {schema!r} not supported "
                f"(this build knows {KNOWN_SCHEMAS}); re-export the reference"
            )
        with open(arrays_path, "rb") as f:
            blob = f.read()
        digest = hashlib.sha256(blob).hexdigest()
        expected = manifest.get("checksum_sha256")
        if digest != expected:
            raise ArtifactChecksumError(
                f"{path}: {_ARRAYS} sha256 {digest[:12]}… does not match "
                f"manifest {str(expected)[:12]}… — bundle is corrupted or was "
                "modified after export"
            )
        import io

        with np.load(io.BytesIO(blob)) as z:
            arrays = {k: z[k] for k in z.files}
        return cls(
            embedding=arrays["embedding"],
            mu=arrays["mu"],
            sigma=arrays["sigma"],
            loadings=arrays["loadings"],
            libsize_mean=float(arrays["libsize_mean"]),
            level_codes=arrays["level_codes"],
            level_tables=[list(t) for t in manifest["level_tables"]],
            stability=arrays["stability"],
            pc_num=int(arrays["pc_num"]),
            hvg_indices=arrays.get("hvg_indices"),
            gene_names=arrays.get("gene_names"),
            n_genes_full=(
                int(arrays["n_genes_full"]) if "n_genes_full" in arrays else None
            ),
            stability_source=manifest.get("stability_source"),
            manifest=manifest,
        )


def reference_from_result(result: Any, config: Any = None) -> ReferenceArtifact:
    """Build a ReferenceArtifact from a ClusterResult carrying serving state.

    The fit state (``result.fit``) is captured by ``consensus_clust`` when
    the run had raw counts to freeze a normalization from; pca-only or
    norm-counts-only runs cannot be served and fail here with instructions.
    """
    fit = getattr(result, "fit", None)
    if fit is None:
        raise ArtifactError(
            "this ClusterResult carries no serving state — export needs a run "
            "fitted from raw counts (consensus_clust(counts=...)); pca= / "
            "norm_counts=-only inputs have no normalization to freeze"
        )
    labels = np.asarray(result.assignments)
    codes, tables = level_tables(labels)
    stability = fit.stability
    if stability is None:
        stability = np.ones(len(tables[-1]), np.float32)
    return ReferenceArtifact(
        embedding=fit.embedding,
        mu=fit.mu,
        sigma=fit.sigma,
        loadings=fit.loadings,
        libsize_mean=float(fit.libsize_mean),
        level_codes=codes,
        level_tables=tables,
        stability=np.asarray(stability, np.float32),
        pc_num=int(fit.pc_num),
        hvg_indices=fit.hvg_indices,
        gene_names=fit.gene_names,
        n_genes_full=fit.n_genes_full,
        stability_source=getattr(fit, "stability_source", None),
    )


def export_reference(result: Any, path: str, config: Any = None) -> ReferenceArtifact:
    """ClusterResult → saved bundle at ``path``. Returns the artifact."""
    art = reference_from_result(result, config=config)
    art.save(path, config=config)
    return art


def load_reference(path: str) -> ReferenceArtifact:
    return ReferenceArtifact.load(path)
