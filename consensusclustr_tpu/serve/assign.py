"""The jit-compiled query path: raw counts → frozen normalization → PC
projection → blockwise kNN vote against the reference embedding.

Pipeline per micro-batch (all one jitted program per bucket shape):

  1. frozen normalization — ``sf = rowsum(counts_hvg) / libsize_mean`` (the
     artifact's frozen library-size rule; all-zero rows get sf 1), then
     ``log1p(x / sf)``: the serving twin of prep/transform.shifted_log;
  2. projection into reference PC space via the fitted loadings and their
     centring/scaling stats (linalg/pca.project_onto_loadings);
  3. exact blockwise kNN against the reference embedding
     (cluster/knn.knn_cross) and a per-class vote over the k neighbours'
     leaf labels: label = majority class, confidence = vote fraction,
     plus the mean bootstrap stability of the winning neighbours;
  4. exact-match snap: a query that lands (numerically) ON a reference cell
     — squared distance ≤ ``snap_eps * (1 + |q|²)`` — inherits that cell's
     label with confidence 1. This is what makes self-assignment reproduce
     the offline consensus labels bit-for-bit at every bucket size: an
     identical cell IS that cell, and no k-neighbour majority in a boundary
     region may overrule it.

Batches pad to power-of-two bucket shapes (``resolve_buckets``) so XLA
compiles one executable per bucket, not per request size; padded rows are
masked out host-side. Granular mode votes once at the LEAF level and reports
each level as the winner's lineage prefix — per-level majorities could
disagree with their own parent, a hierarchy no consumer wants.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from consensusclustr_tpu.serve.artifact import ReferenceArtifact
from consensusclustr_tpu.utils.compile_cache import counting_jit

DEFAULT_MAX_BATCH = 256
DEFAULT_K = 15
# Relative squared-distance threshold for the exact-match snap. A self-query
# differs from its stored embedding only by f32 matmul reassociation across
# batch shapes (≲1e-6 relative), while distinct cells in PC space sit O(1)+
# apart; 1e-4 relative leaves orders of magnitude on both sides.
DEFAULT_SNAP_EPS = 1e-4


def resolve_max_batch(requested: Optional[int] = None) -> int:
    """Explicit arg > $CCTPU_SERVE_MAX_BATCH > 256 (see docs/quirks.md)."""
    if requested is None:
        requested = int(os.environ.get("CCTPU_SERVE_MAX_BATCH", DEFAULT_MAX_BATCH))
    v = int(requested)
    if v < 1:
        raise ValueError(f"serve_max_batch must be >= 1; got {v}")
    return v


def resolve_buckets(
    requested=None, max_batch: Optional[int] = None
) -> Tuple[int, ...]:
    """The compiled bucket ladder: explicit sizes > $CCTPU_SERVE_BUCKETS
    (comma-separated) > powers of two 1..max_batch. Always sorted, deduped,
    and capped so the largest bucket can hold a full micro-batch."""
    if requested is None:
        env = os.environ.get("CCTPU_SERVE_BUCKETS")
        if env:
            requested = [int(s) for s in env.split(",") if s.strip()]
    mb = resolve_max_batch(max_batch)
    if requested is None:
        sizes = []
        b = 1
        while b < mb:
            sizes.append(b)
            b *= 2
        sizes.append(mb)
    else:
        sizes = [int(b) for b in requested]
        if any(b < 1 for b in sizes):
            raise ValueError(f"bucket sizes must be >= 1; got {sizes}")
        if max(sizes) < mb:
            sizes.append(mb)
    return tuple(sorted(set(sizes)))


def bucket_for(n_rows: int, buckets: Tuple[int, ...]) -> int:
    """Smallest bucket >= n_rows (callers cap n_rows at max(buckets))."""
    for b in buckets:
        if b >= n_rows:
            return b
    raise ValueError(f"batch of {n_rows} rows exceeds largest bucket {buckets[-1]}")


@functools.partial(counting_jit, static_argnames=("k", "n_classes"))
def _assign_batch(
    counts,       # [q, g] float32 raw HVG counts (padded rows all-zero)
    ref_emb,      # [n_ref, d] float32
    ref_codes,    # [n_ref] int32 leaf cluster codes
    stability,    # [n_classes] float32 per-cluster bootstrap stability
    mu,           # [g]
    sigma,        # [g]
    loadings,     # [g, d]
    libsize_mean, # scalar
    snap_eps,     # scalar
    k: int,
    n_classes: int,
):
    """One bucket-shaped micro-batch end to end on device."""
    from consensusclustr_tpu.cluster.knn import knn_cross
    from consensusclustr_tpu.linalg.pca import project_onto_loadings

    x = jnp.asarray(counts, jnp.float32)
    lib = jnp.sum(x, axis=1)
    sf = jnp.where(lib > 0, lib / jnp.maximum(libsize_mean, 1e-12), 1.0)
    norm = jnp.log1p(x / sf[:, None])
    proj = project_onto_loadings(norm, loadings, mu, sigma)     # [q, d]

    k_eff = min(k, ref_emb.shape[0])
    idx, dist = knn_cross(proj, ref_emb, k_eff)                 # [q, k_eff]
    codes_nb = ref_codes[idx]                                   # [q, k_eff]

    onehot = (codes_nb[:, :, None] == jnp.arange(n_classes, dtype=jnp.int32)[None, None, :])
    votes = jnp.sum(onehot.astype(jnp.float32), axis=1)         # [q, C]
    winner = jnp.argmax(votes, axis=1).astype(jnp.int32)
    frac = jnp.take_along_axis(votes, winner[:, None], axis=1)[:, 0] / k_eff

    stab_nb = stability[codes_nb]                               # [q, k_eff]
    win_mask = (codes_nb == winner[:, None]).astype(jnp.float32)
    mean_stab = jnp.sum(stab_nb * win_mask, axis=1) / jnp.maximum(
        jnp.sum(win_mask, axis=1), 1.0
    )

    # exact-match snap (see module docstring)
    q2 = jnp.sum(proj * proj, axis=1)
    d2_min = dist[:, 0] ** 2
    nearest = ref_codes[idx[:, 0]]
    snap = d2_min <= snap_eps * (1.0 + q2)
    winner = jnp.where(snap, nearest, winner)
    frac = jnp.where(snap, 1.0, frac)
    mean_stab = jnp.where(snap, stability[nearest], mean_stab)
    return winner, frac, mean_stab, dist[:, 0]


# ---------------------------------------------------------------------------
# Cross-process AOT warm start (ISSUE 13)
# ---------------------------------------------------------------------------
# Per-bucket COMPILED assign executables, keyed in-process by the reference
# identity + the full static shape of the program. assign_bucketed consults
# this registry before the counting_jit path: a registered executable is
# dispatched directly (statics baked in — dynamic args only), skipping trace
# and lowering entirely. The registry is populated by
# AssignmentService.warmup(): either deserialized from the on-disk AOT cache
# (utils/compile_cache.aot_load; the warm-start path — zero traces) or
# compiled once via prepare_assign_executable and saved back for the next
# process (the cold path).

_AOT_EXECS: Dict[tuple, object] = {}


def artifact_sha(reference: ReferenceArtifact) -> str:
    """Stable content identity for one reference: the bundle manifest's
    arrays checksum when the artifact was saved/loaded, else (hand-built
    artifacts, tests) a sha256 over the array payload. Cached per object."""
    cached = getattr(reference, "_aot_sha", None)
    if cached is not None:
        return cached
    sha = reference.manifest.get("checksum_sha256") if reference.manifest else None
    if not sha:
        import hashlib

        h = hashlib.sha256()
        for arr in (
            reference.embedding, reference.mu, reference.sigma,
            reference.loadings, reference.level_codes, reference.stability,
        ):
            a = np.ascontiguousarray(arr)
            h.update(str(a.shape).encode())
            h.update(str(a.dtype).encode())
            h.update(a.tobytes())
        h.update(np.float32(reference.libsize_mean).tobytes())
        sha = h.hexdigest()
    reference._aot_sha = sha
    return sha


def _exec_key(
    reference: ReferenceArtifact, bucket: int, n_genes: int, k: int,
    n_classes: int,
) -> tuple:
    return (artifact_sha(reference), int(bucket), int(n_genes), int(k),
            int(n_classes))


def register_aot_executable(
    reference: ReferenceArtifact, bucket: int, n_genes: int, k: int,
    n_classes: int, compiled,
) -> None:
    _AOT_EXECS[_exec_key(reference, bucket, n_genes, k, n_classes)] = compiled


def aot_executable_for(
    reference: ReferenceArtifact, bucket: int, n_genes: int, k: int,
    n_classes: int,
):
    return _AOT_EXECS.get(_exec_key(reference, bucket, n_genes, k, n_classes))


def clear_aot_executables() -> None:
    """Drop every registered executable (tests; frees the linked programs)."""
    _AOT_EXECS.clear()


def _assign_dynamic_args(reference: ReferenceArtifact, padded, snap_eps):
    """The dynamic operand tuple of one bucket call, in _assign_batch order.
    prepare_assign_executable lowers on EXACTLY this construction and
    assign_bucketed calls with it, so the compiled input avals always match."""
    ref_emb, ref_codes, stability, mu, sigma, loadings, lsm = _device_state(
        reference
    )
    return (padded, ref_emb, ref_codes, stability, mu, sigma, loadings, lsm,
            np.float32(snap_eps))


def prepare_assign_executable(
    reference: ReferenceArtifact, bucket: int, *, k: int = DEFAULT_K,
    snap_eps: float = DEFAULT_SNAP_EPS,
):
    """Trace+compile the assign program for one bucket shape ahead of time.

    Returns the jax ``Compiled`` (statics baked in; call it with the
    ``_assign_dynamic_args`` tuple). The trace goes through counting_jit's
    mirrored ``lower``, so it counts one ``executable_compiles`` exactly like
    a first dispatch would — the cold/warm delta the bench warm_start rung
    measures is real trace work, not an accounting artifact.
    """
    g = reference.n_hvg
    n_classes = len(reference.leaf_table)
    k_eff = int(k)
    args = _assign_dynamic_args(
        reference, np.zeros((int(bucket), g), np.float32), snap_eps
    )
    return _assign_batch.lower(*args, k=k_eff, n_classes=n_classes).compile()


@dataclasses.dataclass
class AssignResult:
    """Per-query labels + confidence from one assign call.

    ``labels`` are leaf (full-lineage) strings; ``levels`` (granular mode
    only) maps level ℓ (1-based) to that level's label strings — level ℓ of
    a query is the first ℓ lineage parts of its leaf label.
    """

    labels: np.ndarray                # [q] str leaf labels
    confidence: np.ndarray            # [q] float32 vote fraction (1.0 = snap)
    neighbor_stability: np.ndarray    # [q] float32 mean winning-neighbour stability
    nearest_distance: np.ndarray      # [q] float32 distance to nearest ref cell
    levels: Optional[Dict[int, np.ndarray]] = None  # granular mode only
    # Request-lifecycle decomposition (ISSUE 7), filled only by the
    # AssignmentService path: req_id plus queue_wait_s / batch_wait_s /
    # device_s / latency_s (the first three sum to latency_s by construction
    # — same clock reads) and the batch context (bucket, batch_rows,
    # batch_requests). None on direct assign_cells calls.
    timing: Optional[Dict[str, float]] = None


class CompileTracker:
    """Host-side record of which (bucket, genes) shapes have been dispatched.

    XLA exposes no per-call compile hook, but the dispatch pattern is fully
    ours: a bucket shape's FIRST dispatch is its compile (jit caches by
    shape). ``note`` increments the ``serve_compile`` counter exactly then.
    """

    def __init__(self) -> None:
        self._seen: set = set()

    def note(self, bucket: int, n_genes: int, metrics=None) -> bool:
        key = (int(bucket), int(n_genes))
        fresh = key not in self._seen
        if fresh:
            self._seen.add(key)
            if metrics is not None:
                metrics.counter("serve_compile").inc()
        return fresh

    @property
    def count(self) -> int:
        return len(self._seen)


def subset_to_hvg(reference: ReferenceArtifact, counts: np.ndarray) -> np.ndarray:
    """Query counts → the artifact's HVG gene space.

    Accepts either the full gene space (subset by the stored hvg_indices) or
    counts already in HVG space; anything else is a loud shape error.
    """
    counts = np.asarray(counts, np.float32)
    if counts.ndim == 1:
        counts = counts[None, :]
    g = reference.n_hvg
    if counts.shape[1] == g:
        return counts
    idx = reference.hvg_indices
    full = reference.n_genes_full
    if idx is not None:
        # exact full-space width when the artifact recorded it; otherwise
        # (hand-built artifacts) any width that covers every HVG index
        if (full is not None and counts.shape[1] == full) or (
            full is None and counts.shape[1] > int(idx.max())
        ):
            return counts[:, idx]
    raise ValueError(
        f"query counts have {counts.shape[1]} genes; the reference expects "
        f"{g} HVG genes"
        + (
            f" or the full {full}-gene space"
            if idx is not None and full is not None
            else ""
        )
        + (
            " (artifact stores no hvg_indices, so full-space input cannot "
            "be subset)"
            if idx is None
            else ""
        )
    )


def _device_state(reference: ReferenceArtifact):
    """Upload the artifact's arrays once per process (keyed on identity)."""
    cached = getattr(reference, "_device_state", None)
    if cached is None:
        cached = (
            jnp.asarray(reference.embedding, jnp.float32),
            jnp.asarray(reference.leaf_codes, jnp.int32),
            jnp.asarray(reference.stability, jnp.float32),
            jnp.asarray(reference.mu, jnp.float32),
            jnp.asarray(reference.sigma, jnp.float32),
            jnp.asarray(reference.loadings, jnp.float32),
            jnp.float32(reference.libsize_mean),
        )
        # dataclass without __slots__: cache lives with the artifact object
        reference._device_state = cached
    return cached


def assign_bucketed(
    reference: ReferenceArtifact,
    counts_hvg: np.ndarray,
    *,
    k: int = DEFAULT_K,
    buckets: Optional[Tuple[int, ...]] = None,
    max_batch: Optional[int] = None,
    snap_eps: float = DEFAULT_SNAP_EPS,
    metrics=None,
    compile_tracker: Optional[CompileTracker] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Codes + confidence for counts already in HVG space, bucket-padded.

    Splits the queries into micro-batches of at most ``max(buckets)`` rows,
    pads each to its bucket with all-zero rows (masked off after), and runs
    one jitted program per bucket shape. Returns (codes [q] int32,
    confidence [q], neighbor_stability [q], nearest_distance [q]).
    """
    buckets = resolve_buckets(buckets, max_batch)
    ref_emb, ref_codes, stability, mu, sigma, loadings, lsm = _device_state(
        reference
    )
    n_classes = len(reference.leaf_table)
    q_total = counts_hvg.shape[0]
    out = [np.empty(q_total, dt) for dt in (np.int32, np.float32, np.float32, np.float32)]
    step = buckets[-1]
    for s in range(0, q_total, step):
        chunk = counts_hvg[s : s + step]
        b = bucket_for(chunk.shape[0], buckets)
        if compile_tracker is not None:
            compile_tracker.note(b, chunk.shape[1], metrics)
        padded = chunk
        if b != chunk.shape[0]:
            padded = np.zeros((b, chunk.shape[1]), np.float32)
            padded[: chunk.shape[0]] = chunk
        exe = aot_executable_for(reference, b, chunk.shape[1], int(k), n_classes)
        if exe is not None:
            # AOT warm start: dispatch the pre-compiled executable directly
            # (statics baked in). Counted as a dispatch so the work ledger
            # stays comparable with the counting_jit path it bypasses.
            from consensusclustr_tpu.obs import global_metrics

            global_metrics().counter("device_dispatches").inc()
            codes, frac, stab, dist = exe(
                *_assign_dynamic_args(reference, padded, snap_eps)
            )
        else:
            codes, frac, stab, dist = _assign_batch(
                padded, ref_emb, ref_codes, stability, mu, sigma, loadings,
                lsm, np.float32(snap_eps), k=k, n_classes=n_classes,
            )
        n = chunk.shape[0]
        for buf, dev in zip(out, (codes, frac, stab, dist)):
            buf[s : s + n] = np.asarray(dev)[:n]
    return tuple(out)  # type: ignore[return-value]


def _labels_from_codes(
    reference: ReferenceArtifact, codes: np.ndarray, granular: bool
) -> Tuple[np.ndarray, Optional[Dict[int, np.ndarray]]]:
    leaf_table = np.asarray(reference.leaf_table, dtype=object)
    labels = leaf_table[codes]
    if not granular:
        return labels, None
    levels: Dict[int, np.ndarray] = {}
    for lvl in range(1, reference.n_levels + 1):
        levels[lvl] = np.asarray(
            ["_".join(str(l).split("_")[:lvl]) for l in labels], dtype=object
        )
    return labels, levels


def assign_cells(
    reference,
    counts,
    *,
    mode: str = "robust",
    k: int = DEFAULT_K,
    buckets: Optional[Tuple[int, ...]] = None,
    max_batch: Optional[int] = None,
    snap_eps: float = DEFAULT_SNAP_EPS,
    metrics=None,
) -> AssignResult:
    """One-shot query-to-reference mapping (no service/queue).

    ``reference`` is a ReferenceArtifact or a bundle path; ``counts`` are raw
    query counts over the full gene space or the HVG subset. ``mode`` follows
    the offline vocabulary: "robust" returns leaf labels only, "granular"
    additionally reports every hierarchy level. For sustained traffic use
    serve.service.AssignmentService, which adds micro-batching across
    requests, warm-up compiles and backpressure on top of this path.
    """
    from consensusclustr_tpu.serve.artifact import load_reference

    if mode not in ("robust", "granular"):
        raise ValueError(f"mode must be 'robust' or 'granular'; got {mode!r}")
    if isinstance(reference, (str, os.PathLike)):
        reference = load_reference(os.fspath(reference))
    counts_hvg = subset_to_hvg(reference, counts)
    codes, frac, stab, dist = assign_bucketed(
        reference, counts_hvg, k=k, buckets=buckets, max_batch=max_batch,
        snap_eps=snap_eps, metrics=metrics,
    )
    labels, levels = _labels_from_codes(reference, codes, mode == "granular")
    return AssignResult(
        labels=labels,
        confidence=frac,
        neighbor_stability=stab,
        nearest_distance=dist,
        levels=levels,
    )


def embed_reference_counts(
    counts_hvg: np.ndarray,
    mu: np.ndarray,
    sigma: np.ndarray,
    loadings: np.ndarray,
    libsize_mean: float,
) -> np.ndarray:
    """The export-side frozen embedding: reference cells through the EXACT
    normalization + projection the query path applies (same functions, so
    reference and query geometry agree by construction)."""
    from consensusclustr_tpu.linalg.pca import project_onto_loadings

    x = jnp.asarray(counts_hvg, jnp.float32)
    lib = jnp.sum(x, axis=1)
    sf = jnp.where(lib > 0, lib / jnp.maximum(libsize_mean, 1e-12), 1.0)
    norm = jnp.log1p(x / sf[:, None])
    return np.asarray(
        project_onto_loadings(
            norm,
            jnp.asarray(loadings, jnp.float32),
            jnp.asarray(mu, jnp.float32),
            jnp.asarray(sigma, jnp.float32),
        )
    )
