"""Online reference-mapping service (ISSUE 3 tentpole).

The offline pipeline (api.consensus_clust) fits a consensus clustering once;
this package makes that fit a *servable model*: persist it as a versioned
artifact, then assign new cells against it at request time without re-running
any clustering — the query-to-reference mapping pattern of Seurat
v4/Azimuth (Hao et al. 2021) and scArches (Lotfollahi et al. 2022), with
TPU-shaped serving mechanics.

Three layers, lowest first:

  * ``artifact``  — ``ReferenceArtifact``: a schema-versioned, checksummed
    bundle (npz arrays + json manifest) freezing everything a query needs:
    HVG indices, normalization constants, PCA components, the reference
    embedding, per-level consensus labels and per-cluster stability.
    Import-light and jax-free: loading/validating an artifact never touches
    a backend.
  * ``assign``    — the jit-compiled query path: raw counts → frozen
    normalization → PC projection (linalg/pca.py components) → blockwise
    kNN vote against the reference embedding (cluster/knn.py) → label +
    confidence. Batches pad to power-of-two buckets so XLA executables are
    reused across request sizes.
  * ``service``   — ``AssignmentService``: bounded request queue,
    micro-batching, warm-up compiles at load, backpressure (queue-full →
    ``RetryableRejection``), graceful drain, and obs/ metrics
    (``serve_latency_seconds``, ``queue_depth``, ``batch_occupancy``,
    ``serve_compile``).
  * ``fleet``     — the ISSUE 18 multi-replica layer: ``FleetRouter``
    (``router``) puts N services behind health-keyed least-loaded
    admission with failover and zero-downtime ``swap_reference``;
    ``control`` is the opt-in alert-driven ``ControlPolicy``;
    ``build_fleet`` (``fleet``) assembles it all.

Top-level surface: ``api.export_reference(result, path)`` /
``api.assign_cells(reference, counts)`` / ``api.build_fleet(reference)``;
``tools/serve_demo.py`` is the export-then-query driver and
``tools/loadgen.py --target fleet`` drives a router.

The fleet names below are lazy (PEP 562): importing this package stays
jax-free; touching ``build_fleet`` / ``FleetRouter`` / ``ControlPolicy``
pulls the serving stack.
"""

from consensusclustr_tpu.serve.artifact import (
    ArtifactChecksumError,
    ArtifactError,
    ArtifactSchemaError,
    ReferenceArtifact,
    ReferenceFit,
    SERVE_SCHEMA_VERSION,
    export_reference,
    load_reference,
    reference_from_result,
)

__all__ = [
    "ArtifactChecksumError",
    "ArtifactError",
    "ArtifactSchemaError",
    "ControlPolicy",
    "FleetRouter",
    "ReferenceArtifact",
    "ReferenceFit",
    "SERVE_SCHEMA_VERSION",
    "build_fleet",
    "export_reference",
    "load_reference",
    "reference_from_result",
]

_LAZY = {
    "FleetRouter": ("consensusclustr_tpu.serve.router", "FleetRouter"),
    "build_fleet": ("consensusclustr_tpu.serve.fleet", "build_fleet"),
    "ControlPolicy": ("consensusclustr_tpu.serve.control", "ControlPolicy"),
}


def __getattr__(name):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    import importlib

    return getattr(importlib.import_module(mod_name), attr)
