"""Checkpoint / resume for long consensus runs (SURVEY §5 checkpoint row).

The reference has no persistence — a crashed 1M-cell run starts over. Here the
expensive, restartable unit is the bootstrap fan-out: per-chunk boot labels
are appended to a directory keyed by a content fingerprint of (pca, config,
seed), so a re-run with identical inputs resumes at the first missing chunk.
The co-clustering distance and everything after it is cheap relative to the
boots and is recomputed.

Layout (one directory per run):
    meta.json             fingerprint + shapes
    boots_<start>.npz     labels [chunk, n] int32, scores [chunk]

Orbax is the right tool for sharded device arrays; boot labels are small
host-side int32 matrices, so plain npz keeps the dependency surface at numpy.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
from typing import Dict, Optional, Tuple

import numpy as np

_CHUNK_RE = re.compile(r"^boots_(\d+)\.npz$")


def run_fingerprint(pca: np.ndarray, cfg_fields: Dict, key_bytes: bytes) -> str:
    """Stable hash of the inputs that determine the bootstrap stream.

    `key_bytes` must be the raw PRNG key data actually driving the boots
    (jax.random.key_data(...)) — the config seed alone does not determine the
    stream when a caller passes its own key.
    """
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(pca, np.float32).tobytes())
    h.update(json.dumps(cfg_fields, sort_keys=True, default=str).encode())
    h.update(key_bytes)
    return h.hexdigest()[:16]


class BootCheckpoint:
    """Append-only per-chunk store for bootstrap assignments.

    Chunks live in a per-fingerprint subdirectory of `directory`, so multiple
    runs (e.g. every subproblem of an iterate=True recursion) share one
    checkpoint root without ever invalidating each other's chunks.
    """

    def __init__(
        self,
        directory: str,
        fingerprint: str,
        nboots: int,
        n_cells: int,
        rows_per_boot: int = 1,
    ):
        """rows_per_boot > 1 is granular mode: each boot contributes its full
        |k_num| * |res_range| candidate slab, stored flattened boot-major as
        [chunk * rows_per_boot, n_cells] (the layout the consensus co-cluster
        consumes). The fingerprint must include the grid shape so a changed
        grid can never resume a stale slab."""
        self.dir = os.path.join(directory, fingerprint)
        self.fp = fingerprint
        self.nboots = nboots
        self.n_cells = n_cells
        self.rows_per_boot = rows_per_boot
        os.makedirs(self.dir, exist_ok=True)
        # clean torn writes from a previous crash
        for name in os.listdir(self.dir):
            if name.endswith(".tmp.npz"):
                try:
                    os.unlink(os.path.join(self.dir, name))
                except OSError:
                    pass
        self._meta_path = os.path.join(self.dir, "meta.json")
        meta = {
            "fingerprint": fingerprint, "nboots": nboots, "n_cells": n_cells,
            "rows_per_boot": rows_per_boot,
        }
        if not os.path.exists(self._meta_path):
            with open(self._meta_path, "w") as f:
                json.dump(meta, f)

    def _chunk_path(self, start: int) -> str:
        return os.path.join(self.dir, f"boots_{start:06d}.npz")

    def load_chunk(self, start: int, size: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        path = self._chunk_path(start)
        if not os.path.exists(path):
            return None
        try:
            with np.load(path) as z:
                labels, scores = z["labels"], z["scores"]
        except Exception:
            return None  # torn write: recompute this chunk
        if labels.shape != (size * self.rows_per_boot, self.n_cells):
            return None
        # scores must be per-row too: a malformed-but-loadable scores array
        # would otherwise crash the granular resume reshape downstream
        # instead of falling back to recompute (ADVICE r4).
        if scores.shape != (size * self.rows_per_boot,):
            return None
        return labels, scores

    def save_chunk(self, start: int, labels: np.ndarray, scores: np.ndarray) -> None:
        path = self._chunk_path(start)
        tmp = path + ".tmp.npz"  # .npz suffix stops savez renaming it
        np.savez(tmp, labels=np.asarray(labels, np.int32), scores=np.asarray(scores))
        os.replace(tmp, path)

    def completed_boots(self) -> int:
        # Count DISTINCT covered boot indices, not file row totals: since
        # chunk size left the fingerprint (ADVICE r4), a resume under a
        # different chunking can leave stale overlapping files behind, and
        # summing rows would double-count the overlap.
        covered = np.zeros(max(self.nboots, 1), bool)
        for name in sorted(os.listdir(self.dir)):
            m = _CHUNK_RE.match(name)
            if m:
                try:
                    start = int(m.group(1))
                    with np.load(os.path.join(self.dir, name)) as z:
                        k = z["labels"].shape[0] // self.rows_per_boot
                    covered[start:start + k] = True
                except Exception:
                    pass
        return int(covered.sum())
