"""Checkpoint / resume for long consensus runs (SURVEY §5 checkpoint row).

The reference has no persistence — a crashed 1M-cell run starts over. Here the
expensive, restartable unit is the bootstrap fan-out: per-chunk boot labels
are appended to a directory keyed by a content fingerprint of (pca, config,
seed), so a re-run with identical inputs resumes at the first missing chunk.
The co-clustering distance and everything after it is cheap relative to the
boots and is recomputed.

Layout (one directory per run):
    meta.json                    fingerprint + shapes
    boots_<start>.npz            labels [chunk, n] int32, scores [chunk]
    boots_<start>.npz.sha256     integrity sidecar (hex digest of the npz)

Integrity contract (ISSUE 10): writes are atomic (tmp file + ``os.replace``,
so a kill mid-write can never leave a torn final file), each chunk's sha256
lands in a sidecar written after the data file, and resume treats a
checksum-mismatched or unreadable chunk as *missing*: the bad file is
quarantine-renamed (``*.npz.quarantine``, kept for forensics), the
``ckpt_quarantined`` counter and event fire, and the chunk is recomputed —
never crashed on, never silently resumed. A chunk whose sidecar is absent
(legacy checkpoint, or a crash between data and sidecar rename) is accepted
on the shape checks alone — the sidecar upgrade must not orphan old runs.

Orbax is the right tool for sharded device arrays; boot labels are small
host-side int32 matrices, so plain npz keeps the dependency surface at numpy.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
from typing import Dict, Optional, Tuple

import numpy as np

_CHUNK_RE = re.compile(r"^boots_(\d+)\.npz$")


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


class ChunkIntegrityError(RuntimeError):
    """A chunk file's bytes do not match its recorded sha256 sidecar."""


def run_fingerprint(pca: np.ndarray, cfg_fields: Dict, key_bytes: bytes) -> str:
    """Stable hash of the inputs that determine the bootstrap stream.

    `key_bytes` must be the raw PRNG key data actually driving the boots
    (jax.random.key_data(...)) — the config seed alone does not determine the
    stream when a caller passes its own key.
    """
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(pca, np.float32).tobytes())
    h.update(json.dumps(cfg_fields, sort_keys=True, default=str).encode())
    h.update(key_bytes)
    return h.hexdigest()[:16]


class BootCheckpoint:
    """Append-only per-chunk store for bootstrap assignments.

    Chunks live in a per-fingerprint subdirectory of `directory`, so multiple
    runs (e.g. every subproblem of an iterate=True recursion) share one
    checkpoint root without ever invalidating each other's chunks.

    ``metrics``/``log`` (optional) receive the quarantine telemetry — the
    ``ckpt_quarantined`` counter and event; absent, the counter goes to the
    process-global registry and the event is dropped.
    """

    def __init__(
        self,
        directory: str,
        fingerprint: str,
        nboots: int,
        n_cells: int,
        rows_per_boot: int = 1,
        metrics=None,
        log=None,
    ):
        """rows_per_boot > 1 is granular mode: each boot contributes its full
        |k_num| * |res_range| candidate slab, stored flattened boot-major as
        [chunk * rows_per_boot, n_cells] (the layout the consensus co-cluster
        consumes). The fingerprint must include the grid shape so a changed
        grid can never resume a stale slab."""
        self.dir = os.path.join(directory, fingerprint)
        self.fp = fingerprint
        self.nboots = nboots
        self.n_cells = n_cells
        self.rows_per_boot = rows_per_boot
        self.metrics = metrics
        self.log = log
        os.makedirs(self.dir, exist_ok=True)
        # clean torn writes from a previous crash (data tmps AND sidecar tmps)
        for name in os.listdir(self.dir):
            if name.endswith(".tmp.npz") or name.endswith(".tmp"):
                try:
                    os.unlink(os.path.join(self.dir, name))
                except OSError:
                    pass
        self._meta_path = os.path.join(self.dir, "meta.json")
        meta = {
            "fingerprint": fingerprint, "nboots": nboots, "n_cells": n_cells,
            "rows_per_boot": rows_per_boot,
        }
        if not os.path.exists(self._meta_path):
            with open(self._meta_path, "w") as f:
                json.dump(meta, f)

    def _chunk_path(self, start: int) -> str:
        return os.path.join(self.dir, f"boots_{start:06d}.npz")

    @staticmethod
    def _sidecar_path(path: str) -> str:
        return path + ".sha256"

    def _metrics(self):
        if self.metrics is not None:
            return self.metrics
        from consensusclustr_tpu.obs.metrics import global_metrics

        return global_metrics()

    def _quarantine(self, start: int, path: str, reason: str) -> None:
        """Rename a corrupt/unreadable chunk (and its sidecar) aside so the
        resume recomputes it; the renamed file is kept for forensics. The
        quarantine itself must never fail the run — worst case the bad file
        stays and keeps being treated as missing."""
        qpath = path + ".quarantine"
        try:
            os.replace(path, qpath)
        except OSError:
            try:
                os.unlink(path)
            except OSError:
                pass
        sidecar = self._sidecar_path(path)
        if os.path.exists(sidecar):
            try:
                os.replace(sidecar, sidecar + ".quarantine")
            except OSError:
                pass
        self._metrics().counter("ckpt_quarantined").inc()
        from consensusclustr_tpu.utils.log import get_logger

        get_logger().warning(
            "checkpoint chunk %s quarantined (%s); it will be recomputed",
            os.path.basename(path), reason,
        )
        if self.log is not None:
            try:
                self.log.event(
                    "ckpt_quarantined", chunk_start=int(start), reason=reason,
                    path=os.path.basename(path),
                )
            except Exception:  # graftlint: noqa[GL007] quarantine event emit is best-effort; the rename already preserved the evidence
                pass

    def load_chunk(self, start: int, size: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        path = self._chunk_path(start)
        if not os.path.exists(path):
            return None
        try:
            sidecar = self._sidecar_path(path)
            if os.path.exists(sidecar):
                with open(sidecar) as f:
                    want = f.read().strip()
                if want and _sha256_file(path) != want:
                    raise ChunkIntegrityError(
                        f"sha256 mismatch for {os.path.basename(path)}"
                    )
            with np.load(path) as z:
                labels, scores = z["labels"], z["scores"]
        except Exception as e:  # graftlint: noqa[GL007] quarantine path: _quarantine logs ckpt_quarantined and the chunk recomputes
            # torn write / bit rot / checksum mismatch: quarantine-rename and
            # recompute — a bad chunk must never crash or poison a resume
            self._quarantine(start, path, type(e).__name__)
            return None
        if labels.shape != (size * self.rows_per_boot, self.n_cells):
            # a SHAPE mismatch is not corruption: a resume under a different
            # chunking legitimately leaves overlapping stale files behind
            # (chunk size left the fingerprint, ADVICE r4) — skip, don't
            # quarantine
            return None
        # scores must be per-row too: a malformed-but-loadable scores array
        # would otherwise crash the granular resume reshape downstream
        # instead of falling back to recompute (ADVICE r4).
        if scores.shape != (size * self.rows_per_boot,):
            return None
        return labels, scores

    def save_chunk(self, start: int, labels: np.ndarray, scores: np.ndarray) -> None:
        from consensusclustr_tpu.resilience.inject import (
            CKPT_WRITE_SITE,
            maybe_corrupt_file,
        )

        path = self._chunk_path(start)
        tmp = path + ".tmp.npz"  # .npz suffix stops savez renaming it
        np.savez(tmp, labels=np.asarray(labels, np.int32), scores=np.asarray(scores))
        digest = _sha256_file(tmp)
        os.replace(tmp, path)  # atomic: a kill here leaves old-or-new, never torn
        # sidecar lands after the data file (atomically too): a crash between
        # the two leaves data without sidecar = accepted legacy chunk, or a
        # stale sidecar against new data = checksum mismatch -> quarantine +
        # recompute. Either way the resume stays correct.
        sidecar = self._sidecar_path(path)
        sidecar_tmp = sidecar + ".tmp"
        with open(sidecar_tmp, "w") as f:
            f.write(digest + "\n")
        os.replace(sidecar_tmp, sidecar)
        # fault injection (resilience/inject.py, off by default): a planted
        # corrupt_bytes fault flips bytes of the FINAL file — simulating the
        # silent on-disk corruption the sidecar exists to catch at resume
        maybe_corrupt_file(CKPT_WRITE_SITE, path, self.metrics)

    def completed_boots(self) -> int:
        # Count DISTINCT covered boot indices, not file row totals: since
        # chunk size left the fingerprint (ADVICE r4), a resume under a
        # different chunking can leave stale overlapping files behind, and
        # summing rows would double-count the overlap.
        covered = np.zeros(max(self.nboots, 1), bool)
        for name in sorted(os.listdir(self.dir)):
            m = _CHUNK_RE.match(name)
            if m:
                try:
                    start = int(m.group(1))
                    with np.load(os.path.join(self.dir, name)) as z:
                        k = z["labels"].shape[0] // self.rows_per_boot
                    covered[start:start + k] = True
                except Exception:  # graftlint: noqa[GL007] resume coverage scan: an unreadable chunk is simply recomputed
                    pass
        return int(covered.sum())
