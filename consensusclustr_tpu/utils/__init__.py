from consensusclustr_tpu.utils.rng import root_key, boot_key, sim_key
from consensusclustr_tpu.utils.log import get_logger, LevelLog
