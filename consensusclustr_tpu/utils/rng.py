"""Deterministic PRNG key discipline.

The reference threads reproducibility through ``set.seed`` + BiocParallel's
``RNGseed`` (reference R/consensusClust.R:128, 194, 944, 956), bumping
``RNGseed+1`` for extra adaptive null rounds. Here a single root key is derived
from the user seed and every unit of work folds in a stable integer tag, so
results are bit-deterministic regardless of device count or batching order.

Tag spaces are kept disjoint so a bootstrap never shares a stream with a null
simulation at the same index.
"""

from __future__ import annotations

import zlib

import jax


def _tag(t):
    """Stable integer for fold_in: ints pass through, strings CRC32-hash
    (Python's hash() is salted per process and would break determinism)."""
    if isinstance(t, str):
        return zlib.crc32(t.encode()) & 0x7FFFFFFF
    return t

_BOOT_SPACE = 0x0B007
_SIM_SPACE = 0x51111
_CLUSTER_SPACE = 0xC1057
_DEPTH_SPACE = 0xD0000


def root_key(seed: int) -> jax.Array:
    return jax.random.key(int(seed))


def boot_key(key: jax.Array, boot_id) -> jax.Array:
    """Per-bootstrap stream (reference: per-worker RNG streams at :391)."""
    return jax.random.fold_in(jax.random.fold_in(key, _BOOT_SPACE), boot_id)


def sim_key(key: jax.Array, sim_id, round_id: int = 0) -> jax.Array:
    """Per-null-simulation stream; round_id mirrors the reference's RNGseed+1
    bump for adaptive rounds (reference :944, :956)."""
    k = jax.random.fold_in(jax.random.fold_in(key, _SIM_SPACE), round_id)
    return jax.random.fold_in(k, sim_id)


def cluster_key(key: jax.Array, tag) -> jax.Array:
    """Stream for tie-breaking inside the clustering kernel."""
    return jax.random.fold_in(jax.random.fold_in(key, _CLUSTER_SPACE), _tag(tag))


def depth_key(key: jax.Array, depth: int, child_id: int) -> jax.Array:
    """Stream for a recursive sub-problem (reference recursion at :562-566)."""
    k = jax.random.fold_in(jax.random.fold_in(key, _DEPTH_SPACE), depth)
    return jax.random.fold_in(k, child_id)
