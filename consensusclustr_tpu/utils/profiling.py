"""Tracing / profiling hooks (SURVEY §5 tracing row).

The reference has no timers at all; the tracked metric here is bootstraps/sec
(BASELINE.md), so the two tools that matter are wall-clock phase timers that
land in the structured LevelLog and jax.profiler traces for kernel-level work
(viewable in TensorBoard / Perfetto).

``phase`` predates the ``obs`` span tracer and remains the flat-event timer;
new code should prefer ``obs.Tracer.span`` / ``obs.maybe_span`` (hierarchy,
RunRecords). Both share the block-until-ready sink contract.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

import jax

from consensusclustr_tpu.utils.log import LevelLog


class PhaseSink:
    """Set ``.value`` to the phase's result so the timer blocks on it."""

    value = None


@contextlib.contextmanager
def phase(name: str, log: Optional[LevelLog] = None, **fields) -> Iterator[PhaseSink]:
    """Wall-clock a pipeline phase into the structured log.

    JAX dispatch is async, so a timer that exits before the device finishes
    records dispatch time, not compute. Assign the phase's output arrays to
    the yielded sink and the timer blocks on them at exit:

        with phase("boots", log) as p:
            p.value = jitted_fn(x)

    Without a sink value, only host work inside the block is covered.

    Exception paths stay distinguishable from success: the emitted event
    carries ``ok: False`` and the exception type, then the exception
    re-raises. (A failed phase's timing covers dispatch up to the raise; the
    sink is not blocked on, its value may be poisoned.)
    """
    sink = PhaseSink()
    t0 = time.perf_counter()
    err: Optional[BaseException] = None
    try:
        yield sink
    except BaseException as e:
        err = e
        raise
    finally:
        if err is None and sink.value is not None:
            jax.block_until_ready(sink.value)
        if log is not None:
            status = (
                {"ok": True}
                if err is None
                else {"ok": False, "error": type(err).__name__}
            )
            log.event(
                "phase", name=name,
                seconds=round(time.perf_counter() - t0, 4), **fields, **status,
            )


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """jax.profiler trace of everything inside the block (TensorBoard format)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region inside a device trace (shows up in the profiler timeline)."""
    return jax.profiler.TraceAnnotation(name)
