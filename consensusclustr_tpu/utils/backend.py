"""Backend identification that cannot hang on a dead accelerator link.

`jax.default_backend()` initializes the default PJRT client, and on this
sandbox's tunneled TPU the axon plugin's `get_backend` hook dials the
serving tunnel — a wedged tunnel then blocks *indefinitely*, even when
`JAX_PLATFORMS=cpu` pins the process to the host platform (observed r5:
an e2e CPU run sat >25 min inside `enable_persistent_cache`'s backend
probe with 8 s of CPU time).

When JAX_PLATFORMS names the platform explicitly there is nothing to
probe: trust the env and never touch the backend registry. Only an
unpinned process (empty/unset JAX_PLATFORMS, i.e. "autodetect") pays the
real `jax.default_backend()` call — which is then the correct, intended
behavior, wedge risk included, because the answer genuinely depends on
what initializes.
"""

from __future__ import annotations

import os


def default_backend() -> str:
    """The default platform name, resolved from $JAX_PLATFORMS when pinned.

    The axon plugin serves TPU devices (jax.default_backend() reports
    "tpu" under it), so "axon" maps to "tpu" here.

    When the env pins plain "cpu", also re-pin jax's *config*: the axon
    sitecustomize sets jax_platforms="axon,cpu" at interpreter start,
    overriding the env, so without this the process's first device op
    still dials the accelerator plugin (tests/conftest.py applies the
    same correction for the pytest process).
    """
    env = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if "," in env:
        # a list ("tpu,cpu") is a fallback preference, not a pin — which
        # entry actually initialized is only knowable from the real probe
        import jax

        return jax.default_backend()
    if env == "cpu":
        import jax

        if jax.config.jax_platforms != "cpu":
            jax.config.update("jax_platforms", "cpu")
        return "cpu"
    if env:
        return "tpu" if env == "axon" else env
    import jax

    return jax.default_backend()
