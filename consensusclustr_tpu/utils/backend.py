"""Backend identification that cannot hang on a dead accelerator link.

`jax.default_backend()` initializes the default PJRT client, and on this
sandbox's tunneled TPU the axon plugin's `get_backend` hook dials the
serving tunnel — a wedged tunnel then blocks *indefinitely*, even when
`JAX_PLATFORMS=cpu` pins the process to the host platform (observed r5:
an e2e CPU run sat >25 min inside `enable_persistent_cache`'s backend
probe with 8 s of CPU time).

When `JAX_PLATFORMS=cpu` pins the process, there is nothing to probe:
trust the env, re-pin jax's config, and never touch the backend
registry. Otherwise a single-platform jax *config* value (the more
current signal — bench.py's CPU forcing and the test conftest both
select via config while the launch env still names the accelerator)
answers without a probe. Only a genuinely ambiguous process (platform
list like "axon,cpu", or nothing set) pays the real
`jax.default_backend()` call — which is then the correct, intended
behavior, wedge risk included, because the answer depends on what
initializes.
"""

from __future__ import annotations

import os

# Single source of truth for the cpu-pin check, shared with the package
# root's import-time re-pin (ADVICE r5 #3: two inlined copies could drift).
# Re-exported here because this module is the documented home of the check.
from consensusclustr_tpu._env import cpu_env_pinned, repin_cpu_from_env  # noqa: F401


def default_backend() -> str:
    """The default platform name, resolved from $JAX_PLATFORMS when pinned.

    The axon plugin serves TPU devices (jax.default_backend() reports
    "tpu" under it), so "axon" maps to "tpu" here.

    When the env pins plain "cpu", also re-pin jax's *config*: the axon
    sitecustomize sets jax_platforms="axon,cpu" at interpreter start,
    overriding the env, so without this the process's first device op
    still dials the accelerator plugin (tests/conftest.py applies the
    same correction for the pytest process).
    """
    env = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if cpu_env_pinned():
        repin_cpu_from_env()
        return "cpu"
    # For anything but an env cpu-pin, the live config is the more current
    # signal: bench.py's CCTPU_FORCE_CPU and tests/conftest.py both select
    # cpu via the config while the launch env still names the accelerator —
    # reporting "tpu" there would e.g. enable the persistent compile cache
    # on an XLA:CPU process (a known SIGSEGV source, see compile_cache.py).
    import jax

    cfg = (jax.config.jax_platforms or "").strip().lower()
    if cfg and "," not in cfg:
        return "tpu" if cfg == "axon" else cfg
    if env and "," not in env:
        # config is unset or an ambiguous fallback list ("axon,cpu" — the
        # sitecustomize default), but the launch env names one platform:
        # trust it rather than pay the wedge-prone probe (JAX_PLATFORMS=axon
        # is the driver's normal accelerator pin)
        return "tpu" if env == "axon" else env
    # nothing pinned anywhere: which platform initializes is only knowable
    # from the real probe
    return jax.default_backend()
