"""Persistent XLA compilation cache.

The pipeline's jitted programs are keyed by (shape, static args); a fresh
process otherwise pays the full TPU compile (~20-40 s per program) again.
Pointing jax's compilation cache at a disk directory makes every rerun—and
every recursion level that repeats a shape—hit the cache across processes.

Enabled by the top-level API on first use; opt out with CCTPU_NO_COMPILE_CACHE
or redirect with CCTPU_COMPILE_CACHE_DIR.

Idempotency contract (ISSUE 3 satellite): ``enable_persistent_cache`` may be
called unconditionally from any entry point — the offline API, the serving
warm-up path, bench — and only the FIRST call does configuration work; every
call increments the ``compile_cache_enable_calls`` counter and the
``compile_cache_enabled`` gauge reflects the resolved state (1 active,
0 disabled: CPU backend, opt-out env, or setup failure) exactly once per
process. The function returns that resolved state so callers can log it.
"""

from __future__ import annotations

import functools
import os

import jax

from consensusclustr_tpu.obs import global_metrics
from consensusclustr_tpu.utils.backend import default_backend

_done = False


def counting_jit(fun=None, *, donate_argnums=(), **jit_kwargs):
    """``jax.jit`` with dispatch/compile/donation accounting (ISSUE 5).

    Wraps the pipeline's TOP-LEVEL jitted entry programs and counts, in the
    process-global metrics registry (obs/schema.py):

      * ``device_dispatches`` — calls that launch an executable. A call made
        while an enclosing program is being traced inlines into that program
        and is NOT counted (that is the point of fusing: fewer dispatches).
      * ``executable_compiles`` — traces, i.e. new (shape, static-args) cache
        entries. One per shape bucket; counted even when the persistent XLA
        cache serves the binary (a trace is the compile-shaped host work the
        accounting is meant to expose).
      * ``donated_bytes`` — bytes of operand buffers handed to the executable
        via ``donate_argnums`` per dispatch (in-place carry updates: the
        consensus accumulator, per-chunk key/index slices).
      * ``estimated_flops`` / ``estimated_bytes_accessed`` (ISSUE 6) — XLA
        ``cost_analysis`` of each freshly traced shape bucket, one execution's
        worth per compile. Harvested from the *lowered* (pre-optimization)
        HLO — no second backend compile — and tolerant of backends that
        report nothing: the counters simply stay at 0. This is O4's
        FLOP/byte denominator next to the dispatch counts.

    The counters cover exactly the functions wrapped here — the per-boot hot
    path and its chunk drivers — not every small jit in the package, so
    bench deltas are stable, gateable program counts (tools/bench_diff.py
    ``--gate compiles:...`` / ``--gate rss:...``).
    """
    if fun is None:
        return functools.partial(
            counting_jit, donate_argnums=donate_argnums, **jit_kwargs
        )
    donate = tuple(donate_argnums)
    in_harvest = [False]  # cost-harvest re-lowering must not count as a compile

    @functools.wraps(fun)
    def _traced(*args, **kwargs):
        # runs once per jit cache entry (trace time), not per call
        if not in_harvest[0]:
            global_metrics().counter("executable_compiles").inc()
        return fun(*args, **kwargs)

    jitted = jax.jit(_traced, donate_argnums=donate, **jit_kwargs)

    def _harvest_cost(args, kwargs) -> None:
        # One fresh (shape, static-args) cache entry just traced: re-lower on
        # abstract shapes (donated operands may already be deleted — avals
        # survive deletion) and fold the pre-optimization HLO cost analysis
        # into the cost-model counters. One extra trace per shape bucket,
        # never a second backend compile; any failure (backend reports
        # nothing, AOT API drift) leaves the counters untouched. The extra
        # trace is skippable with CCTPU_NO_COST_ANALYSIS for hosts where even
        # once-per-bucket re-tracing is too much.
        if os.environ.get("CCTPU_NO_COST_ANALYSIS"):
            return
        try:
            def _aval(leaf):
                if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
                    return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype)
                return leaf

            sds = jax.tree_util.tree_map(_aval, (args, kwargs))
            in_harvest[0] = True
            try:
                cost = jitted.lower(*sds[0], **sds[1]).cost_analysis()
            finally:
                in_harvest[0] = False
        except Exception:
            return
        mets = global_metrics()
        for entry in cost if isinstance(cost, (list, tuple)) else (cost,):
            if not isinstance(entry, dict):
                continue
            for counter, key in (
                ("estimated_flops", "flops"),
                ("estimated_bytes_accessed", "bytes accessed"),
            ):
                v = entry.get(key)
                if v is not None and float(v) > 0:
                    mets.counter(counter).inc(float(v))

    @functools.wraps(fun)
    def wrapper(*args, **kwargs):
        leaves = jax.tree_util.tree_leaves((args, kwargs))
        if any(isinstance(leaf, jax.core.Tracer) for leaf in leaves):
            return fun(*args, **kwargs)  # inlining into an enclosing program
        mets = global_metrics()
        mets.counter("device_dispatches").inc()
        if donate:
            nbytes = 0
            for i in donate:
                if i < len(args):
                    for leaf in jax.tree_util.tree_leaves(args[i]):
                        nbytes += int(getattr(leaf, "nbytes", 0) or 0)
            mets.counter("donated_bytes").inc(nbytes)
        try:
            size_before = jitted._cache_size()
        except Exception:
            size_before = None
        out = jitted(*args, **kwargs)
        if size_before is not None:
            try:
                fresh_compile = jitted._cache_size() > size_before
            except Exception:
                fresh_compile = False
            if fresh_compile:
                _harvest_cost(args, kwargs)
        return out

    wrapper._counting_jitted = jitted  # escape hatch (lower/AOT, tests)
    # preserve the jax.jit introspection surface callers already rely on
    # (e.g. tests/test_buckets.py bounds _boot_batch._cache_size())
    for attr in ("_cache_size", "clear_cache", "lower", "trace", "eval_shape"):
        if hasattr(jitted, attr):
            setattr(wrapper, attr, getattr(jitted, attr))
    return wrapper


def enable_persistent_cache() -> bool:
    """Idempotently enable the on-disk XLA cache; True iff it is active."""
    global _done
    mets = global_metrics()
    mets.counter("compile_cache_enable_calls").inc()
    if _done or os.environ.get("CCTPU_NO_COMPILE_CACHE"):
        if not _done:
            # opted out: record the decision once so later (env-less) calls
            # stay no-ops and records show the cache state explicitly
            mets.gauge("compile_cache_enabled").set(0)
            _done = True
        return bool(mets.gauge("compile_cache_enabled").value)
    # XLA:CPU executable deserialization is unreliable (observed: SIGSEGV in
    # compilation_cache.get_executable_and_time on a cache hit written by the
    # SAME process's host, plus "machine features mismatch ... SIGILL"
    # warnings from the AOT loader). CPU compiles are cheap anyway — the
    # cache only pays for itself on accelerators, so enable it only there.
    if default_backend() == "cpu":
        mets.gauge("compile_cache_enabled").set(0)
        _done = True
        return False
    cache_dir = os.environ.get(
        "CCTPU_COMPILE_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "consensusclustr_tpu", "xla"),
    )
    enabled = False
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache even fast compiles: recursion levels re-enter many small jits
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        enabled = True
        # RunRecord accounting: entry count at enable time (a warm-cache
        # proxy — jax exposes no per-lookup hit counter); a later run with
        # entries > 0 started warm.
        try:
            mets.gauge("compile_cache_entries").set(len(os.listdir(cache_dir)))
        except OSError:
            pass
    except Exception:
        pass  # cache is an optimisation, never a requirement
    mets.gauge("compile_cache_enabled").set(1 if enabled else 0)
    _done = True
    return enabled
