"""Persistent XLA compilation cache.

The pipeline's jitted programs are keyed by (shape, static args); a fresh
process otherwise pays the full TPU compile (~20-40 s per program) again.
Pointing jax's compilation cache at a disk directory makes every rerun—and
every recursion level that repeats a shape—hit the cache across processes.

Enabled by the top-level API on first use; opt out with CCTPU_NO_COMPILE_CACHE
or redirect with CCTPU_COMPILE_CACHE_DIR.

Idempotency contract (ISSUE 3 satellite): ``enable_persistent_cache`` may be
called unconditionally from any entry point — the offline API, the serving
warm-up path, bench — and only the FIRST call does configuration work; every
call increments the ``compile_cache_enable_calls`` counter and the
``compile_cache_enabled`` gauge reflects the resolved state (1 active,
0 disabled: CPU backend, opt-out env, or setup failure) exactly once per
process. The function returns that resolved state so callers can log it.
"""

from __future__ import annotations

import os

import jax

from consensusclustr_tpu.obs import global_metrics
from consensusclustr_tpu.utils.backend import default_backend

_done = False


def enable_persistent_cache() -> bool:
    """Idempotently enable the on-disk XLA cache; True iff it is active."""
    global _done
    mets = global_metrics()
    mets.counter("compile_cache_enable_calls").inc()
    if _done or os.environ.get("CCTPU_NO_COMPILE_CACHE"):
        if not _done:
            # opted out: record the decision once so later (env-less) calls
            # stay no-ops and records show the cache state explicitly
            mets.gauge("compile_cache_enabled").set(0)
            _done = True
        return bool(mets.gauge("compile_cache_enabled").value)
    # XLA:CPU executable deserialization is unreliable (observed: SIGSEGV in
    # compilation_cache.get_executable_and_time on a cache hit written by the
    # SAME process's host, plus "machine features mismatch ... SIGILL"
    # warnings from the AOT loader). CPU compiles are cheap anyway — the
    # cache only pays for itself on accelerators, so enable it only there.
    if default_backend() == "cpu":
        mets.gauge("compile_cache_enabled").set(0)
        _done = True
        return False
    cache_dir = os.environ.get(
        "CCTPU_COMPILE_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "consensusclustr_tpu", "xla"),
    )
    enabled = False
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache even fast compiles: recursion levels re-enter many small jits
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        enabled = True
        # RunRecord accounting: entry count at enable time (a warm-cache
        # proxy — jax exposes no per-lookup hit counter); a later run with
        # entries > 0 started warm.
        try:
            mets.gauge("compile_cache_entries").set(len(os.listdir(cache_dir)))
        except OSError:
            pass
    except Exception:
        pass  # cache is an optimisation, never a requirement
    mets.gauge("compile_cache_enabled").set(1 if enabled else 0)
    _done = True
    return enabled
