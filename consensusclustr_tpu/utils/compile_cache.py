"""Persistent XLA compilation cache.

The pipeline's jitted programs are keyed by (shape, static args); a fresh
process otherwise pays the full TPU compile (~20-40 s per program) again.
Pointing jax's compilation cache at a disk directory makes every rerun—and
every recursion level that repeats a shape—hit the cache across processes.

Enabled by the top-level API on first use; opt out with CCTPU_NO_COMPILE_CACHE
or redirect with CCTPU_COMPILE_CACHE_DIR.

Idempotency contract (ISSUE 3 satellite): ``enable_persistent_cache`` may be
called unconditionally from any entry point — the offline API, the serving
warm-up path, bench — and only the FIRST call does configuration work; every
call increments the ``compile_cache_enable_calls`` counter and the
``compile_cache_enabled`` gauge reflects the resolved state (1 active,
0 disabled: CPU backend, opt-out env, or setup failure) exactly once per
process. The function returns that resolved state so callers can log it.
"""

from __future__ import annotations

import functools
import hashlib
import json
import os
import pickle
import threading
import time
from typing import Dict, Optional

import jax

from consensusclustr_tpu.obs import global_metrics
from consensusclustr_tpu.utils.backend import default_backend

_done = False
_cache_dir: Optional[str] = None  # resolved XLA cache dir once enabled


# ---------------------------------------------------------------------------
# Per-program cost attribution (ISSUE 16 tentpole front 1)
# ---------------------------------------------------------------------------
# The global counters above answer "how much did the run move"; this registry
# answers "which jitted program moved it". Every counting_jit entry point gets
# a row keyed by its function name (override with program_name=...), and every
# increment the wrapper folds into the global metrics is folded into the
# program's row at the same call site — so the rows sum to the global counters
# by construction, not by reconciliation. Field names are *_PROG constants so
# check_obs_schema/GL001 can pin them against obs.schema.PROGRAM_PROFILE_FIELDS
# both ways, and the set of decorated entry points is pinned against
# obs.schema.PROGRAM_NAMES (check_program_registry).

DISPATCHES_PROG = "dispatches"
COMPILES_PROG = "compiles"
FLOPS_PROG = "est_flops"
BYTES_PROG = "est_bytes"
DONATED_PROG = "donated_bytes"
WALL_PROG = "dispatch_wall_s"

# summable numeric fields of one program row, in report/rank order
_PROG_FIELDS = (
    DISPATCHES_PROG,
    COMPILES_PROG,
    FLOPS_PROG,
    BYTES_PROG,
    DONATED_PROG,
    WALL_PROG,
)
# per-shape-bucket sub-row fields (one bucket per fresh (shape, static) trace)
_BUCKET_FIELDS = (COMPILES_PROG, FLOPS_PROG, BYTES_PROG)

_prog_lock = threading.Lock()
_programs: Dict[str, dict] = {}


def _program_entry(name: str) -> dict:
    # callers hold _prog_lock
    entry = _programs.get(name)
    if entry is None:
        entry = {field: 0.0 for field in _PROG_FIELDS}
        entry["shapes"] = {}
        _programs[name] = entry
    return entry


def _shape_bucket_key(args, kwargs) -> str:
    """One dispatch's shape signature: dtype[dims] per array leaf, in tree
    order. Computed only on the fresh-compile path (compiles are rare)."""
    parts = []
    for leaf in jax.tree_util.tree_leaves((args, kwargs)):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            dims = ",".join(str(d) for d in leaf.shape)
            parts.append(f"{leaf.dtype}[{dims}]")
    return ";".join(parts) or "()"


def program_registry() -> Dict[str, dict]:
    """Deep-copied snapshot of the per-program registry (safe to mutate,
    usable as a ``since=`` window marker for :func:`program_profile`)."""
    with _prog_lock:
        return {
            name: {
                **{f: entry[f] for f in _PROG_FIELDS},
                "shapes": {k: dict(b) for k, b in entry["shapes"].items()},
            }
            for name, entry in _programs.items()
        }


def reset_program_registry() -> None:
    """Drop all program rows (tests / bench isolation)."""
    with _prog_lock:
        _programs.clear()


def _row_delta(cur: dict, base: dict) -> dict:
    out = {f: cur.get(f, 0) - base.get(f, 0) for f in _PROG_FIELDS}
    shapes = {}
    base_shapes = base.get("shapes", {})
    for key, bucket in cur.get("shapes", {}).items():
        prior = base_shapes.get(key, {})
        d = {f: bucket.get(f, 0) - prior.get(f, 0) for f in _BUCKET_FIELDS}
        if any(d.values()):
            shapes[key] = d
    out["shapes"] = shapes
    return out


def program_profile(since: Optional[Dict[str, dict]] = None,
                    top: Optional[int] = None,
                    shapes: bool = True) -> dict:
    """The RunRecord/bench ``program_profile`` block: per-program rows ranked
    by ``est_bytes`` (the O7 axis), plus totals that match the global
    ``estimated_*`` counter deltas over the same window by construction.

    ``since`` narrows to activity after a :func:`program_registry` snapshot
    (bench's headline window); ``top`` truncates the ranked rows (totals
    still cover every program); ``shapes=False`` drops the per-bucket
    sub-rows for lean payloads.
    """
    snap = program_registry()
    if since:
        snap = {
            name: _row_delta(entry, since.get(name, {}))
            for name, entry in snap.items()
        }
        snap = {
            name: entry for name, entry in snap.items()
            if any(entry[f] for f in _PROG_FIELDS)
        }
    totals = {f: 0.0 for f in _PROG_FIELDS}
    rows = []
    for name, entry in snap.items():
        row = {"name": name}
        for f in _PROG_FIELDS:
            v = entry[f]
            totals[f] += v
            row[f] = int(v) if f in (DISPATCHES_PROG, COMPILES_PROG,
                                     DONATED_PROG) else float(v)
        if shapes:
            row["shapes"] = {
                k: {**b, COMPILES_PROG: int(b.get(COMPILES_PROG, 0))}
                for k, b in entry.get("shapes", {}).items()
            }
        rows.append(row)
    rows.sort(key=lambda r: (-r[BYTES_PROG], r["name"]))
    n_programs = len(rows)
    if top is not None:
        rows = rows[:top]
    for f in (DISPATCHES_PROG, COMPILES_PROG, DONATED_PROG):
        totals[f] = int(totals[f])
    return {"programs": rows, "n_programs": n_programs, "totals": totals}


def counting_jit(fun=None, *, donate_argnums=(), **jit_kwargs):
    """``jax.jit`` with dispatch/compile/donation accounting (ISSUE 5).

    Wraps the pipeline's TOP-LEVEL jitted entry programs and counts, in the
    process-global metrics registry (obs/schema.py):

      * ``device_dispatches`` — calls that launch an executable. A call made
        while an enclosing program is being traced inlines into that program
        and is NOT counted (that is the point of fusing: fewer dispatches).
      * ``executable_compiles`` — traces, i.e. new (shape, static-args) cache
        entries. One per shape bucket; counted even when the persistent XLA
        cache serves the binary (a trace is the compile-shaped host work the
        accounting is meant to expose).
      * ``donated_bytes`` — bytes of operand buffers handed to the executable
        via ``donate_argnums`` per dispatch (in-place carry updates: the
        consensus accumulator, per-chunk key/index slices).
      * ``estimated_flops`` / ``estimated_bytes_accessed`` (ISSUE 6) — XLA
        ``cost_analysis`` of each freshly traced shape bucket, one execution's
        worth per compile. Harvested from the *lowered* (pre-optimization)
        HLO — no second backend compile — and tolerant of backends that
        report nothing: the counters simply stay at 0. This is O4's
        FLOP/byte denominator next to the dispatch counts.

    The counters cover exactly the functions wrapped here — the per-boot hot
    path and its chunk drivers — not every small jit in the package, so
    bench deltas are stable, gateable program counts (tools/bench_diff.py
    ``--gate compiles:...`` / ``--gate rss:...``).

    ISSUE 16: every increment is ALSO attributed to the wrapped program in
    the per-program registry (``program_registry`` / ``program_profile``),
    keyed by the function's name (override with ``program_name=...``), plus
    per-program host-side dispatch wall and per-shape-bucket cost rows —
    so "14.96 GB moved" decomposes into a ranked table whose rows sum to
    the global counters by construction.
    """
    if fun is None:
        return functools.partial(
            counting_jit, donate_argnums=donate_argnums, **jit_kwargs
        )
    prog = str(
        jit_kwargs.pop("program_name", None)
        or getattr(fun, "__name__", None)
        or "<anonymous>"
    )
    donate = tuple(donate_argnums)
    in_harvest = [False]  # cost-harvest re-lowering must not count as a compile

    @functools.wraps(fun)
    def _traced(*args, **kwargs):
        # runs once per jit cache entry (trace time), not per call
        if not in_harvest[0]:
            global_metrics().counter("executable_compiles").inc()
            with _prog_lock:
                _program_entry(prog)[COMPILES_PROG] += 1
        return fun(*args, **kwargs)

    jitted = jax.jit(_traced, donate_argnums=donate, **jit_kwargs)

    def _harvest_cost(args, kwargs) -> None:
        # One fresh (shape, static-args) cache entry just traced: re-lower on
        # abstract shapes (donated operands may already be deleted — avals
        # survive deletion) and fold the pre-optimization HLO cost analysis
        # into the cost-model counters. One extra trace per shape bucket,
        # never a second backend compile; any failure (backend reports
        # nothing, AOT API drift) leaves the counters untouched. The extra
        # trace is skippable with CCTPU_NO_COST_ANALYSIS for hosts where even
        # once-per-bucket re-tracing is too much.
        if os.environ.get("CCTPU_NO_COST_ANALYSIS"):
            return
        try:
            def _aval(leaf):
                if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
                    return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype)
                return leaf

            sds = jax.tree_util.tree_map(_aval, (args, kwargs))
            in_harvest[0] = True
            try:
                cost = jitted.lower(*sds[0], **sds[1]).cost_analysis()
            finally:
                in_harvest[0] = False
        except Exception:  # graftlint: noqa[GL007] cost analysis is an optional metric source, never a requirement
            return
        mets = global_metrics()
        total = {FLOPS_PROG: 0.0, BYTES_PROG: 0.0}
        for entry in cost if isinstance(cost, (list, tuple)) else (cost,):
            if not isinstance(entry, dict):
                continue
            for counter, key, field in (
                ("estimated_flops", "flops", FLOPS_PROG),
                ("estimated_bytes_accessed", "bytes accessed", BYTES_PROG),
            ):
                v = entry.get(key)
                if v is not None and float(v) > 0:
                    mets.counter(counter).inc(float(v))
                    total[field] += float(v)
        # fold the SAME values into the program row + its shape bucket, so
        # the per-program table sums exactly to the global counters
        bucket_key = _shape_bucket_key(args, kwargs)
        with _prog_lock:
            entry = _program_entry(prog)
            entry[FLOPS_PROG] += total[FLOPS_PROG]
            entry[BYTES_PROG] += total[BYTES_PROG]
            bucket = entry["shapes"].setdefault(
                bucket_key, {f: 0.0 for f in _BUCKET_FIELDS}
            )
            bucket[COMPILES_PROG] += 1
            bucket[FLOPS_PROG] += total[FLOPS_PROG]
            bucket[BYTES_PROG] += total[BYTES_PROG]

    @functools.wraps(fun)
    def wrapper(*args, **kwargs):
        leaves = jax.tree_util.tree_leaves((args, kwargs))
        if any(isinstance(leaf, jax.core.Tracer) for leaf in leaves):
            return fun(*args, **kwargs)  # inlining into an enclosing program
        mets = global_metrics()
        mets.counter("device_dispatches").inc()
        nbytes = 0
        if donate:
            for i in donate:
                if i < len(args):
                    for leaf in jax.tree_util.tree_leaves(args[i]):
                        nbytes += int(getattr(leaf, "nbytes", 0) or 0)
            mets.counter("donated_bytes").inc(nbytes)
        try:
            size_before = jitted._cache_size()
        except Exception:  # graftlint: noqa[GL007] cache-size introspection uses private jax API; absence just skips the compile counter
            size_before = None
        t0 = time.perf_counter()
        out = jitted(*args, **kwargs)
        wall = time.perf_counter() - t0
        with _prog_lock:
            entry = _program_entry(prog)
            entry[DISPATCHES_PROG] += 1
            entry[DONATED_PROG] += nbytes
            entry[WALL_PROG] += wall
        if size_before is not None:
            try:
                fresh_compile = jitted._cache_size() > size_before
            except Exception:  # graftlint: noqa[GL007] cache-size introspection uses private jax API; absence just skips the compile counter
                fresh_compile = False
            if fresh_compile:
                _harvest_cost(args, kwargs)
        return out

    wrapper._counting_jitted = jitted  # escape hatch (lower/AOT, tests)
    # preserve the jax.jit introspection surface callers already rely on
    # (e.g. tests/test_buckets.py bounds _boot_batch._cache_size())
    for attr in ("_cache_size", "clear_cache", "lower", "trace", "eval_shape"):
        if hasattr(jitted, attr):
            setattr(wrapper, attr, getattr(jitted, attr))
    return wrapper


def enable_persistent_cache() -> bool:
    """Idempotently enable the on-disk XLA cache; True iff it is active."""
    global _done, _cache_dir
    mets = global_metrics()
    mets.counter("compile_cache_enable_calls").inc()
    if _done or os.environ.get("CCTPU_NO_COMPILE_CACHE"):
        if not _done:
            # opted out: record the decision once so later (env-less) calls
            # stay no-ops and records show the cache state explicitly
            mets.gauge("compile_cache_enabled").set(0)
            _done = True
        return bool(mets.gauge("compile_cache_enabled").value)
    # XLA:CPU executable deserialization is unreliable (observed: SIGSEGV in
    # compilation_cache.get_executable_and_time on a cache hit written by the
    # SAME process's host, plus "machine features mismatch ... SIGILL"
    # warnings from the AOT loader). CPU compiles are cheap anyway — the
    # cache only pays for itself on accelerators, so enable it only there.
    if default_backend() == "cpu":
        mets.gauge("compile_cache_enabled").set(0)
        _done = True
        return False
    cache_dir = os.environ.get(
        "CCTPU_COMPILE_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "consensusclustr_tpu", "xla"),
    )
    enabled = False
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache even fast compiles: recursion levels re-enter many small jits
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        enabled = True
        _cache_dir = cache_dir
        # RunRecord accounting: entry count at enable time (a warm-cache
        # proxy — jax exposes no per-lookup hit counter); a later run with
        # entries > 0 started warm. Re-sampled at run-record attach
        # (refresh_cache_entries_gauge) so RunRecord shows post-run state.
        try:
            mets.gauge("compile_cache_entries").set(len(os.listdir(cache_dir)))
        except OSError:
            pass
    except Exception:  # graftlint: noqa[GL007] persistent compile cache is an optimisation, never a requirement
        pass  # cache is an optimisation, never a requirement
    mets.gauge("compile_cache_enabled").set(1 if enabled else 0)
    _done = True
    return enabled


def refresh_cache_entries_gauge() -> Optional[int]:
    """Re-sample ``compile_cache_entries`` from the active cache directory.

    ``enable_persistent_cache`` samples the gauge once at enable time, which
    meant a RunRecord attached at run END still showed the PRE-run entry
    count — entries written by the current run were invisible to the
    warm-start proxy. RunRecord.from_tracer calls this just before snapshotting
    metrics so the record reflects post-run state. Returns the fresh count,
    or None when no persistent cache is active (the gauge is then left as
    the enable path set it)."""
    if _cache_dir is None:
        return None
    try:
        count = len(os.listdir(_cache_dir))
    except OSError:
        return None
    global_metrics().gauge("compile_cache_entries").set(count)
    return count


# ---------------------------------------------------------------------------
# Cross-process AOT executable cache (ISSUE 13 tentpole front 3)
# ---------------------------------------------------------------------------
# The persistent XLA cache above stores compiled *binaries*, but a fresh
# process still pays the full trace (tracing + lowering, the dominant serving
# warm-up cost on CPU/TPU alike) before the binary lookup can even happen.
# jax.experimental.serialize_executable round-trips the COMPILED executable —
# trace, lowering and binary — so a warm process can skip straight to a
# loaded callable. Entries are keyed by (artifact sha256, bucket, jax
# version, backend): any drift in any component simply misses (a different
# key), and a present-but-unloadable entry is a LOUD fallback (warning +
# aot_fallbacks counter), never a crash — trace-from-scratch is always
# correct.

AOT_CACHE_VERSION = 1


def aot_cache_dir() -> str:
    """The AOT executable cache directory (CCTPU_AOT_CACHE_DIR overrides)."""
    return os.environ.get(
        "CCTPU_AOT_CACHE_DIR",
        os.path.join(
            os.path.expanduser("~"), ".cache", "consensusclustr_tpu", "aot"
        ),
    )


def aot_key(artifact_sha: str, bucket: int, **extra) -> str:
    """Deterministic cache key for one serving executable: the reference
    artifact hash, the padded batch bucket, the jax version and backend (an
    executable is only loadable into the runtime that serialized it), plus
    any extra static identity the caller bakes in (k, n_classes, ...)."""
    ident = {
        "v": AOT_CACHE_VERSION,
        "artifact_sha": str(artifact_sha),
        "bucket": int(bucket),
        "jax": jax.__version__,
        "backend": default_backend(),
        **{k: extra[k] for k in sorted(extra)},
    }
    return hashlib.sha256(
        json.dumps(ident, sort_keys=True).encode()
    ).hexdigest()[:32]


def _aot_path(key: str) -> str:
    return os.path.join(aot_cache_dir(), f"{key}.aotx")


def aot_save(key: str, compiled) -> Optional[str]:
    """Serialize a jax ``Compiled`` to the AOT cache (atomic tmp+rename).
    Returns the path, or None on any failure (serialization is an
    optimisation; the counter ``aot_cache_saves`` tracks successes)."""
    try:
        from jax.experimental import serialize_executable as _se

        payload, in_tree, out_tree = _se.serialize(compiled)
        blob = pickle.dumps(
            {
                "v": AOT_CACHE_VERSION,
                "jax": jax.__version__,
                "backend": default_backend(),
                "key": key,
                "payload": payload,
                "in_tree": in_tree,
                "out_tree": out_tree,
            }
        )
        path = _aot_path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
        global_metrics().counter("aot_cache_saves").inc()
        return path
    except Exception:  # graftlint: noqa[GL007] AOT cache save is best-effort; a failed save costs a recompile, not a run
        return None


def aot_load(key: str):
    """Deserialize-and-link the executable cached under ``key``; None on a
    miss. A PRESENT entry that fails to load (corrupt file, jax/backend
    mismatch inside the blob, deserializer drift) is the loud fallback: it
    warns, bumps ``aot_fallbacks``, and returns None so the caller traces
    from scratch. Hits/misses land on ``aot_cache_hits`` /
    ``aot_cache_misses``."""
    mets = global_metrics()
    path = _aot_path(key)
    if not os.path.isfile(path):
        mets.counter("aot_cache_misses").inc()
        return None
    try:
        with open(path, "rb") as f:
            blob = pickle.load(f)
        if (
            blob.get("v") != AOT_CACHE_VERSION
            or blob.get("jax") != jax.__version__
            or blob.get("backend") != default_backend()
            or blob.get("key") != key
        ):
            raise ValueError(
                f"AOT entry identity mismatch (entry: jax={blob.get('jax')} "
                f"backend={blob.get('backend')}; runtime: jax={jax.__version__} "
                f"backend={default_backend()})"
            )
        from jax.experimental import serialize_executable as _se

        loaded = _se.deserialize_and_load(
            blob["payload"], blob["in_tree"], blob["out_tree"]
        )
        mets.counter("aot_cache_hits").inc()
        return loaded
    except Exception as e:
        import warnings

        mets.counter("aot_fallbacks").inc()
        warnings.warn(
            f"AOT executable cache entry {os.path.basename(path)} failed to "
            f"load — falling back to trace ({type(e).__name__}: {e})",
            RuntimeWarning,
        )
        return None
