"""Synthetic scRNA-seq count generators for benchmarks and statistical tests.

The reference's only executable verification artifacts are roxygen examples
built on `rpois` matrices (SURVEY §4); these generators are the realistic
upgrade: negative-binomial counts with per-cell depth variation, gene-level
dispersion, and planted populations — the pbmc3k-shaped fixture BASELINE
config 1 calls for (2,700 cells, ~90% sparsity).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def nb_mixture_counts(
    n_cells: int = 2700,
    n_genes: int = 2000,
    n_populations: int = 6,
    de_frac: float = 0.08,
    de_lfc: float = 1.6,
    depth_sd: float = 0.35,
    mean_shape: float = 0.4,
    mean_scale: float = 1.0,
    dispersion: float = 1.2,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Planted NB mixture with per-cell depth variation.

    Marginals follow the standard scRNA model: per-gene base rate mu_g from a
    gamma (most genes lowly expressed -> realistic sparsity), per-population
    log-fold changes on a random `de_frac` of genes, per-cell depth factor
    lognormal(0, depth_sd), counts ~ NB(mean = depth * mu, size = dispersion)
    drawn as gamma-Poisson. Population sizes are unequal (probability decays
    geometrically) as in real tissue.

    Returns (counts [n_cells, n_genes] float32, labels [n_cells] int32).
    """
    r = np.random.default_rng(seed)
    mu_g = r.gamma(shape=mean_shape, scale=mean_scale, size=n_genes)

    p = 0.75 ** np.arange(n_populations)
    p /= p.sum()
    labels = r.choice(n_populations, size=n_cells, p=p)

    lfc = np.zeros((n_populations, n_genes))
    for c in range(n_populations):
        de = r.random(n_genes) < de_frac
        signs = r.choice([-1.0, 1.0], size=de.sum())
        lfc[c, de] = signs * r.uniform(de_lfc * 0.5, de_lfc, size=de.sum())
    mu = mu_g[None, :] * np.exp(lfc)[labels]              # [n, g]

    depth = np.exp(r.normal(0.0, depth_sd, size=n_cells))
    mu = mu * depth[:, None]

    lam = r.gamma(shape=dispersion, scale=mu / dispersion)
    counts = r.poisson(lam).astype(np.float32)
    return counts, labels.astype(np.int32)


def realistic_10x_counts(
    n_cells: int = 600,
    n_genes: int = 500,
    n_populations: int = 4,
    de_frac: float = 0.12,
    de_lfc: float = 1.8,
    doublet_frac: float = 0.04,
    ambient_frac: float = 0.08,
    depth_gradient: float = 0.5,
    seed: int = 7,
):
    """NB mixture plus the three droplet-protocol artifacts real 10x runs
    carry (VERDICT r4 missing #4: no network in this sandbox, so the fixture
    models realism instead of downloading it):

      * **doublets** — `doublet_frac` of droplets captured two cells; their
        counts are the sum of two independently drawn cells (biased toward
        cross-population pairs, the detectable kind). Labels keep the first
        cell's identity — as in real data, doublets arrive unannotated.
      * **ambient RNA** — every droplet's mean gains `ambient_frac` of a
        shared "soup" profile (the depth-weighted average expression of all
        cells, which is what lysed-cell mRNA pooling produces).
      * **library-size gradient** — a log-linear depth trend across barcode
        order (chip-loading / cell-size drift), on top of the lognormal
        per-cell depth noise.

    Returns (counts [n, g] float32, labels [n] int32, doublet_mask [n] bool).
    Quality metrics should score singlets only (mask out doublets).
    """
    r = np.random.default_rng(seed)
    counts, labels = nb_mixture_counts(
        n_cells=n_cells, n_genes=n_genes, n_populations=n_populations,
        de_frac=de_frac, de_lfc=de_lfc, seed=seed,
    )

    # library-size gradient across barcode order
    gradient = np.exp(depth_gradient * np.linspace(-1.0, 1.0, n_cells))
    counts = r.binomial(
        counts.astype(np.int64), np.clip(gradient, None, 1.0)[:, None]
    ) + r.poisson(counts * np.clip(gradient - 1.0, 0.0, None)[:, None])
    counts = counts.astype(np.float32)

    # ambient soup: resample ambient_frac of each droplet's mean from the
    # global depth-weighted profile
    soup = counts.mean(axis=0)
    soup = soup / max(soup.sum(), 1e-9)
    depth_per_cell = counts.sum(axis=1)
    counts += r.poisson(
        ambient_frac * depth_per_cell[:, None] * soup[None, :]
    ).astype(np.float32)

    # doublets: overwrite the tail fraction of droplets with two-cell sums,
    # pairing across populations when possible
    n_dbl = int(round(doublet_frac * n_cells))
    doublet_mask = np.zeros(n_cells, bool)
    if n_dbl:
        hosts = r.choice(n_cells, size=n_dbl, replace=False)
        partners = np.empty(n_dbl, np.int64)
        for i, h in enumerate(hosts):
            other = np.flatnonzero(labels != labels[h])
            pool = other if other.size else np.arange(n_cells)
            partners[i] = r.choice(pool)
        counts[hosts] = counts[hosts] + counts[partners]
        doublet_mask[hosts] = True

    return counts, labels.astype(np.int32), doublet_mask


def pure_noise_counts(
    n_cells: int = 500, n_genes: int = 800, seed: int = 0
) -> np.ndarray:
    """Single-population NB counts — the null-calibration fixture (the
    reference's own examples are this, as rpois; README.md:13)."""
    counts, _ = nb_mixture_counts(
        n_cells=n_cells, n_genes=n_genes, n_populations=1, de_frac=0.0,
        seed=seed,
    )
    return counts
