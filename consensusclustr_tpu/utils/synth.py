"""Synthetic scRNA-seq count generators for benchmarks and statistical tests.

The reference's only executable verification artifacts are roxygen examples
built on `rpois` matrices (SURVEY §4); these generators are the realistic
upgrade: negative-binomial counts with per-cell depth variation, gene-level
dispersion, and planted populations — the pbmc3k-shaped fixture BASELINE
config 1 calls for (2,700 cells, ~90% sparsity).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def nb_mixture_counts(
    n_cells: int = 2700,
    n_genes: int = 2000,
    n_populations: int = 6,
    de_frac: float = 0.08,
    de_lfc: float = 1.6,
    depth_sd: float = 0.35,
    mean_shape: float = 0.4,
    mean_scale: float = 1.0,
    dispersion: float = 1.2,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Planted NB mixture with per-cell depth variation.

    Marginals follow the standard scRNA model: per-gene base rate mu_g from a
    gamma (most genes lowly expressed -> realistic sparsity), per-population
    log-fold changes on a random `de_frac` of genes, per-cell depth factor
    lognormal(0, depth_sd), counts ~ NB(mean = depth * mu, size = dispersion)
    drawn as gamma-Poisson. Population sizes are unequal (probability decays
    geometrically) as in real tissue.

    Returns (counts [n_cells, n_genes] float32, labels [n_cells] int32).
    """
    r = np.random.default_rng(seed)
    mu_g = r.gamma(shape=mean_shape, scale=mean_scale, size=n_genes)

    p = 0.75 ** np.arange(n_populations)
    p /= p.sum()
    labels = r.choice(n_populations, size=n_cells, p=p)

    lfc = np.zeros((n_populations, n_genes))
    for c in range(n_populations):
        de = r.random(n_genes) < de_frac
        signs = r.choice([-1.0, 1.0], size=de.sum())
        lfc[c, de] = signs * r.uniform(de_lfc * 0.5, de_lfc, size=de.sum())
    mu = mu_g[None, :] * np.exp(lfc)[labels]              # [n, g]

    depth = np.exp(r.normal(0.0, depth_sd, size=n_cells))
    mu = mu * depth[:, None]

    lam = r.gamma(shape=dispersion, scale=mu / dispersion)
    counts = r.poisson(lam).astype(np.float32)
    return counts, labels.astype(np.int32)


def pure_noise_counts(
    n_cells: int = 500, n_genes: int = 800, seed: int = 0
) -> np.ndarray:
    """Single-population NB counts — the null-calibration fixture (the
    reference's own examples are this, as rpois; README.md:13)."""
    counts, _ = nb_mixture_counts(
        n_cells=n_cells, n_genes=n_genes, n_populations=1, de_frac=0.0,
        seed=seed,
    )
    return counts
