"""Structured per-level observability (compatibility layer).

The reference's only observability is one message("Failed Test")
(reference R/consensusClust.R:613). The build plan (SURVEY §5) called for a
structured per-level log; that grew into the full ``obs/`` subsystem
(hierarchical spans + metrics + RunRecords). ``LevelLog`` remains the
interface every call site already uses, now as a thin shim over
``obs.Tracer``: ``event(...)`` feeds the tracer's flat record stream and
``records`` aliases it, so pre-obs code and tests keep working unchanged.

``get_logger`` is plain stdlib logging so the package never prints unless
asked; ``CCTPU_LOG_LEVEL`` (name like "DEBUG" or a number) overrides the
level.
"""

from __future__ import annotations

import logging
import os
from typing import Any, List, Optional

from consensusclustr_tpu.obs.tracer import Tracer

_HANDLER_MARK = "_cctpu_handler"


def get_logger(name: str = "consensusclustr_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    # Marker-based dedup: `logging.getLogger` returns the same object across
    # repeated import/reload, but checking `logger.handlers` truthiness would
    # still double-add ours next to any handler another library attached.
    if not any(getattr(h, _HANDLER_MARK, False) for h in logger.handlers):
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter("[%(name)s] %(message)s"))
        setattr(handler, _HANDLER_MARK, True)
        logger.addHandler(handler)
        logger.propagate = False
    env_level = os.environ.get("CCTPU_LOG_LEVEL", "").strip()
    if env_level:
        try:
            logger.setLevel(
                int(env_level) if env_level.isdigit() else env_level.upper()
            )
        except ValueError:
            logger.setLevel(logging.INFO)
    elif logger.level == logging.NOTSET:
        logger.setLevel(logging.INFO)
    return logger


class LevelLog:
    """Append-only record of what happened at one recursion level.

    Thin compatibility shim over ``obs.Tracer``: the constructor signature
    (``records``, ``enabled``, ``_t0``) matches the original dataclass, and
    ``records`` is the live tracer event list. Pass ``tracer=`` to wrap an
    existing tracer (bench.py does); ``child()`` shares the tracer so
    recursion levels append to one stream, as before.
    """

    def __init__(
        self,
        records: Optional[List[dict]] = None,
        enabled: bool = False,
        _t0: Optional[float] = None,
        tracer: Optional[Tracer] = None,
    ) -> None:
        if tracer is None:
            tracer = Tracer(progress=enabled)
            if records is not None:
                tracer.events = records
            if _t0 is not None:
                tracer.epoch = _t0
        elif enabled:
            tracer.progress = True
        self.tracer = tracer
        self.enabled = enabled or tracer.progress

    @property
    def records(self) -> List[dict]:
        return self.tracer.events

    @property
    def _t0(self) -> float:
        return self.tracer.epoch

    def event(self, kind: str, **fields: Any) -> None:
        self.tracer.event(kind, **fields)

    def span(self, name: str, **attrs: Any):
        return self.tracer.span(name, **attrs)

    def child(self) -> "LevelLog":
        return LevelLog(enabled=self.enabled, tracer=self.tracer)


def _jsonable(x: Any):
    try:
        import numpy as np

        if isinstance(x, (np.integer,)):
            return int(x)
        if isinstance(x, (np.floating,)):
            return float(x)
        if isinstance(x, np.ndarray):
            return x.tolist()
    except Exception:  # graftlint: noqa[GL007] JSON sanitizer fallback: logging about a logging failure would recurse
        pass
    return str(x)


__all__ = ["LevelLog", "get_logger", "_jsonable"]
