"""Structured per-level observability.

The reference's only observability is one message("Failed Test")
(reference R/consensusClust.R:613). The build plan (SURVEY §5) calls for a
structured per-level log: cells, pcNum, candidate scores, best silhouette,
p-values, merges. ``LevelLog`` collects those records; ``get_logger`` is plain
stdlib logging so the package never prints unless asked.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import time
from typing import Any, Dict, List, Optional


def get_logger(name: str = "consensusclustr_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter("[%(name)s] %(message)s"))
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
        logger.propagate = False
    return logger


@dataclasses.dataclass
class LevelLog:
    """Append-only record of what happened at one recursion level."""

    records: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    enabled: bool = False
    _t0: float = dataclasses.field(default_factory=time.monotonic)

    def event(self, kind: str, **fields: Any) -> None:
        rec = {"t": round(time.monotonic() - self._t0, 4), "kind": kind, **fields}
        self.records.append(rec)
        if self.enabled:
            get_logger().info(json.dumps(rec, default=_jsonable))

    def child(self) -> "LevelLog":
        return LevelLog(records=self.records, enabled=self.enabled, _t0=self._t0)


def _jsonable(x: Any):
    try:
        import numpy as np

        if isinstance(x, (np.integer,)):
            return int(x)
        if isinstance(x, (np.floating,)):
            return float(x)
        if isinstance(x, np.ndarray):
            return x.tolist()
    except Exception:
        pass
    return str(x)
