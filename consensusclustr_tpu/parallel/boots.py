"""Bootstrap fan-out sharded over the device mesh.

Distributed form of consensus/pipeline.py's ``run_bootstraps`` — the TPU
counterpart of the reference's `bplapply(1:nboots)` worker pool
(reference R/consensusClust.R:388-400; SURVEY §2.4 row 1): bootstraps are
data-parallel over the FLATTENED ("boot", "cell") mesh — every device in the
2-D mesh owns a distinct slice of the boot axis, so no compute is duplicated
across the cell axis; the PCA matrix is replicated (it is small — n x pcNum);
each device runs the full kNN->SNN->Leiden grid for its local bootstraps via
the same jitted kernels as the single-chip path. The co-clustering stage then
reshards the labels to boot-axis-only layout (one all-gather over "cell").

Like the reference's share-nothing workers, no communication happens here —
the assignments stay boot-sharded and flow straight into the sharded
co-clustering psum (parallel/cocluster.py).

Determinism: per-boot keys are folded from the global boot id (utils/rng.py),
so assignments are bit-identical regardless of mesh shape.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from consensusclustr_tpu.cluster.engine import (
    DEFAULT_COMMUNITY_ITERS,
    align_to_cells,
    cluster_grid,
    ties_last_argmax,
)
from consensusclustr_tpu.parallel.mesh import BOOT_AXIS, CELL_AXIS
from consensusclustr_tpu.utils.compile_cache import counting_jit


@counting_jit(
    static_argnames=(
        "mesh", "k_list", "max_clusters", "n_iters", "n_cells", "cluster_fun",
        "compute_dtype",
    ),
)
def sharded_run_bootstraps_granular(
    keys: jax.Array,       # [B] per-boot PRNG keys
    idx: jax.Array,        # [B, m] int32 bootstrap gathers
    pca: jax.Array,        # [n, d] float32, replicated
    res_list: jax.Array,   # [R]
    mesh: jax.sharding.Mesh,
    k_list: Tuple[int, ...],
    max_clusters: int,
    n_cells: int,
    n_iters: int = DEFAULT_COMMUNITY_ITERS,
    cluster_fun: str = "leiden",
    compute_dtype: str = "float32",
) -> Tuple[jax.Array, jax.Array]:
    """Granular-mode bootstraps over the mesh: EVERY (k, resolution)
    candidate of every bootstrap is kept (reference :688), aligned to cells.

    Returns (labels [B, |k|*R, n] int32 with -1 for unsampled, scores
    [B, |k|*R]), boot axis sharded over the flattened ("boot", "cell") mesh.
    """
    n_dev = mesh.shape[BOOT_AXIS] * mesh.shape[CELL_AXIS]
    if idx.shape[0] % n_dev:
        raise ValueError(
            f"B={idx.shape[0]} not divisible by device count {n_dev}"
        )

    def kernel(keys_local, idx_local, pca_rep, res_rep):
        def one(key_b, idx_b):
            x = pca_rep[idx_b]
            grid = cluster_grid(
                key_b, x, res_rep, k_list, jnp.float32(0.0),
                max_clusters=max_clusters, n_iters=n_iters,
                cluster_fun=cluster_fun, compute_dtype=compute_dtype,
            )
            aligned = align_to_cells(grid.labels, idx_b, n_cells)  # [cand, n]
            return aligned, grid.scores

        return jax.vmap(one)(keys_local, idx_local)

    both = (BOOT_AXIS, CELL_AXIS)
    return jax.shard_map(
        kernel,
        mesh=mesh,
        in_specs=(P(both), P(both, None), P(None, None), P(None)),
        out_specs=(P(both, None, None), P(both, None)),
    )(keys, idx, jnp.asarray(pca, jnp.float32), jnp.asarray(res_list, jnp.float32))


@counting_jit(
    static_argnames=(
        "mesh", "k_list", "max_clusters", "n_iters", "n_cells", "cluster_fun",
        "compute_dtype"
    ),
)
def sharded_run_bootstraps(
    keys: jax.Array,       # [B] per-boot PRNG keys
    idx: jax.Array,        # [B, m] int32 bootstrap gathers
    pca: jax.Array,        # [n, d] float32, replicated
    res_list: jax.Array,   # [R]
    mesh: jax.sharding.Mesh,
    k_list: Tuple[int, ...],
    max_clusters: int,
    n_cells: int,
    n_iters: int = DEFAULT_COMMUNITY_ITERS,
    cluster_fun: str = "leiden",
    compute_dtype: str = "float32",
) -> Tuple[jax.Array, jax.Array]:
    """Robust-mode bootstraps over the mesh.

    Returns (labels [B, n] int32 with -1 for unsampled, scores [B]), sharded
    over the flattened ("boot", "cell") mesh axes. B must divide by the total
    device count.
    """
    n_dev = mesh.shape[BOOT_AXIS] * mesh.shape[CELL_AXIS]
    if idx.shape[0] % n_dev:
        raise ValueError(
            f"B={idx.shape[0]} not divisible by device count {n_dev}"
        )

    def kernel(keys_local, idx_local, pca_rep, res_rep):
        def one(key_b, idx_b):
            x = pca_rep[idx_b]
            grid = cluster_grid(
                key_b, x, res_rep, k_list, jnp.float32(0.0),
                max_clusters=max_clusters, n_iters=n_iters,
                cluster_fun=cluster_fun, compute_dtype=compute_dtype,
            )
            best = ties_last_argmax(grid.scores)
            aligned = align_to_cells(grid.labels[best], idx_b, n_cells)
            return aligned, grid.scores[best]

        return jax.vmap(one)(keys_local, idx_local)

    both = (BOOT_AXIS, CELL_AXIS)
    return jax.shard_map(
        kernel,
        mesh=mesh,
        in_specs=(P(both), P(both, None), P(None, None), P(None)),
        out_specs=(P(both, None), P(both)),
    )(keys, idx, jnp.asarray(pca, jnp.float32), jnp.asarray(res_list, jnp.float32))
