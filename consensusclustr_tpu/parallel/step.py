"""The fused distributed consensus step.

This is the framework's "training step" analog: one jitted program over the
("boot", "cell") mesh that runs the whole device-side consensus pipeline
(reference R/consensusClust.R:388-456; SURVEY §3.1 hot loops 1-2):

  bootstrap grid clustering   — data-parallel over "boot" (parallel/boots.py)
  co-clustering counts        — MXU matmuls, psum over "boot", rows sharded
                                over "cell" (parallel/cocluster.py)
  consensus kNN               — local top_k per row block (parallel/knn.py)
  SNN + Leiden res sweep      — resolution axis sharded over "boot"
  candidate selection         — argmax over gathered scores

Collectives used: one psum (co-clustering counts), the all-gather XLA inserts
to replicate the [n, k] kNN graph, and the all-gathers implied by the sharded
resolution sweep's outputs. Everything rides ICI inside a slice.

RNG tags match the single-chip path (consensus/pipeline.py), so given the same
inputs the distributed step selects bit-identical candidates on any mesh
shape — the determinism contract of SURVEY §4 item 5.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from consensusclustr_tpu.cluster.engine import (
    DEFAULT_COMMUNITY_ITERS,
    community_detect,
    consensus_candidate_score,
)
from consensusclustr_tpu.cluster.leiden import compact_labels
from consensusclustr_tpu.cluster.snn import snn_graph
from consensusclustr_tpu.config import ClusterConfig
from consensusclustr_tpu.consensus.bootstrap import bootstrap_indices
from consensusclustr_tpu.parallel.boots import (
    sharded_run_bootstraps,
    sharded_run_bootstraps_granular,
)
from consensusclustr_tpu.parallel.cocluster import (
    sharded_blockwise_consensus_knn,
    sharded_coclustering_distance,
)
from consensusclustr_tpu.obs import metrics_of
from consensusclustr_tpu.parallel.knn import sharded_knn_from_distance
from consensusclustr_tpu.parallel.mesh import BOOT_AXIS, CELL_AXIS
from consensusclustr_tpu.utils.compile_cache import counting_jit
from consensusclustr_tpu.utils.rng import cluster_key


@functools.partial(
    jax.jit,  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
    static_argnames=("mesh", "ki", "n_res", "max_clusters", "n_iters", "cluster_fun"),
)
def _consensus_grid_sharded(
    keys: jax.Array,       # [R] PRNG keys (global resolution order)
    knn_idx: jax.Array,    # [n, k] int32 consensus kNN graph
    pca: jax.Array,        # [n, d] for silhouette ranking
    res_list: jax.Array,   # [R] resolutions (padded to a multiple of boot axis)
    res_mask: jax.Array,   # [R] 1.0 for real entries, 0.0 for padding
    mesh: jax.sharding.Mesh,
    ki: int,
    n_res: int,
    max_clusters: int,
    n_iters: int = DEFAULT_COMMUNITY_ITERS,
    cluster_fun: str = "leiden",
) -> Tuple[jax.Array, jax.Array]:
    """Leiden/Louvain over the resolution sweep, res axis sharded over the flattened
    ("boot", "cell") mesh — every device owns distinct resolutions.

    Returns (labels [R, n] int32, scores [R] with -inf at padding).
    """
    del ki, n_res  # tags live in `keys`; kept in the signature for cache keys

    def kernel(keys_local, res_local, mask_local, idx_rep, pca_rep):
        graph = snn_graph(idx_rep)

        def one_res(kk, res, mask):
            raw = community_detect(kk, graph, res, cluster_fun, n_iters=n_iters)
            compact, n_c, overflow = compact_labels(raw, max_clusters)
            score = consensus_candidate_score(pca_rep, compact, n_c, overflow, max_clusters)
            return compact, jnp.where(mask > 0, score, -jnp.inf)

        return jax.vmap(one_res)(keys_local, res_local, mask_local)

    both = (BOOT_AXIS, CELL_AXIS)
    return jax.shard_map(
        kernel,
        mesh=mesh,
        in_specs=(P(both), P(both), P(both), P(None, None), P(None, None)),
        out_specs=(P(both, None), P(both)),
    )(keys, res_list, res_mask, knn_idx, pca)


class DistributedStepResult(NamedTuple):
    labels: jax.Array       # [n] best consensus candidate (replicated)
    scores: jax.Array       # [K*R_pad] candidate scores (-inf at padding)
    dist: Optional[jax.Array]  # [n, n] co-clustering distance (row-sharded);
    #                            None in the blockwise (dense=False) regime
    boot_labels: jax.Array  # [B_pad, n] aligned boot assignments (boot-sharded)


@counting_jit(
    static_argnames=(
        "mesh", "k_list", "max_clusters", "n_iters", "cluster_fun", "dense",
    ),
)
def _consensus_tail_sharded(
    key: jax.Array,
    pca: jax.Array,          # [n, d] float32
    boot_labels: jax.Array,  # [B_rows, n] int32 (-1 masked); B_rows % n_dev == 0
    res_list: jax.Array,     # [R_pad]
    res_mask: jax.Array,     # [R_pad]
    mesh: jax.sharding.Mesh,
    k_list: Tuple[int, ...],
    max_clusters: int,
    n_iters: int = DEFAULT_COMMUNITY_ITERS,
    cluster_fun: str = "leiden",
    dense: bool = True,
):
    """Everything downstream of the boot fan-out: co-clustering counts,
    consensus kNN, SNN + community grid, candidate selection. Split out so the
    checkpointed path can feed boot labels restored from disk; the fused step
    inlines this same function, so both paths run identical ops (boot labels
    are integers — no float drift across the phase boundary)."""
    if dense:
        dist = sharded_coclustering_distance(boot_labels, mesh, max_clusters)
        knn_all, _ = sharded_knn_from_distance(dist, mesh, max(k_list))
    else:
        # scale regime: no [n, n] anywhere — rows stream past a local top-k
        dist = None
        knn_all, _ = sharded_blockwise_consensus_knn(
            boot_labels, mesh, max(k_list), max_clusters
        )

    all_labels, all_scores = [], []
    r_pad = res_list.shape[0]
    for ki, k in enumerate(k_list):
        # smaller-k graphs are prefixes of the max-k one (deterministic
        # top_k order), mirroring the single-chip _consensus_grid_from_knn
        knn_idx = knn_all[:, :k]
        # same RNG tags as the single-chip _consensus_grid (pipeline.py)
        gkeys = jax.vmap(
            lambda t: cluster_key(key, 90_000 + ki * 1000 + t)
        )(jnp.arange(r_pad, dtype=jnp.int32))
        labels_k, scores_k = _consensus_grid_sharded(
            gkeys, knn_idx, pca, res_list, res_mask, mesh, ki, r_pad,
            max_clusters, n_iters, cluster_fun=cluster_fun,
        )
        all_labels.append(labels_k)
        all_scores.append(scores_k)
    labels = jnp.concatenate(all_labels, axis=0)
    scores = jnp.concatenate(all_scores, axis=0)
    best = jnp.argmax(scores)   # ties -> first, as in the single-chip path
    return labels[best], scores, dist


@counting_jit(
    static_argnames=(
        "mesh", "k_list", "max_clusters", "n_iters", "n_res_real", "cluster_fun",
        "compute_dtype", "dense", "granular",
    ),
)
def distributed_consensus_step(
    key: jax.Array,
    pca: jax.Array,        # [n, d] float32
    idx: jax.Array,        # [B_pad, m] int32 bootstrap gathers
    res_list: jax.Array,   # [R_pad]
    res_mask: jax.Array,   # [R_pad]
    n_real_boots: jax.Array,  # scalar: boots beyond this are padding
    mesh: jax.sharding.Mesh,
    k_list: Tuple[int, ...],
    max_clusters: int,
    n_res_real: int,
    n_iters: int = DEFAULT_COMMUNITY_ITERS,
    cluster_fun: str = "leiden",
    compute_dtype: str = "float32",
    dense: bool = True,
    granular: bool = False,
) -> DistributedStepResult:
    n, _ = pca.shape
    b_pad = idx.shape[0]

    keys = jax.vmap(lambda b: cluster_key(key, 50_000 + b))(jnp.arange(b_pad, dtype=jnp.int32))
    if granular:
        # every (k, res) candidate of every bootstrap joins the consensus
        # (reference :688); the flattened candidate axis feeds the same
        # sharded co-clustering as robust mode's boot axis
        labels_g, _ = sharded_run_bootstraps_granular(
            keys, idx, pca, res_list[:n_res_real], mesh, k_list,
            max_clusters, n, n_iters=n_iters, cluster_fun=cluster_fun,
            compute_dtype=compute_dtype,
        )
        labels_g = jnp.where(
            (jnp.arange(b_pad, dtype=jnp.int32) < n_real_boots)[:, None, None], labels_g, -1
        )
        boot_labels = labels_g.reshape(-1, n)          # [B_pad * |k|*R, n]
    else:
        boot_labels, _ = sharded_run_bootstraps(
            keys, idx, pca, res_list[:n_res_real], mesh, k_list,
            max_clusters, n, n_iters=n_iters, cluster_fun=cluster_fun,
            compute_dtype=compute_dtype,
        )
        # padding boots contribute nothing to the co-clustering counts
        boot_labels = jnp.where(
            (jnp.arange(b_pad, dtype=jnp.int32) < n_real_boots)[:, None], boot_labels, -1
        )
    best_labels, scores, dist = _consensus_tail_sharded(
        key, pca, boot_labels, res_list, res_mask, mesh, k_list, max_clusters,
        n_iters=n_iters, cluster_fun=cluster_fun, dense=dense,
    )
    return DistributedStepResult(
        labels=best_labels, scores=scores, dist=dist, boot_labels=boot_labels
    )


def distributed_consensus_cluster(
    key: jax.Array,
    pca: np.ndarray,
    cfg: ClusterConfig,
    mesh: jax.sharding.Mesh,
    return_dist: bool = True,
    dense: bool = True,
    log=None,
) -> Tuple[np.ndarray, Optional[np.ndarray], np.ndarray]:
    """Host wrapper: pad the boot and resolution axes to the mesh, run the
    fused step, return (labels [n], dist [n, n] or None, boot_labels as
    numpy — [B, n] in robust mode, [B * |k|*|res|, n] in granular mode,
    exactly the single-chip run_bootstraps layouts).

    n must divide by the mesh's "cell" extent (the row-sharding granularity).
    `return_dist=False` skips the host gather of the dense distance matrix —
    required at the scales where the matrix only exists row-sharded (the
    downstream merges then run on the boot labels / kNN graph instead).

    With cfg.checkpoint_dir set, the boot fan-out runs chunked with per-chunk
    persistence and resume (robust AND granular) instead of as one fused
    program; results are bit-identical either way.
    """
    pca = jnp.asarray(pca, jnp.float32)
    n = pca.shape[0]
    dc = mesh.shape[CELL_AXIS]
    n_dev = mesh.shape[BOOT_AXIS] * dc
    if n % dc:
        raise ValueError(f"n={n} must divide by the cell mesh axis ({dc})")

    m = max(2, int(round(cfg.boot_size * n)))
    b_pad = -(-cfg.nboots // n_dev) * n_dev
    idx = bootstrap_indices(key, n, b_pad, m)

    res = list(cfg.res_range)
    r_real = len(res)
    r_pad = -(-r_real // n_dev) * n_dev
    res_arr = jnp.asarray(res + [res[-1]] * (r_pad - r_real), jnp.float32)
    res_mask = jnp.asarray([1.0] * r_real + [0.0] * (r_pad - r_real), jnp.float32)

    granular = cfg.mode == "granular"
    k_list = tuple(int(k) for k in cfg.k_num)
    n_real_rows = cfg.nboots * (
        len(cfg.k_num) * r_real if granular else 1
    )

    if cfg.checkpoint_dir:
        labels_np, dist_dev, boot_rows = _checkpointed_distributed_run(
            key, pca, idx, res_arr, res_mask, mesh, cfg, k_list, r_real,
            dense=dense, granular=granular, log=log,
        )
        return (
            labels_np,
            np.asarray(dist_dev) if (return_dist and dist_dev is not None) else None,
            boot_rows[:n_real_rows],
        )

    out = distributed_consensus_step(
        key, pca, idx, res_arr, res_mask, jnp.int32(cfg.nboots), mesh,
        k_list, cfg.max_clusters, r_real,
        cluster_fun=cfg.cluster_fun, compute_dtype=cfg.compute_dtype,
        dense=dense, granular=granular,
    )
    metrics_of(log).counter("boots_completed").inc(cfg.nboots)
    return (
        np.asarray(out.labels),
        np.asarray(out.dist) if (return_dist and out.dist is not None) else None,
        np.asarray(out.boot_labels[:n_real_rows]),
    )


def _ckpt_chunk_boots(b_pad: int, n_dev: int) -> int:
    """Boots per persisted chunk: a multiple of the device count (the shard
    granularity), defaulting to the smallest multiple >= 32 so a 1000-boot run
    leaves ~32 resume points. CCTPU_CKPT_CHUNK overrides (rounded up)."""
    import os

    want = int(os.environ.get("CCTPU_CKPT_CHUNK", "32"))
    chunk = -(-max(1, want) // n_dev) * n_dev
    return min(b_pad, chunk)


def _checkpointed_distributed_run(
    key: jax.Array,
    pca: jax.Array,
    idx: jax.Array,
    res_arr: jax.Array,
    res_mask: jax.Array,
    mesh: jax.sharding.Mesh,
    cfg: ClusterConfig,
    k_list: Tuple[int, ...],
    r_real: int,
    dense: bool,
    granular: bool,
    log=None,
):
    """Distributed run with a persistable chunk boundary (SURVEY §5 checkpoint
    row; VERDICT r3 next #3): the sharded boot fan-out runs in chunks along
    the padded boot axis, each chunk's aligned labels land on disk before the
    next starts, and a rerun resumes at the first missing chunk. Granular mode
    checkpoints the flattened candidate axis (|k|*|res| rows per boot).

    The fingerprint hashes every determinant of a chunk's content — including
    b_pad (device-count-derived) — but NOT the mesh layout (per-boot labels
    are bit-identical across mesh shapes, the determinism contract, so a
    (boot=8, cell=1) run may resume chunks written by a (boot=2, cell=4) run
    on the same 8 devices) and NOT the chunk size (chunks are shape-validated
    on load, so changing CCTPU_CKPT_CHUNK between runs reuses aligned chunks
    rather than orphaning them all)."""
    from consensusclustr_tpu.parallel.mesh import BOOT_AXIS as _BA, CELL_AXIS as _CA
    from consensusclustr_tpu.utils.checkpoint import (
        BootCheckpoint,
        run_fingerprint,
    )

    n = pca.shape[0]
    b_pad = idx.shape[0]
    n_dev = mesh.shape[_BA] * mesh.shape[_CA]
    chunk_boots = _ckpt_chunk_boots(b_pad, n_dev)
    rows_per_boot = len(k_list) * r_real if granular else 1

    fp = run_fingerprint(
        np.asarray(pca),
        {
            "distributed": True, "mode": cfg.mode,
            "nboots": cfg.nboots, "b_pad": b_pad, "boot_size": cfg.boot_size,
            "k_num": list(k_list), "res_range": [float(r) for r in cfg.res_range],
            # chunk size deliberately not hashed: chunks are validated by
            # shape on load, so a resume under a different CCTPU_CKPT_CHUNK
            # reuses aligned chunks instead of orphaning the run (ADVICE r4)
            "max_clusters": cfg.max_clusters,
            "cluster_fun": cfg.cluster_fun, "compute_dtype": cfg.compute_dtype,
            "n_iters": DEFAULT_COMMUNITY_ITERS,
        },
        np.asarray(jax.random.key_data(key)).tobytes(),
    )
    ckpt = BootCheckpoint(
        cfg.checkpoint_dir, fp, b_pad, n, rows_per_boot=rows_per_boot
    )

    keys = jax.vmap(lambda b: cluster_key(key, 50_000 + b))(jnp.arange(b_pad, dtype=jnp.int32))
    chunks = []
    for s in range(0, b_pad, chunk_boots):
        e = min(s + chunk_boots, b_pad)
        cached = ckpt.load_chunk(s, e - s)
        if cached is not None:
            chunks.append(cached[0])
            metrics_of(log).counter("boots_resumed").inc(e - s)
            if log:
                log.event("boots_resumed", done=e, total=b_pad, distributed=True)
            continue
        if granular:
            lab, sc = sharded_run_bootstraps_granular(
                keys[s:e], idx[s:e], pca, res_arr[:r_real], mesh, k_list,
                cfg.max_clusters, n, cluster_fun=cfg.cluster_fun,
                compute_dtype=cfg.compute_dtype,
            )
            lab_np = np.asarray(lab).reshape(-1, n)    # [(e-s)*|k|*R, n]
        else:
            lab, sc = sharded_run_bootstraps(
                keys[s:e], idx[s:e], pca, res_arr[:r_real], mesh, k_list,
                cfg.max_clusters, n, cluster_fun=cfg.cluster_fun,
                compute_dtype=cfg.compute_dtype,
            )
            lab_np = np.asarray(lab)
        ckpt.save_chunk(s, lab_np, np.asarray(sc).reshape(-1))
        chunks.append(lab_np)
        metrics_of(log).counter("boots_completed").inc(e - s)
        if log:
            log.event("boots", done=e, total=b_pad, distributed=True)

    boot_rows = np.concatenate(chunks, axis=0)          # [b_pad*rpb, n]
    # padding boots contribute nothing to the co-clustering counts — the same
    # mask the fused step applies before its reshape
    boot_id = np.repeat(np.arange(b_pad), rows_per_boot)
    boot_rows = np.where(
        (boot_id < cfg.nboots)[:, None], boot_rows, np.int32(-1)
    ).astype(np.int32)
    best_labels, _, dist = _consensus_tail_sharded(
        key, pca, jnp.asarray(boot_rows), res_arr, res_mask, mesh, k_list,
        cfg.max_clusters, cluster_fun=cfg.cluster_fun, dense=dense,
    )
    return np.asarray(best_labels), dist, boot_rows
