"""The fused distributed consensus step.

This is the framework's "training step" analog: one jitted program over the
("boot", "cell") mesh that runs the whole device-side consensus pipeline
(reference R/consensusClust.R:388-456; SURVEY §3.1 hot loops 1-2):

  bootstrap grid clustering   — data-parallel over "boot" (parallel/boots.py)
  co-clustering counts        — MXU matmuls, psum over "boot", rows sharded
                                over "cell" (parallel/cocluster.py)
  consensus kNN               — local top_k per row block (parallel/knn.py)
  SNN + Leiden res sweep      — resolution axis sharded over "boot"
  candidate selection         — argmax over gathered scores

Collectives used: one psum (co-clustering counts), the all-gather XLA inserts
to replicate the [n, k] kNN graph, and the all-gathers implied by the sharded
resolution sweep's outputs. Everything rides ICI inside a slice.

RNG tags match the single-chip path (consensus/pipeline.py), so given the same
inputs the distributed step selects bit-identical candidates on any mesh
shape — the determinism contract of SURVEY §4 item 5.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from consensusclustr_tpu.cluster.engine import (
    DEFAULT_COMMUNITY_ITERS,
    community_detect,
    consensus_candidate_score,
)
from consensusclustr_tpu.cluster.leiden import compact_labels
from consensusclustr_tpu.cluster.snn import snn_graph
from consensusclustr_tpu.config import ClusterConfig
from consensusclustr_tpu.consensus.bootstrap import bootstrap_indices
from consensusclustr_tpu.parallel.boots import (
    sharded_run_bootstraps,
    sharded_run_bootstraps_granular,
)
from consensusclustr_tpu.parallel.cocluster import (
    sharded_blockwise_consensus_knn,
    sharded_coclustering_distance,
)
from consensusclustr_tpu.parallel.knn import sharded_knn_from_distance
from consensusclustr_tpu.parallel.mesh import BOOT_AXIS, CELL_AXIS
from consensusclustr_tpu.utils.rng import cluster_key


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "ki", "n_res", "max_clusters", "n_iters", "cluster_fun"),
)
def _consensus_grid_sharded(
    keys: jax.Array,       # [R] PRNG keys (global resolution order)
    knn_idx: jax.Array,    # [n, k] int32 consensus kNN graph
    pca: jax.Array,        # [n, d] for silhouette ranking
    res_list: jax.Array,   # [R] resolutions (padded to a multiple of boot axis)
    res_mask: jax.Array,   # [R] 1.0 for real entries, 0.0 for padding
    mesh: jax.sharding.Mesh,
    ki: int,
    n_res: int,
    max_clusters: int,
    n_iters: int = DEFAULT_COMMUNITY_ITERS,
    cluster_fun: str = "leiden",
) -> Tuple[jax.Array, jax.Array]:
    """Leiden/Louvain over the resolution sweep, res axis sharded over the flattened
    ("boot", "cell") mesh — every device owns distinct resolutions.

    Returns (labels [R, n] int32, scores [R] with -inf at padding).
    """
    del ki, n_res  # tags live in `keys`; kept in the signature for cache keys

    def kernel(keys_local, res_local, mask_local, idx_rep, pca_rep):
        graph = snn_graph(idx_rep)

        def one_res(kk, res, mask):
            raw = community_detect(kk, graph, res, cluster_fun, n_iters=n_iters)
            compact, n_c, overflow = compact_labels(raw, max_clusters)
            score = consensus_candidate_score(pca_rep, compact, n_c, overflow, max_clusters)
            return compact, jnp.where(mask > 0, score, -jnp.inf)

        return jax.vmap(one_res)(keys_local, res_local, mask_local)

    both = (BOOT_AXIS, CELL_AXIS)
    return jax.shard_map(
        kernel,
        mesh=mesh,
        in_specs=(P(both), P(both), P(both), P(None, None), P(None, None)),
        out_specs=(P(both, None), P(both)),
    )(keys, res_list, res_mask, knn_idx, pca)


class DistributedStepResult(NamedTuple):
    labels: jax.Array       # [n] best consensus candidate (replicated)
    scores: jax.Array       # [K*R_pad] candidate scores (-inf at padding)
    dist: Optional[jax.Array]  # [n, n] co-clustering distance (row-sharded);
    #                            None in the blockwise (dense=False) regime
    boot_labels: jax.Array  # [B_pad, n] aligned boot assignments (boot-sharded)


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh", "k_list", "max_clusters", "n_iters", "n_res_real", "cluster_fun",
        "compute_dtype", "dense", "granular",
    ),
)
def distributed_consensus_step(
    key: jax.Array,
    pca: jax.Array,        # [n, d] float32
    idx: jax.Array,        # [B_pad, m] int32 bootstrap gathers
    res_list: jax.Array,   # [R_pad]
    res_mask: jax.Array,   # [R_pad]
    n_real_boots: jax.Array,  # scalar: boots beyond this are padding
    mesh: jax.sharding.Mesh,
    k_list: Tuple[int, ...],
    max_clusters: int,
    n_res_real: int,
    n_iters: int = DEFAULT_COMMUNITY_ITERS,
    cluster_fun: str = "leiden",
    compute_dtype: str = "float32",
    dense: bool = True,
    granular: bool = False,
) -> DistributedStepResult:
    n, _ = pca.shape
    b_pad = idx.shape[0]

    keys = jax.vmap(lambda b: cluster_key(key, 50_000 + b))(jnp.arange(b_pad))
    if granular:
        # every (k, res) candidate of every bootstrap joins the consensus
        # (reference :688); the flattened candidate axis feeds the same
        # sharded co-clustering as robust mode's boot axis
        labels_g, _ = sharded_run_bootstraps_granular(
            keys, idx, pca, res_list[:n_res_real], mesh, k_list,
            max_clusters, n, n_iters=n_iters, cluster_fun=cluster_fun,
            compute_dtype=compute_dtype,
        )
        labels_g = jnp.where(
            (jnp.arange(b_pad) < n_real_boots)[:, None, None], labels_g, -1
        )
        boot_labels = labels_g.reshape(-1, n)          # [B_pad * |k|*R, n]
    else:
        boot_labels, _ = sharded_run_bootstraps(
            keys, idx, pca, res_list[:n_res_real], mesh, k_list,
            max_clusters, n, n_iters=n_iters, cluster_fun=cluster_fun,
            compute_dtype=compute_dtype,
        )
        # padding boots contribute nothing to the co-clustering counts
        boot_labels = jnp.where(
            (jnp.arange(b_pad) < n_real_boots)[:, None], boot_labels, -1
        )
    if dense:
        dist = sharded_coclustering_distance(boot_labels, mesh, max_clusters)
        knn_all, _ = sharded_knn_from_distance(dist, mesh, max(k_list))
    else:
        # scale regime: no [n, n] anywhere — rows stream past a local top-k
        dist = None
        knn_all, _ = sharded_blockwise_consensus_knn(
            boot_labels, mesh, max(k_list), max_clusters
        )

    all_labels, all_scores = [], []
    r_pad = res_list.shape[0]
    for ki, k in enumerate(k_list):
        # smaller-k graphs are prefixes of the max-k one (deterministic
        # top_k order), mirroring the single-chip _consensus_grid_from_knn
        knn_idx = knn_all[:, :k]
        # same RNG tags as the single-chip _consensus_grid (pipeline.py)
        gkeys = jax.vmap(
            lambda t: cluster_key(key, 90_000 + ki * 1000 + t)
        )(jnp.arange(r_pad))
        labels_k, scores_k = _consensus_grid_sharded(
            gkeys, knn_idx, pca, res_list, res_mask, mesh, ki, r_pad,
            max_clusters, n_iters, cluster_fun=cluster_fun,
        )
        all_labels.append(labels_k)
        all_scores.append(scores_k)
    labels = jnp.concatenate(all_labels, axis=0)
    scores = jnp.concatenate(all_scores, axis=0)
    best = jnp.argmax(scores)   # ties -> first, as in the single-chip path
    return DistributedStepResult(
        labels=labels[best], scores=scores, dist=dist, boot_labels=boot_labels
    )


def distributed_consensus_cluster(
    key: jax.Array,
    pca: np.ndarray,
    cfg: ClusterConfig,
    mesh: jax.sharding.Mesh,
    return_dist: bool = True,
    dense: bool = True,
) -> Tuple[np.ndarray, Optional[np.ndarray], np.ndarray]:
    """Host wrapper: pad the boot and resolution axes to the mesh, run the
    fused step, return (labels [n], dist [n, n] or None, boot_labels as
    numpy — [B, n] in robust mode, [B * |k|*|res|, n] in granular mode,
    exactly the single-chip run_bootstraps layouts).

    n must divide by the mesh's "cell" extent (the row-sharding granularity).
    `return_dist=False` skips the host gather of the dense distance matrix —
    required at the scales where the matrix only exists row-sharded (the
    downstream merges then run on the boot labels / kNN graph instead).
    """
    pca = jnp.asarray(pca, jnp.float32)
    n = pca.shape[0]
    dc = mesh.shape[CELL_AXIS]
    n_dev = mesh.shape[BOOT_AXIS] * dc
    if n % dc:
        raise ValueError(f"n={n} must divide by the cell mesh axis ({dc})")

    m = max(2, int(round(cfg.boot_size * n)))
    b_pad = -(-cfg.nboots // n_dev) * n_dev
    idx = bootstrap_indices(key, n, b_pad, m)

    res = list(cfg.res_range)
    r_real = len(res)
    r_pad = -(-r_real // n_dev) * n_dev
    res_arr = jnp.asarray(res + [res[-1]] * (r_pad - r_real), jnp.float32)
    res_mask = jnp.asarray([1.0] * r_real + [0.0] * (r_pad - r_real), jnp.float32)

    granular = cfg.mode == "granular"
    out = distributed_consensus_step(
        key, pca, idx, res_arr, res_mask, jnp.int32(cfg.nboots), mesh,
        tuple(int(k) for k in cfg.k_num), cfg.max_clusters, r_real,
        cluster_fun=cfg.cluster_fun, compute_dtype=cfg.compute_dtype,
        dense=dense, granular=granular,
    )
    n_real_rows = cfg.nboots * (
        len(cfg.k_num) * r_real if granular else 1
    )
    return (
        np.asarray(out.labels),
        np.asarray(out.dist) if (return_dist and out.dist is not None) else None,
        np.asarray(out.boot_labels[:n_real_rows]),
    )
