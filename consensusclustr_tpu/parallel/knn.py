"""Distributed k-nearest-neighbour search.

Two regimes (SURVEY §5 long-context row — the cell dimension is this
framework's seq-length analog, and the remedies mirror ring attention):

* ``sharded_knn_from_distance`` — the consensus path (reference
  R/consensusClust.R:425): the distance matrix is already row-sharded over the
  mesh's "cell" axis (parallel/cocluster.py), so each device takes a local
  ``top_k`` over its row block; no communication at all.

* ``ring_knn`` — the raw-point path for cell counts where even one n x n tile
  pass per device is too big to hold against a replicated point set: the point
  set is sharded over "cell", and block tiles circulate around the ring via
  ``ppermute`` (one hop per step, bandwidth rides ICI) while every device
  maintains a running top-k merge of its rows against each arriving tile —
  exactly ring attention's schedule with (distance, top-k-merge) in place of
  (logits, softmax-accumulate).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from consensusclustr_tpu.parallel.mesh import CELL_AXIS


@functools.partial(jax.jit, static_argnames=("mesh", "k"))  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
def sharded_knn_from_distance(
    dist: jax.Array,            # [n, n] row-sharded over "cell"
    mesh: jax.sharding.Mesh,
    k: int,
) -> Tuple[jax.Array, jax.Array]:
    """Top-k per row of a row-sharded distance matrix (self excluded).

    Returns (idx [n, k] int32, dist [n, k]) sharded the same way as the input
    rows. Pure local compute: columns are complete within each row block.
    """
    n = dist.shape[0]
    n_cell = mesh.shape[CELL_AXIS]
    n_rows = n // n_cell

    def kernel(block):
        row_start = jax.lax.axis_index(CELL_AXIS).astype(jnp.int32) * n_rows
        rows = row_start + jnp.arange(n_rows, dtype=jnp.int32)
        d = block.at[jnp.arange(n_rows, dtype=jnp.int32), rows].set(jnp.inf)
        neg, idx = jax.lax.top_k(-d, k)
        return idx.astype(jnp.int32), -neg

    return jax.shard_map(
        kernel, mesh=mesh, in_specs=P(CELL_AXIS, None),
        out_specs=(P(CELL_AXIS, None), P(CELL_AXIS, None)),
    )(dist)


def _merge_topk(
    best_d: jax.Array, best_i: jax.Array, cand_d: jax.Array, cand_i: jax.Array, k: int
):
    """Merge two (dist, idx) candidate sets into the k smallest per row."""
    d = jnp.concatenate([best_d, cand_d], axis=1)
    i = jnp.concatenate([best_i, cand_i], axis=1)
    neg, pos = jax.lax.top_k(-d, k)
    return -neg, jnp.take_along_axis(i, pos, axis=1)


@functools.partial(jax.jit, static_argnames=("mesh", "k"))  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
def ring_knn(
    x: jax.Array,               # [n, d] row-sharded over "cell"
    mesh: jax.sharding.Mesh,
    k: int,
) -> Tuple[jax.Array, jax.Array]:
    """Exact Euclidean kNN of a point set sharded over the "cell" axis.

    Returns (idx [n, k] int32 into the global point order, dist [n, k]),
    row-sharded like the input. Each of the D ring steps moves one [n/D, d]
    tile one hop (ppermute) and fuses an [n/D, n/D] distance tile (MXU matmul)
    with a running top-k merge, so peak memory is O(n^2/D^2) per device.
    """
    n = x.shape[0]
    n_cell = mesh.shape[CELL_AXIS]
    n_rows = n // n_cell
    perm = [(i, (i + 1) % n_cell) for i in range(n_cell)]

    def kernel(x_local):
        me = jax.lax.axis_index(CELL_AXIS).astype(jnp.int32)
        my_sq = jnp.sum(x_local * x_local, axis=1)            # [n_rows]
        row_ids = me * n_rows + jnp.arange(n_rows, dtype=jnp.int32)

        def tile_topk(tile, tile_owner):
            tile_sq = jnp.sum(tile * tile, axis=1)
            d2 = my_sq[:, None] - 2.0 * (x_local @ tile.T) + tile_sq[None, :]
            d2 = jnp.maximum(d2, 0.0)
            col_ids = tile_owner * n_rows + jnp.arange(n_rows, dtype=jnp.int32)
            d2 = jnp.where(row_ids[:, None] == col_ids[None, :], jnp.inf, d2)
            neg, pos = jax.lax.top_k(-d2, min(k, n_rows))
            idx = col_ids[pos]
            if n_rows < k:  # pad so the running merge has fixed width
                pad = k - n_rows
                neg = jnp.concatenate([neg, jnp.full((n_rows, pad), -jnp.inf, jnp.float32)], axis=1)
                idx = jnp.concatenate([idx, jnp.repeat(idx[:, -1:], pad, axis=1)], axis=1)
            return -neg, idx

        def step(carry, _):
            tile, owner, best_d, best_i = carry
            cand_d, cand_i = tile_topk(tile, owner)
            best_d, best_i = _merge_topk(best_d, best_i, cand_d, cand_i, k)
            tile = jax.lax.ppermute(tile, CELL_AXIS, perm)
            owner = jax.lax.ppermute(owner, CELL_AXIS, perm)
            return (tile, owner, best_d, best_i), None

        init_d = jax.lax.pcast(jnp.full((n_rows, k), jnp.inf, jnp.float32), (CELL_AXIS,), to="varying")
        init_i = jax.lax.pcast(jnp.zeros((n_rows, k), jnp.int32), (CELL_AXIS,), to="varying")
        (_, _, best_d, best_i), _ = jax.lax.scan(
            step, (x_local, me, init_d, init_i), None, length=n_cell
        )
        return best_i, jnp.sqrt(best_d)

    return jax.shard_map(
        kernel, mesh=mesh, in_specs=P(CELL_AXIS, None),
        out_specs=(P(CELL_AXIS, None), P(CELL_AXIS, None)),
    )(jnp.asarray(x, jnp.float32))
