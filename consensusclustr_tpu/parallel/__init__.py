"""Distributed execution layer: device meshes, sharded kernels, collectives.

The TPU counterpart of the reference's BiocParallel process pools + OpenMP
threads (SURVEY §2.4): shard_map over a ("boot", "cell") Mesh, psum for the
co-clustering counts, ppermute for the ring kNN.
"""

from consensusclustr_tpu.parallel.mesh import (
    BOOT_AXIS,
    CELL_AXIS,
    consensus_mesh,
    factor_devices,
)
from consensusclustr_tpu.parallel.boots import sharded_run_bootstraps
from consensusclustr_tpu.parallel.pipelined import (
    AsyncChunkWriter,
    ChunkPipeline,
    pipeline_depth,
)
from consensusclustr_tpu.parallel.cocluster import sharded_coclustering_distance
from consensusclustr_tpu.parallel.knn import ring_knn, sharded_knn_from_distance
from consensusclustr_tpu.parallel.step import (
    DistributedStepResult,
    distributed_consensus_cluster,
    distributed_consensus_step,
)

__all__ = [
    "BOOT_AXIS",
    "CELL_AXIS",
    "consensus_mesh",
    "factor_devices",
    "sharded_run_bootstraps",
    "sharded_coclustering_distance",
    "AsyncChunkWriter",
    "ChunkPipeline",
    "pipeline_depth",
    "ring_knn",
    "sharded_knn_from_distance",
    "DistributedStepResult",
    "distributed_consensus_cluster",
    "distributed_consensus_step",
]
