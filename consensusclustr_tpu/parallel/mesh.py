"""Device-mesh construction for the distributed consensus pipeline.

The reference's only parallel substrate is BiocParallel process pools with
zero inter-worker traffic (reference R/consensusClust.R:391, README.md:41-45;
SURVEY §2.4). The TPU counterpart is a 2-D ``jax.sharding.Mesh``:

  * axis ``"boot"`` — data parallelism over bootstrap resamples (the analog of
    the reference's `bplapply(1:nboots)` worker pool);
  * axis ``"cell"`` — model parallelism over rows of the n x n co-clustering
    matrix (the reference's OpenMP-threaded parDist pass, :421, which is the
    memory wall at scale — SURVEY §5 long-context row).

Collectives ride ICI inside a slice: one ``psum`` over "boot" accumulates the
co-clustering counts (the design's single true all-reduce, SURVEY §2.4), and
``ppermute`` over "cell" drives the ring kNN for sharded point sets.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh

BOOT_AXIS = "boot"
CELL_AXIS = "cell"


def shard_map_capability() -> Tuple[bool, str]:
    """Can this environment run the sharded (shard_map) paths at all?

    The distributed step is written against the ``jax.shard_map`` /
    varying-manual-axes API (``jax.lax.pcast``) and needs more than one
    local device for sharding to mean anything. Returns ``(ok, reason)``
    with ``reason`` naming the first missing capability. The tier-1 suite
    uses this to *skip* the sharded tests with an explicit environment
    reason — a red sharded test should mean broken code, not a CPU sandbox
    whose jax predates the API.
    """
    if not hasattr(jax, "shard_map"):
        return False, f"jax.shard_map not in jax {jax.__version__}"
    if not hasattr(jax.lax, "pcast"):
        return False, (
            f"jax.lax.pcast (varying-manual-axes API) not in jax {jax.__version__}"
        )
    try:
        n = len(jax.devices())
    except Exception as e:  # backend init failed: nothing to shard over  # graftlint: noqa[GL007] capability probe: the error is returned to the caller as the unavailability reason
        return False, f"device enumeration failed: {type(e).__name__}: {e}"
    if n < 2:
        return False, f"needs >= 2 local devices, found {n}"
    return True, ""


def factor_devices(n_devices: int) -> Tuple[int, int]:
    """Split a device count into (boot, cell) mesh extents.

    Prefers a balanced 2-D mesh (boot >= cell) so both the bootstrap fan-out
    and the n x n matrix rows shard; falls back to all-boot for primes.
    """
    best = (n_devices, 1)
    for cell in range(1, int(np.sqrt(n_devices)) + 1):
        if n_devices % cell == 0:
            best = (n_devices // cell, cell)
    return best


def consensus_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    boot: Optional[int] = None,
    cell: Optional[int] = None,
) -> Mesh:
    """Build the ("boot", "cell") mesh over the given (default: all) devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if boot is None or cell is None:
        boot, cell = factor_devices(n)
    if boot * cell != n:
        raise ValueError(f"mesh {boot}x{cell} != {n} devices")
    dev_array = np.asarray(devices).reshape(boot, cell)
    return Mesh(dev_array, (BOOT_AXIS, CELL_AXIS))
