"""Device-mesh construction for the distributed consensus pipeline.

The reference's only parallel substrate is BiocParallel process pools with
zero inter-worker traffic (reference R/consensusClust.R:391, README.md:41-45;
SURVEY §2.4). The TPU counterpart is a 2-D ``jax.sharding.Mesh``:

  * axis ``"boot"`` — data parallelism over bootstrap resamples (the analog of
    the reference's `bplapply(1:nboots)` worker pool);
  * axis ``"cell"`` — model parallelism over rows of the n x n co-clustering
    matrix (the reference's OpenMP-threaded parDist pass, :421, which is the
    memory wall at scale — SURVEY §5 long-context row).

Collectives ride ICI inside a slice: one ``psum`` over "boot" accumulates the
co-clustering counts (the design's single true all-reduce, SURVEY §2.4), and
``ppermute`` over "cell" drives the ring kNN for sharded point sets.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh

BOOT_AXIS = "boot"
CELL_AXIS = "cell"


def factor_devices(n_devices: int) -> Tuple[int, int]:
    """Split a device count into (boot, cell) mesh extents.

    Prefers a balanced 2-D mesh (boot >= cell) so both the bootstrap fan-out
    and the n x n matrix rows shard; falls back to all-boot for primes.
    """
    best = (n_devices, 1)
    for cell in range(1, int(np.sqrt(n_devices)) + 1):
        if n_devices % cell == 0:
            best = (n_devices // cell, cell)
    return best


def consensus_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    boot: Optional[int] = None,
    cell: Optional[int] = None,
) -> Mesh:
    """Build the ("boot", "cell") mesh over the given (default: all) devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if boot is None or cell is None:
        boot, cell = factor_devices(n)
    if boot * cell != n:
        raise ValueError(f"mesh {boot}x{cell} != {n} devices")
    dev_array = np.asarray(devices).reshape(boot, cell)
    return Mesh(dev_array, (BOOT_AXIS, CELL_AXIS))
