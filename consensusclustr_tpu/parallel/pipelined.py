"""Bounded-window async chunk pipeline: overlap device compute with host work.

JAX dispatch is asynchronous — a jitted call returns device "futures"
immediately and the chip executes in the background. The serial chunk drivers
(`consensus/pipeline.run_bootstraps`, `nulltest/null.generate_null_statistics`)
threw that away: they called ``np.asarray`` right after each dispatch, so the
device idled through the whole host-transfer + checkpoint-IO tail of every
chunk. This module keeps up to ``depth`` chunks in flight (dispatch chunk i+1
while chunk i still executes), fetches results strictly in submission order,
and moves checkpoint serialization onto a background writer thread so disk IO
never sits on the dispatch path.

Correctness contract:

* Results are bit-identical to the serial path at any depth — the pipeline
  changes *when* a chunk is fetched, never what was dispatched. Depth 1
  reproduces today's serial behavior exactly (fetch before the next dispatch,
  synchronous checkpoint writes).
* A chunk that raises (at dispatch or at fetch) drains the in-flight window
  (secondary errors swallowed) and surfaces the ORIGINAL exception.
* Host-ready values (checkpoint-resume chunks) ride the same ordered window
  without consuming a device slot, so resumed and computed chunks interleave
  in chunk order.

Observability (names registered in obs/schema.py):

* ``inflight_chunks`` gauge — high-water mark of concurrently in-flight
  dispatched chunks (window occupancy; ``depth`` when the pipeline filled).
* ``chunk_overlap_seconds`` histogram — per chunk, the seconds between its
  dispatch and the moment the host blocked on its fetch: the window in which
  device compute could overlap host work (fetch of earlier chunks, checkpoint
  IO, the next dispatch). An upper bound on realized overlap; ~0 at depth 1.
  Like every histogram it carries log-spaced bucket counts (obs/hist.py), so
  per-chunk overlap quantiles survive into RunRecords and /metrics scrapes
  without retaining per-chunk samples.

The window knob is ``CCTPU_PIPELINE_DEPTH`` (default 2), overridable per call
(``ClusterConfig.pipeline_depth`` / the ``pipeline_depth=`` arguments).
Depth >2 only helps when a single chunk's host tail (fetch + IO) exceeds a
full chunk's device time — see docs/perf.md "Pipelined chunk execution".
"""

from __future__ import annotations

import os
import queue
import threading
import time
from collections import deque
from typing import Any, Callable, Iterator, Optional

import numpy as np

from consensusclustr_tpu.obs.metrics import MetricsRegistry

DEFAULT_PIPELINE_DEPTH = 2


def pipeline_depth(requested: Optional[int] = None) -> int:
    """Resolve the window depth: explicit arg > $CCTPU_PIPELINE_DEPTH > 2.

    Loud contract: a depth < 1 is a configuration error, not a clamp — depth
    1 is the serial pipeline, there is nothing below it.
    """
    if requested is None:
        requested = int(os.environ.get("CCTPU_PIPELINE_DEPTH", DEFAULT_PIPELINE_DEPTH))
    depth = int(requested)
    if depth < 1:
        raise ValueError(f"pipeline depth must be >= 1; got {depth}")
    return depth


def _fetch_host(payload: Any) -> Any:
    """Blocking device->host transfer of a pytree of arrays."""
    import jax

    return jax.tree_util.tree_map(np.asarray, payload)


class AsyncChunkWriter:
    """Single background thread draining a queue of write callables.

    Serialization (np.savez + atomic os.replace in BootCheckpoint.save_chunk)
    runs off the dispatch path; submission order is preserved, so chunk files
    land in the order they were produced. The first error is latched and
    re-raised on the next ``submit`` (stopping the producer loop promptly) or
    at ``close`` — a full disk fails the run instead of silently dropping
    checkpoints.
    """

    def __init__(self, name: str = "cctpu-chunk-writer") -> None:
        self._q: "queue.Queue" = queue.Queue()
        self._error: Optional[BaseException] = None
        self._closed = False
        self._thread = threading.Thread(target=self._loop, name=name, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, args, kwargs = item
            if self._error is None:  # after an error, drain without writing
                try:
                    fn(*args, **kwargs)
                except BaseException as e:  # latched, re-raised on the host thread  # graftlint: noqa[GL007] error latched and re-raised on the host thread by _raise_pending
                    self._error = e

    def _raise_pending(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def submit(self, fn: Callable, *args: Any, **kwargs: Any) -> None:
        if self._closed:
            raise RuntimeError("AsyncChunkWriter already closed")
        self._raise_pending()
        self._q.put((fn, args, kwargs))

    def close(self, raise_errors: bool = True) -> None:
        """Flush the queue, join the thread; re-raise any latched write error."""
        if not self._closed:
            self._closed = True
            self._q.put(None)
            self._thread.join()
        if raise_errors:
            self._raise_pending()


class PendingChunk:
    """One window slot: a dispatched chunk's device output, or a host-ready
    value (resume path). ``fetch()`` blocks until the value is on host;
    idempotent, and always returns entries' values in submission order when
    driven through :class:`ChunkPipeline`.
    """

    __slots__ = (
        "index", "meta", "ready", "overlap_seconds", "latency_seconds",
        "_payload", "_value", "_fetched", "_dispatched_at", "_pipe",
    )

    def __init__(self, pipe: "ChunkPipeline", index: int, payload: Any,
                 meta: Any, ready: bool) -> None:
        self._pipe = pipe
        self.index = index
        self.meta = meta
        self.ready = ready
        self._payload = payload
        self._value = payload if ready else None
        self._fetched = ready
        self._dispatched_at = time.perf_counter()
        self.overlap_seconds = 0.0
        self.latency_seconds = 0.0

    def peek(self) -> Any:
        """The raw payload — device arrays for a dispatched chunk, the host
        value for a ready one — WITHOUT forcing a fetch. For consumers that
        chain further device work onto an in-flight chunk (the donated
        co-clustering accumulator feeds on this), keeping the whole
        accumulation on the async stream."""
        return self._value if self._fetched else self._payload

    def fetch(self) -> Any:
        """Host value of this chunk; blocks on the device the first time."""
        if not self._fetched:
            t_wait = time.perf_counter()
            self.overlap_seconds = t_wait - self._dispatched_at
            self._pipe._record_fetch_start(self)
            self._value = _fetch_host(self._payload)
            self._payload = None
            self._fetched = True
            self.latency_seconds = time.perf_counter() - self._dispatched_at
            self._pipe._record_fetch_done(self)
        return self._value


class ChunkPipeline:
    """Ordered bounded window of in-flight chunks.

    Driver shape (see run_bootstraps / generate_null_statistics):

        pipe = ChunkPipeline(depth, metrics=mets)
        try:
            for s in chunk_starts:
                for ent in pipe.ready_for_dispatch():
                    consume(ent)                  # fetch + post-process
                pipe.put(s, dispatch_chunk(s))    # async jitted call
            for ent in pipe.drain():
                consume(ent)
        except BaseException:
            pipe.abort()
            raise

    ``ready_for_dispatch`` yields the oldest entries until a new dispatch
    fits under ``depth``; each yielded entry must be ``fetch()``ed before the
    iterator is advanced (the driver loops above do). Host-ready entries
    (``put_ready``) occupy the ordered window but not a device slot.
    """

    def __init__(
        self,
        depth: int,
        metrics: Optional[MetricsRegistry] = None,
        on_enqueue: Optional[Callable[["PendingChunk"], None]] = None,
        site: Optional[str] = None,
        retry: Optional[Any] = None,
        log: Any = None,
    ):
        """``on_enqueue``, when given, runs synchronously for every entry the
        moment it joins the window (``put`` AND ``put_ready``) — the hook the
        chunk drivers use to chain follow-on device work (e.g. the donated
        co-clustering accumulator) onto a chunk right at dispatch, while the
        chunk itself is still executing. The hook sees the entry before any
        fetch: use ``ent.peek()`` for the raw payload.

        ``site``/``retry``/``log`` (ISSUE 10): a fault-site name from
        obs.schema.FAULT_SITES plus a resilience.retry.RetryPolicy turn
        :meth:`dispatch` into a retried dispatch — a transient chunk failure
        (injected or real) re-dispatches under the bounded-backoff policy
        instead of draining the whole run. Dispatch is a pure function of
        the chunk inputs, so a retried chunk is bit-identical to a
        first-try one. With ``site=None`` dispatch degenerates to
        ``put(index, thunk())`` exactly."""
        self.depth = int(depth)
        if self.depth < 1:
            raise ValueError(f"pipeline depth must be >= 1; got {self.depth}")
        self._metrics = metrics
        self._on_enqueue = on_enqueue
        self._site = site
        self._retry = retry
        self._log = log
        self._window: "deque[PendingChunk]" = deque()
        self._inflight = 0
        self.max_inflight = 0
        self.overlap_seconds = 0.0
        self.chunks_fetched = 0

    # -- bookkeeping (called by PendingChunk.fetch) --------------------------

    def _record_fetch_start(self, ent: PendingChunk) -> None:
        self.overlap_seconds += ent.overlap_seconds
        if self._metrics is not None:
            self._metrics.histogram("chunk_overlap_seconds").observe(
                ent.overlap_seconds
            )

    def _record_fetch_done(self, ent: PendingChunk) -> None:
        self._inflight -= 1
        self.chunks_fetched += 1

    # -- producer side -------------------------------------------------------

    def put(self, index: int, payload: Any, meta: Any = None) -> PendingChunk:
        """Enqueue a freshly dispatched chunk (device arrays, not yet ready)."""
        ent = PendingChunk(self, index, payload, meta, ready=False)
        self._window.append(ent)
        self._inflight += 1
        if self._inflight > self.max_inflight:
            self.max_inflight = self._inflight
            if self._metrics is not None:
                # high-water mark: a last-write gauge would always read 0
                # after the drain, which is the only time records snapshot it
                self._metrics.gauge("inflight_chunks").set(self.max_inflight)
        if self._on_enqueue is not None:
            self._on_enqueue(ent)
        return ent

    def dispatch(self, index: int, thunk: Callable[[], Any], meta: Any = None) -> PendingChunk:
        """Dispatch one chunk through the retry policy: ``thunk()`` runs the
        (async) jitted call and its result joins the window via ``put``.
        When the pipeline carries a fault ``site``, each attempt first runs
        the site's injection check and a host-side dispatch failure is
        retried under the policy (resilience/retry.py) — exhaustion
        surfaces the original exception, preserving the drain semantics of
        the driver's except/abort path."""
        if self._site is None:
            return self.put(index, thunk(), meta)
        from consensusclustr_tpu.resilience.retry import retry_call

        payload = retry_call(
            thunk, site=self._site, policy=self._retry,
            metrics=self._metrics, log=self._log,
        )
        return self.put(index, payload, meta)

    def put_ready(self, index: int, value: Any, meta: Any = None) -> PendingChunk:
        """Enqueue a host-ready value (resume cache) in chunk order."""
        ent = PendingChunk(self, index, value, meta, ready=True)
        self._window.append(ent)
        if self._on_enqueue is not None:
            self._on_enqueue(ent)
        return ent

    # -- consumer side -------------------------------------------------------

    def ready_for_dispatch(self) -> Iterator[PendingChunk]:
        """Yield oldest entries until one more dispatch fits in the window."""
        while self._window and self._inflight >= self.depth:
            yield self._window.popleft()

    def drain(self) -> Iterator[PendingChunk]:
        """Yield every remaining entry, oldest first."""
        while self._window:
            yield self._window.popleft()

    def abort(self) -> None:
        """Quiesce after an error: block on in-flight work, swallow secondary
        failures, clear the window — so the original exception surfaces
        instead of an async error leaking into unrelated later code."""
        while self._window:
            ent = self._window.popleft()
            if not ent._fetched:
                self._inflight -= 1
                try:
                    import jax

                    jax.block_until_ready(ent._payload)
                except Exception:  # graftlint: noqa[GL007] best-effort drain during teardown; the latched error already propagated
                    pass
                ent._payload = None
                ent._fetched = True
