"""Multi-host (multi-slice) initialisation — the DCN leg of the backend.

The reference's distribution story tops out at single-machine BiocParallel
pools (SURVEY §2.4); the scale configs (BASELINE.json config 5) need a
multi-host TPU pod. JAX's runtime handles the cross-host plumbing once
jax.distributed is initialised; after that, `consensus_mesh` over
jax.devices() spans the whole pod and the existing shard_map programs run
unchanged — psum over "boot" rides ICI within a slice and DCN across slices,
exactly the layering SURVEY §5's distributed-backend row prescribes.

Call `ensure_distributed()` once per process before building meshes. It is a
no-op on a single host (and under the CPU test mesh), keying off the standard
cluster env vars (JAX_COORDINATOR_ADDRESS / TPU metadata autodetection).
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def ensure_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialise jax.distributed when a multi-host environment is detected
    (or when explicitly configured). Returns True if distributed mode is on.

    Detection: explicit args > JAX_COORDINATOR_ADDRESS env > TPU pod metadata
    (jax.distributed.initialize() autodetects on Cloud TPU). Safe to call
    multiple times.
    """
    global _initialized
    if _initialized:
        return True
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    explicit = coordinator_address is not None
    autodetect = os.environ.get("TPU_WORKER_HOSTNAMES", "").count(",") > 0
    if not explicit and not autodetect:
        return False  # single host: nothing to do
    if _already_initialized():
        _initialized = True
        return True
    if explicit:
        # num_processes/process_id may come from env (jax reads
        # JAX_NUM_PROCESSES / JAX_PROCESS_ID) when not passed
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    else:
        jax.distributed.initialize()
    _initialized = True
    return True


def _already_initialized() -> bool:
    """True iff jax.distributed was initialised by an outer launcher."""
    try:
        from jax._src.distributed import global_state

        return global_state.client is not None
    except Exception:  # graftlint: noqa[GL007] capability probe: failure IS the signal, returned to the caller
        return False


def process_info() -> dict:
    """Topology summary for logs: process index/count, local/global devices."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
