"""Sharded co-clustering (consensus Jaccard) distance.

Distributed form of consensus/cocluster.py — the TPU equivalent of the
reference's OpenMP parDist pass over the inline Armadillo kernel
(reference R/consensusClust.R:411-421). Sharding layout (SURVEY §2.4 /
§5 long-context row):

  * the boot axis of ``labels [B, n]`` is sharded over mesh axis "boot";
  * the *rows* of the n x n agree/union accumulators are sharded over mesh
    axis "cell", so no device ever materialises the full matrix;
  * each (boot-shard, cell-shard) device computes its partial
    ``agree[rows_block, :]`` from its local bootstraps as a batched matmul on
    the MXU, then one ``psum`` over "boot" completes the counts — the single
    true all-reduce in the whole design.

At 1M cells (BASELINE.json config 5) the full float32 matrix is 4 TB; even
row-sharded it cannot be held dense, so at that scale the consensus graph
must be built from the top-k of each row block as it is produced (blockwise
kNN + sparse graph — the dist output here is for the moderate-n regime where
the row-sharded matrix fits, and the step wrapper's `return_dist=False` skips
the host gather).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from consensusclustr_tpu.parallel.mesh import BOOT_AXIS, CELL_AXIS


def _partial_counts(
    labels_local: jax.Array,   # [B_loc, n] int32, -1 = unsampled
    row_start: jax.Array,      # scalar int32: first row of this device's block
    n_rows: int,
    max_clusters: int,
    chunk: int,
    vary_axes: Tuple[str, ...] = (),
) -> Tuple[jax.Array, jax.Array]:
    """(agree, union) [n_rows, n] from this device's local bootstraps."""
    b, n = labels_local.shape
    pad = (-b) % chunk
    if pad:
        labels_local = jnp.concatenate(
            [labels_local, jnp.full((pad, n), -1, jnp.int32)], axis=0
        )
    labels_local = labels_local.reshape(-1, chunk, n)
    cvals = jnp.arange(max_clusters, dtype=jnp.int32)

    def body(carry, chunk_labels):
        agree, union = carry
        valid = (chunk_labels >= 0).astype(jnp.bfloat16)                 # [c, n]
        onehot = (chunk_labels[:, :, None] == cvals[None, None, :]).astype(jnp.bfloat16)
        onehot = onehot * valid[:, :, None]                               # [c, n, C]
        rows = jax.lax.dynamic_slice_in_dim(onehot, row_start, n_rows, axis=1)
        vrows = jax.lax.dynamic_slice_in_dim(valid, row_start, n_rows, axis=1)
        agree = agree + jnp.einsum(
            "cik,cjk->ij", rows, onehot, preferred_element_type=jnp.float32
        )
        union = union + jnp.einsum(
            "ci,cj->ij", vrows, valid, preferred_element_type=jnp.float32
        )
        return (agree, union), None

    zero = jnp.zeros((n_rows, n), jnp.float32)
    if vary_axes:  # inside shard_map the carry must match the body's vma type
        zero = jax.lax.pcast(zero, vary_axes, to="varying")
    (agree, union), _ = jax.lax.scan(body, (zero, zero), labels_local)
    return agree, union


@functools.partial(jax.jit, static_argnames=("mesh", "max_clusters", "chunk"))
def sharded_coclustering_distance(
    labels: jax.Array,
    mesh: jax.sharding.Mesh,
    max_clusters: int = 64,
    chunk: int = 8,
) -> jax.Array:
    """labels: [B, n] int32 (-1 = unsampled). Returns the [n, n] float32
    co-clustering distance, row-sharded over the mesh's "cell" axis.

    Requires B % mesh["boot"] == 0 and n % mesh["cell"] == 0 (pad bootstraps
    with all -1 rows — they contribute nothing — and pick n accordingly; the
    host wrappers handle boot padding).
    """
    b, n = labels.shape
    n_cell = mesh.shape[CELL_AXIS]
    if n % n_cell:
        raise ValueError(f"n={n} not divisible by cell axis {n_cell}")
    if b % mesh.shape[BOOT_AXIS]:
        raise ValueError(f"B={b} not divisible by boot axis {mesh.shape[BOOT_AXIS]}")
    n_rows = n // n_cell

    def kernel(labels_local):
        row_start = jax.lax.axis_index(CELL_AXIS).astype(jnp.int32) * n_rows
        agree, union = _partial_counts(
            labels_local, row_start, n_rows, max_clusters, chunk,
            vary_axes=(BOOT_AXIS, CELL_AXIS),
        )
        agree = jax.lax.psum(agree, BOOT_AXIS)
        union = jax.lax.psum(union, BOOT_AXIS)
        jac = jnp.where(union > 0, agree / jnp.maximum(union, 1.0), 0.0)
        dist = 1.0 - jac
        # zero the diagonal of this row block
        rows = row_start + jnp.arange(n_rows)
        dist = dist.at[jnp.arange(n_rows), rows].set(0.0)
        return dist

    return jax.shard_map(
        kernel,
        mesh=mesh,
        in_specs=P(BOOT_AXIS, None),
        out_specs=P(CELL_AXIS, None),
    )(jnp.asarray(labels, jnp.int32))
