"""Sharded co-clustering (consensus Jaccard) distance.

Distributed form of consensus/cocluster.py — the TPU equivalent of the
reference's OpenMP parDist pass over the inline Armadillo kernel
(reference R/consensusClust.R:411-421). Sharding layout (SURVEY §2.4 /
§5 long-context row):

  * the boot axis of ``labels [B, n]`` is sharded over mesh axis "boot";
  * the *rows* of the n x n agree/union accumulators are sharded over mesh
    axis "cell", so no device ever materialises the full matrix;
  * each (boot-shard, cell-shard) device computes its partial
    ``agree[rows_block, :]`` from its local bootstraps as a batched matmul on
    the MXU, then one ``psum`` over "boot" completes the counts — the single
    true all-reduce in the whole design.

At 1M cells (BASELINE.json config 5) the full float32 matrix is 4 TB; even
row-sharded it cannot be held dense, so at that scale the consensus graph
must be built from the top-k of each row block as it is produced (blockwise
kNN + sparse graph — the dist output here is for the moderate-n regime where
the row-sharded matrix fits, and the step wrapper's `return_dist=False` skips
the host gather).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from consensusclustr_tpu.parallel.mesh import BOOT_AXIS, CELL_AXIS


def _sharded_tile_impl(max_clusters: int):
    """Tile kernel choice for the sharded streamers: (impl, variant, interpret).

    Opt-in via CCTPU_SHARDED_PALLAS=1 (plus CCTPU_PALLAS_INTERPRET=1 for the
    CPU-mesh parity tests), conservative einsum default: unlike the
    single-chip paths, a Mosaic failure inside the one fused sharded program
    has no in-graph fallback — flip the default only after the sharded
    composition has compiled on real multi-chip hardware. Resolved at trace
    time; set the env before the first sharded call.
    """
    import os

    if os.environ.get("CCTPU_SHARDED_PALLAS") != "1":
        return ("einsum", "mxu", False)
    from consensusclustr_tpu.consensus.blockwise import _pallas_tile_opts

    pallas, variant, interpret = _pallas_tile_opts(True, max_clusters)
    return ("pallas" if pallas else "einsum", variant, interpret)


def _partial_counts(
    labels_local: jax.Array,   # [B_loc, n] int32, -1 = unsampled
    row_start: jax.Array,      # scalar int32: first row of this device's block
    n_rows: int,
    max_clusters: int,
    chunk: int,
    vary_axes: Tuple[str, ...] = (),
) -> Tuple[jax.Array, jax.Array]:
    """(agree, union) [n_rows, n] from this device's local bootstraps."""
    b, n = labels_local.shape
    pad = (-b) % chunk
    if pad:
        labels_local = jnp.concatenate(
            [labels_local, jnp.full((pad, n), -1, jnp.int32)], axis=0
        )
    labels_local = labels_local.reshape(-1, chunk, n)
    cvals = jnp.arange(max_clusters, dtype=jnp.int32)

    def body(carry, chunk_labels):
        agree, union = carry
        valid = (chunk_labels >= 0).astype(jnp.bfloat16)                 # [c, n]
        onehot = (chunk_labels[:, :, None] == cvals[None, None, :]).astype(jnp.bfloat16)  # graftlint: noqa[GL008] the bf16 one-hot IS the MXU matmul operand (both einsums below contract it); bounded by chunk rows per step
        onehot = onehot * valid[:, :, None]                               # [c, n, C]
        rows = jax.lax.dynamic_slice_in_dim(onehot, row_start, n_rows, axis=1)
        vrows = jax.lax.dynamic_slice_in_dim(valid, row_start, n_rows, axis=1)
        agree = agree + jnp.einsum(
            "cik,cjk->ij", rows, onehot, preferred_element_type=jnp.float32
        )
        union = union + jnp.einsum(
            "ci,cj->ij", vrows, valid, preferred_element_type=jnp.float32
        )
        return (agree, union), None

    zero = jnp.zeros((n_rows, n), jnp.float32)
    if vary_axes:  # inside shard_map the carry must match the body's vma type
        zero = jax.lax.pcast(zero, vary_axes, to="varying")
    (agree, union), _ = jax.lax.scan(body, (zero, zero), labels_local)
    return agree, union


@functools.partial(
    jax.jit, static_argnames=("mesh", "k", "max_clusters", "block", "chunk")  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
)
def sharded_blockwise_consensus_knn(
    labels: jax.Array,
    mesh: jax.sharding.Mesh,
    k: int,
    max_clusters: int = 64,
    block: int = 512,
    chunk: int = 8,
):
    """Sharded co-clustering kNN without a dense [n, n] anywhere — the scale
    regime (BASELINE configs 3-5) where even the row-sharded matrix of
    `sharded_coclustering_distance` cannot be held (200k cells: 20 GB per
    device on an 8-mesh).

    Rows are sharded over the FLATTENED ("boot", "cell") mesh — every device
    owns n/D rows and streams [block, n] distance tiles from the replicated
    boot labels (consensus/blockwise.py tile kernel) past a local top-k.
    Returns (idx [n, k], dist [n, k]) sharded over the flattened axes; the
    small [n, k] graph is then cheap to replicate. Any n: the cell axis is
    padded to the device count (x TILE for the opt-in Pallas tile,
    CCTPU_SHARDED_PALLAS=1) with all -1 columns that always lose top_k ties.
    """
    from consensusclustr_tpu.consensus.blockwise import _make_tile

    b, n = labels.shape
    n_dev = mesh.shape[BOOT_AXIS] * mesh.shape[CELL_AXIS]
    tile_impl, variant, interpret = _sharded_tile_impl(max_clusters)
    # pad the cell axis to the device count with all -1 columns: padded cells
    # sit at distance 1 from everything and always lose top_k ties to real
    # cells (earliest-index tie-break), so they never contaminate real rows.
    # The Pallas tile additionally needs TILE-aligned per-device row blocks.
    if tile_impl == "pallas":
        from consensusclustr_tpu.ops.pallas_cocluster import TILE

        align = n_dev * TILE
    else:
        align = n_dev
    n_pad = -(-n // align) * align
    if n_pad != n:
        labels = jnp.concatenate(
            [jnp.asarray(labels, jnp.int32),
             jnp.full((b, n_pad - n), -1, jnp.int32)], axis=1
        )
    n_rows = n_pad // n_dev
    k_eff = min(k, n - 1)
    if tile_impl == "pallas":
        # largest TILE-multiple divisor of the per-device rows <= block
        m = n_rows // TILE
        bmax = max(block // TILE, 1)
        d = min(bmax, m)
        while m % d:
            d -= 1
        blk = d * TILE
    else:
        blk = min(block, n_rows)
        while n_rows % blk:  # largest divisor of the per-device rows <= block
            blk -= 1

    def kernel(labels_rep):
        i_boot = jax.lax.axis_index(BOOT_AXIS)
        i_cell = jax.lax.axis_index(CELL_AXIS)
        dev = i_boot * mesh.shape[CELL_AXIS] + i_cell
        row0 = (dev * n_rows).astype(jnp.int32)
        rows_local = jnp.arange(blk, dtype=jnp.int32)
        tile = _make_tile(
            labels_rep, n_pad, max_clusters, blk, chunk, tile_impl, variant,
            interpret,
            vma=(BOOT_AXIS, CELL_AXIS) if not interpret else (),
        )

        def one_block(i):
            start = row0 + i * blk
            d = tile(start)                                      # [blk, n_pad]
            self_col = jnp.clip(start + rows_local, 0, n_pad - 1)
            d = d.at[rows_local, self_col].set(jnp.inf)
            return jax.lax.top_k(-d, k_eff)

        neg, idx = jax.lax.map(one_block, jnp.arange(n_rows // blk, dtype=jnp.int32))
        return idx.reshape(n_rows, k_eff), -neg.reshape(n_rows, k_eff)

    both = (BOOT_AXIS, CELL_AXIS)
    # the pallas tile's INTERPRET-mode lowering cannot yet propagate varying
    # manual axes through its internal grid scan (jax asks for an upstream
    # issue and suggests exactly this workaround), so only the interpret
    # test path relaxes vma checking; the einsum default and the hardware
    # pallas path (which declares its vma on the out_shape) stay strict
    idx, dist = jax.shard_map(
        kernel,
        mesh=mesh,
        in_specs=P(None, None),
        out_specs=(P(both, None), P(both, None)),
        check_vma=not (tile_impl == "pallas" and interpret),
    )(jnp.asarray(labels, jnp.int32))
    idx, dist = idx[:n], dist[:n]
    if k_eff < k:
        pad = k - k_eff
        idx = jnp.concatenate([idx, jnp.repeat(idx[:, -1:], pad, axis=1)], axis=1)
        dist = jnp.concatenate([dist, jnp.repeat(dist[:, -1:], pad, axis=1)], axis=1)
    return idx.astype(jnp.int32), dist


@functools.partial(jax.jit, static_argnames=("mesh", "max_clusters", "chunk"))  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
def sharded_coclustering_distance(
    labels: jax.Array,
    mesh: jax.sharding.Mesh,
    max_clusters: int = 64,
    chunk: int = 8,
) -> jax.Array:
    """labels: [B, n] int32 (-1 = unsampled). Returns the [n, n] float32
    co-clustering distance, row-sharded over the mesh's "cell" axis.

    Requires B % mesh["boot"] == 0 and n % mesh["cell"] == 0 (pad bootstraps
    with all -1 rows — they contribute nothing — and pick n accordingly; the
    host wrappers handle boot padding).
    """
    b, n = labels.shape
    n_cell = mesh.shape[CELL_AXIS]
    if n % n_cell:
        raise ValueError(f"n={n} not divisible by cell axis {n_cell}")
    if b % mesh.shape[BOOT_AXIS]:
        raise ValueError(f"B={b} not divisible by boot axis {mesh.shape[BOOT_AXIS]}")
    n_rows = n // n_cell

    def kernel(labels_local):
        row_start = jax.lax.axis_index(CELL_AXIS).astype(jnp.int32) * n_rows
        agree, union = _partial_counts(
            labels_local, row_start, n_rows, max_clusters, chunk,
            vary_axes=(BOOT_AXIS, CELL_AXIS),
        )
        agree = jax.lax.psum(agree, BOOT_AXIS)
        union = jax.lax.psum(union, BOOT_AXIS)
        jac = jnp.where(union > 0, agree / jnp.maximum(union, 1.0), 0.0)
        dist = 1.0 - jac
        # zero the diagonal of this row block
        rows = row_start + jnp.arange(n_rows, dtype=jnp.int32)
        dist = dist.at[jnp.arange(n_rows, dtype=jnp.int32), rows].set(0.0)
        return dist

    return jax.shard_map(
        kernel,
        mesh=mesh,
        in_specs=P(BOOT_AXIS, None),
        out_specs=P(CELL_AXIS, None),
    )(jnp.asarray(labels, jnp.int32))
