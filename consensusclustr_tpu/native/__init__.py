"""ctypes bindings for the native host runtime (ccruntime.cpp).

Build-on-first-use: the shared library is compiled with g++ into the package
directory and cached; staleness is detected by source mtime. Every entry
point has a pure-numpy fallback so the package works without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "ccruntime.cpp")
_LIB = os.path.join(_DIR, "libccruntime.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> bool:
    cmd = [
        "g++", "-O3", "-std=c++17", "-fPIC", "-shared", "-pthread",
        _SRC, "-o", _LIB,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception:  # graftlint: noqa[GL007] build probe: failure IS the signal, returned to the caller
        return False


def load_library() -> Optional[ctypes.CDLL]:
    """The cached CDLL, building it if needed; None if no toolchain."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        stale = (
            not os.path.exists(_LIB)
            or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
        )
        if stale and not _build():
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            _build_failed = True
            return None
        lib.cc_jaccard_distance.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_float), ctypes.c_int,
        ]
        lib.cc_mtx_open.restype = ctypes.c_void_p
        lib.cc_mtx_open.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ]
        lib.cc_mtx_fill.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float),
        ]
        lib.cc_mtx_close.argtypes = [ctypes.c_void_p]
        lib.cc_coo_to_csr.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float),
        ]
        _lib = lib
        return _lib


def _ptr(a: np.ndarray, typ):
    return a.ctypes.data_as(ctypes.POINTER(typ))


def jaccard_distance_host(labels: np.ndarray, n_threads: int = 0) -> np.ndarray:
    """Threaded host co-clustering distance — the CPU oracle for the device
    kernels (same contract: [B, n] int32 with -1 masks -> [n, n] float32)."""
    labels = np.ascontiguousarray(labels, dtype=np.int32)
    b, n = labels.shape
    lib = load_library()
    if lib is None:  # numpy fallback
        valid = labels >= 0
        both = valid.astype(np.int64).T @ valid.astype(np.int64)
        agree = np.zeros((n, n), np.int64)
        for bb in range(b):
            lb, vb = labels[bb], valid[bb]
            eq = (lb[:, None] == lb[None, :]) & vb[:, None] & vb[None, :]
            agree += eq
        with np.errstate(invalid="ignore", divide="ignore"):
            dist = 1.0 - np.where(both > 0, agree / np.maximum(both, 1), 0.0)
        np.fill_diagonal(dist, 0.0)
        return dist.astype(np.float32)
    out = np.empty((n, n), np.float32)
    lib.cc_jaccard_distance(
        _ptr(labels, ctypes.c_int32), b, n, _ptr(out, ctypes.c_float), n_threads
    )
    return out


def read_mtx(path: str) -> Tuple[np.ndarray, np.ndarray, np.ndarray, Tuple[int, int]]:
    """Parse a MatrixMarket coordinate file.

    Returns (row_idx [nnz] int32, col_idx [nnz] int32, values [nnz] float32,
    (rows, cols)).
    """
    lib = load_library()
    if lib is None:  # scipy fallback
        from scipy.io import mmread

        m = mmread(path).tocoo()
        return (
            m.row.astype(np.int32), m.col.astype(np.int32),
            m.data.astype(np.float32), (int(m.shape[0]), int(m.shape[1])),
        )
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    nnz = ctypes.c_int64()
    handle = lib.cc_mtx_open(
        path.encode(), ctypes.byref(rows), ctypes.byref(cols), ctypes.byref(nnz)
    )
    if not handle:
        raise ValueError(f"not a MatrixMarket coordinate file: {path}")
    try:
        r = np.empty(nnz.value, np.int32)
        c = np.empty(nnz.value, np.int32)
        v = np.empty(nnz.value, np.float32)
        lib.cc_mtx_fill(
            handle, _ptr(r, ctypes.c_int32), _ptr(c, ctypes.c_int32),
            _ptr(v, ctypes.c_float),
        )
    finally:
        lib.cc_mtx_close(handle)
    return r, c, v, (rows.value, cols.value)


def coo_to_csr(
    row_idx: np.ndarray, col_idx: np.ndarray, values: np.ndarray, rows: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """COO -> CSR (indptr int64, col int32, val float32)."""
    row_idx = np.ascontiguousarray(row_idx, np.int32)
    col_idx = np.ascontiguousarray(col_idx, np.int32)
    values = np.ascontiguousarray(values, np.float32)
    nnz = len(values)
    lib = load_library()
    if lib is None:
        order = np.argsort(row_idx, kind="stable")
        indptr = np.zeros(rows + 1, np.int64)
        np.add.at(indptr, row_idx + 1, 1)
        np.cumsum(indptr, out=indptr)
        return indptr, col_idx[order], values[order]
    indptr = np.empty(rows + 1, np.int64)
    out_col = np.empty(nnz, np.int32)
    out_val = np.empty(nnz, np.float32)
    lib.cc_coo_to_csr(
        _ptr(row_idx, ctypes.c_int32), _ptr(col_idx, ctypes.c_int32),
        _ptr(values, ctypes.c_float), nnz, rows,
        _ptr(indptr, ctypes.c_int64), _ptr(out_col, ctypes.c_int32),
        _ptr(out_val, ctypes.c_float),
    )
    return indptr, out_col, out_val
