// Native host runtime for consensusclustr_tpu.
//
// The reference's runtime-native surface is (a) an inline Armadillo Jaccard
// kernel applied over all cell pairs by parallelDist's OpenMP engine
// (reference R/consensusClust.R:411-421) and (b) the C++ sparse-matrix /
// ingestion machinery of the Matrix package that every count matrix flows
// through. This file provides the host-side equivalents: a threaded
// co-clustering distance (the CPU oracle / small-problem fallback for the
// TPU kernels) and a MatrixMarket COO parser feeding the CSR ingestion path
// (SURVEY §7.2 stage 1).
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in the image).

#include <atomic>
#include <cctype>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Co-clustering (consensus Jaccard) distance, threaded over row blocks.
//
// labels: [B, n] row-major int32, -1 = cell unsampled in that bootstrap.
// out:    [n, n] row-major float32 distance; diagonal 0; never-co-sampled
//         pairs get 1 (same contract as the device kernels).
// ---------------------------------------------------------------------------
void cc_jaccard_distance(const int32_t* labels, int64_t n_boots, int64_t n_cells,
                         float* out, int n_threads) {
  if (n_threads <= 0) {
    n_threads = (int)std::thread::hardware_concurrency();
    if (n_threads <= 0) n_threads = 1;
  }
  std::atomic<int64_t> next_row{0};
  auto worker = [&]() {
    for (;;) {
      int64_t i = next_row.fetch_add(1);
      if (i >= n_cells) return;
      out[i * n_cells + i] = 0.0f;
      for (int64_t j = i + 1; j < n_cells; ++j) {
        int64_t agree = 0, both = 0;
        for (int64_t b = 0; b < n_boots; ++b) {
          const int32_t li = labels[b * n_cells + i];
          const int32_t lj = labels[b * n_cells + j];
          const bool valid = (li >= 0) & (lj >= 0);
          both += valid;
          agree += valid & (li == lj);
        }
        const float d = both > 0 ? 1.0f - (float)agree / (float)both : 1.0f;
        out[i * n_cells + j] = d;
        out[j * n_cells + i] = d;
      }
    }
  };
  std::vector<std::thread> pool;
  for (int t = 0; t < n_threads; ++t) pool.emplace_back(worker);
  for (auto& th : pool) th.join();
}

// ---------------------------------------------------------------------------
// MatrixMarket coordinate-format parser -> COO buffers.
//
// Two-phase protocol for ctypes: cc_mtx_open parses the file into an opaque
// handle and reports (rows, cols, nnz); cc_mtx_fill copies the triplets into
// caller-allocated arrays; cc_mtx_close frees the handle. Supports the
// "%%MatrixMarket matrix coordinate (real|integer|pattern) general|symmetric"
// headers 10x/scanpy exports use.
// ---------------------------------------------------------------------------
struct CcMtx {
  int64_t rows = 0, cols = 0;
  std::vector<int32_t> r, c;
  std::vector<float> v;
};

void* cc_mtx_open(const char* path, int64_t* rows, int64_t* cols, int64_t* nnz) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  char line[1 << 16];
  bool symmetric = false, pattern = false;
  // header line
  if (!std::fgets(line, sizeof line, f)) { std::fclose(f); return nullptr; }
  if (std::strncmp(line, "%%MatrixMarket", 14) != 0 ||
      !std::strstr(line, "coordinate")) {
    std::fclose(f);
    return nullptr;
  }
  symmetric = std::strstr(line, "symmetric") != nullptr;
  pattern = std::strstr(line, "pattern") != nullptr;
  // comments, then the size line
  int64_t nr = 0, nc = 0, nz = 0;
  for (;;) {
    if (!std::fgets(line, sizeof line, f)) { std::fclose(f); return nullptr; }
    if (line[0] == '%') continue;
    if (std::sscanf(line, "%ld %ld %ld", &nr, &nc, &nz) != 3) {
      std::fclose(f);
      return nullptr;
    }
    break;
  }
  auto* m = new CcMtx;
  m->rows = nr;
  m->cols = nc;
  m->r.reserve(nz);
  m->c.reserve(nz);
  m->v.reserve(nz);
  while (std::fgets(line, sizeof line, f)) {
    const char* p = line;
    while (*p && std::isspace((unsigned char)*p)) ++p;
    if (!*p || *p == '%') continue;
    char* end = nullptr;
    const long ri = std::strtol(p, &end, 10);
    if (end == p) { std::fclose(f); delete m; return nullptr; }
    const char* mid = end;
    const long ci = std::strtol(mid, &end, 10);
    if (end == mid) { std::fclose(f); delete m; return nullptr; }
    double val = 1.0;
    if (!pattern) {
      const char* vp = end;
      val = std::strtod(vp, &end);
      if (end == vp) { std::fclose(f); delete m; return nullptr; }
    }
    // 1-based indices must land inside the declared dims: cc_coo_to_csr
    // scatter-writes with them, so out-of-range entries are memory-unsafe,
    // not just wrong (ADVICE r1 item 1).
    if (ri < 1 || ri > nr || ci < 1 || ci > nc) {
      std::fclose(f);
      delete m;
      return nullptr;
    }
    m->r.push_back((int32_t)(ri - 1));  // MatrixMarket is 1-based
    m->c.push_back((int32_t)(ci - 1));
    m->v.push_back((float)val);
    if (symmetric && ri != ci) {
      m->r.push_back((int32_t)(ci - 1));
      m->c.push_back((int32_t)(ri - 1));
      m->v.push_back((float)val);
    }
  }
  std::fclose(f);
  *rows = m->rows;
  *cols = m->cols;
  *nnz = (int64_t)m->r.size();
  return m;
}

void cc_mtx_fill(void* handle, int32_t* row_idx, int32_t* col_idx, float* values) {
  auto* m = (CcMtx*)handle;
  std::memcpy(row_idx, m->r.data(), m->r.size() * sizeof(int32_t));
  std::memcpy(col_idx, m->c.data(), m->c.size() * sizeof(int32_t));
  std::memcpy(values, m->v.data(), m->v.size() * sizeof(float));
}

void cc_mtx_close(void* handle) { delete (CcMtx*)handle; }

// ---------------------------------------------------------------------------
// COO -> CSR conversion (counting sort), threaded value scatter.
// indptr: [rows+1], out_col/out_val: [nnz] caller-allocated.
// ---------------------------------------------------------------------------
void cc_coo_to_csr(const int32_t* row_idx, const int32_t* col_idx,
                   const float* values, int64_t nnz, int64_t rows,
                   int64_t* indptr, int32_t* out_col, float* out_val) {
  std::memset(indptr, 0, (rows + 1) * sizeof(int64_t));
  for (int64_t k = 0; k < nnz; ++k) indptr[row_idx[k] + 1]++;
  for (int64_t r = 0; r < rows; ++r) indptr[r + 1] += indptr[r];
  std::vector<int64_t> cursor(indptr, indptr + rows);
  for (int64_t k = 0; k < nnz; ++k) {
    const int64_t dst = cursor[row_idx[k]]++;
    out_col[dst] = col_idx[k];
    out_val[dst] = values[k];
  }
}

}  // extern "C"
