"""Optional matplotlib renderings of the pipeline's outputs.

The reference renders two plots: an interactive PCA elbow during pcNum
selection (reference R/consensusClust.R:342-346) and a clustree of the
iterated hierarchy (:603-606); it also returns a stats dendrogram the user
typically plot()s. Equivalents here, all gated on matplotlib so the core
package stays plot-free (SURVEY §2.3 ggplot2/clustree rows).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


def _mpl():
    try:
        import matplotlib

        matplotlib.use("Agg", force=False)
        import matplotlib.pyplot as plt

        return plt
    except ImportError as e:  # pragma: no cover - matplotlib is baked in
        raise ImportError("plotting requires matplotlib") from e


def plot_elbow(sdev: np.ndarray, chosen: Optional[int] = None, path: Optional[str] = None):
    """Scree/elbow plot of PC standard deviations (reference :342-346).

    Returns the matplotlib Figure; saves to `path` when given.
    """
    plt = _mpl()
    sdev = np.asarray(sdev)
    fig, ax = plt.subplots(figsize=(6, 4))
    ax.plot(np.arange(1, len(sdev) + 1), sdev, marker="o", ms=3, lw=1)
    if chosen is not None:
        ax.axvline(chosen, color="tab:red", ls="--", lw=1, label=f"pcNum = {chosen}")
        ax.legend()
    ax.set_xlabel("principal component")
    ax.set_ylabel("standard deviation")
    ax.set_title("PCA elbow")
    fig.tight_layout()
    if path:
        fig.savefig(path, dpi=120)
    return fig


def plot_clustree(
    table: Dict[str, np.ndarray],
    edges: List[Tuple[str, str, int]],
    path: Optional[str] = None,
):
    """Layered lineage-tree rendering of the clustree table/edges
    (hierarchy/clustree.py) — node size ~ cell count, edge width ~ flow.
    """
    plt = _mpl()
    cols = sorted(table, key=lambda c: int(c.removeprefix("Cluster")))
    # node positions: depth on y, nodes spread on x in label order
    pos: Dict[Tuple[int, str], Tuple[float, float]] = {}
    sizes: Dict[Tuple[int, str], int] = {}
    for d, col in enumerate(cols):
        labels, counts = np.unique(np.asarray(table[col], dtype=str), return_counts=True)
        for i, (lab, cnt) in enumerate(zip(labels, counts)):
            pos[(d, lab)] = (i - (len(labels) - 1) / 2.0, -d)
            sizes[(d, lab)] = int(cnt)
    fig, ax = plt.subplots(figsize=(7, 1.8 + 1.2 * len(cols)))
    max_flow = max((n for *_ , n in edges), default=1)
    for parent, child, n in edges:
        pd = parent.count("_")
        cd = child.count("_")
        if (pd, parent) in pos and (cd, child) in pos:
            (x0, y0), (x1, y1) = pos[(pd, parent)], pos[(cd, child)]
            ax.plot([x0, x1], [y0, y1], color="grey", lw=0.5 + 2.5 * n / max_flow, zorder=1)
    max_size = max(sizes.values(), default=1)
    for (d, lab), (x, y) in pos.items():
        ax.scatter([x], [y], s=100 + 900 * sizes[(d, lab)] / max_size, zorder=2)
        ax.annotate(lab, (x, y), ha="center", va="center", fontsize=8, zorder=3)
    ax.set_yticks([-d for d in range(len(cols))], cols)
    ax.set_xticks([])
    for side in ("top", "right", "bottom", "left"):
        ax.spines[side].set_visible(False)
    ax.set_title("cluster hierarchy")
    fig.tight_layout()
    if path:
        fig.savefig(path, dpi=120)
    return fig


def plot_dendrogram(dend, path: Optional[str] = None):
    """Render a hierarchy.dendro.Dendrogram (merge-matrix format)."""
    plt = _mpl()
    from scipy.cluster.hierarchy import dendrogram as scipy_dendrogram

    fig, ax = plt.subplots(figsize=(6, 4))
    scipy_dendrogram(dend.linkage, labels=list(dend.labels), ax=ax)
    ax.set_ylabel("distance")
    fig.tight_layout()
    if path:
        fig.savefig(path, dpi=120)
    return fig
