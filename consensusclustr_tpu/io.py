"""Count-matrix container and file ingestion (SURVEY §7.2 stage 1).

The reference leans on R's Matrix package (C++ dgCMatrix) for every sparse
count matrix and on Seurat/SCE loaders for files (SURVEY §2.3 Matrix row).
Here: a CSR container over numpy buffers filled by the native runtime
(native/ccruntime.cpp) with pure-python fallbacks, plus format dispatch for
the formats scRNA-seq data actually ships in — MatrixMarket (.mtx), scipy
.npz, dense .npy, and AnnData .h5ad (gated on the optional anndata package).

Orientation: cells x genes throughout (the Python convention; the reference
is genes x cells — adapters transpose at the boundary, api.py).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Tuple

import numpy as np

from consensusclustr_tpu.native import coo_to_csr, read_mtx


@dataclasses.dataclass
class CountMatrix:
    """CSR counts [n_cells, n_genes] with optional axis names."""

    indptr: np.ndarray          # [n_cells + 1] int64
    col: np.ndarray             # [nnz] int32 gene indices
    val: np.ndarray             # [nnz] float32
    shape: Tuple[int, int]
    cell_names: Optional[np.ndarray] = None
    gene_names: Optional[np.ndarray] = None

    @property
    def nnz(self) -> int:
        return len(self.val)

    @property
    def density(self) -> float:
        return self.nnz / max(self.shape[0] * self.shape[1], 1)

    def dense(self) -> np.ndarray:
        """Materialise [n_cells, n_genes] float32 (device kernels are dense)."""
        out = np.zeros(self.shape, np.float32)
        rows = np.repeat(
            np.arange(self.shape[0]), np.diff(self.indptr).astype(np.int64)
        )
        out[rows, self.col] = self.val
        return out

    def row_sums(self) -> np.ndarray:
        return np.add.reduceat(
            np.append(self.val, 0.0), self.indptr[:-1].astype(np.int64)
        ) * (np.diff(self.indptr) > 0)

    @classmethod
    def from_coo(
        cls, row: np.ndarray, col: np.ndarray, val: np.ndarray,
        shape: Tuple[int, int], **names,
    ) -> "CountMatrix":
        indptr, ccol, cval = coo_to_csr(row, col, val, shape[0])
        return cls(indptr=indptr, col=ccol, val=cval, shape=shape, **names)

    @classmethod
    def from_dense(cls, x: np.ndarray, **names) -> "CountMatrix":
        x = np.asarray(x)
        row, col = np.nonzero(x)
        return cls.from_coo(
            row.astype(np.int32), col.astype(np.int32),
            x[row, col].astype(np.float32), x.shape, **names,
        )


def load_counts(path: str, transpose: bool = False) -> CountMatrix:
    """Load counts from .mtx / .mtx.gz / .npz / .npy / .h5ad.

    `transpose=True` flips a genes x cells file (10x's mtx convention) into
    the cells x genes orientation used throughout.
    """
    lower = path.lower()
    if lower.endswith((".mtx", ".mtx.gz")):
        if lower.endswith(".gz"):
            import gzip
            import shutil
            import tempfile

            with gzip.open(path, "rb") as src, tempfile.NamedTemporaryFile(
                suffix=".mtx", delete=False
            ) as dst:
                shutil.copyfileobj(src, dst)
                tmp = dst.name
            try:
                row, col, val, shape = read_mtx(tmp)
            finally:
                os.unlink(tmp)
        else:
            row, col, val, shape = read_mtx(path)
        if transpose:
            row, col, shape = col, row, (shape[1], shape[0])
        return CountMatrix.from_coo(row, col, val, shape)

    if lower.endswith(".npz"):
        with np.load(path, allow_pickle=False) as z:
            if "indptr" in z:  # scipy.sparse.save_npz CSR/CSC layout
                from scipy import sparse

                m = sparse.load_npz(path).tocsr()
                if transpose:
                    m = m.T.tocsr()
                return CountMatrix(
                    indptr=m.indptr.astype(np.int64),
                    col=m.indices.astype(np.int32),
                    val=m.data.astype(np.float32),
                    shape=(int(m.shape[0]), int(m.shape[1])),
                )
            arr = z[z.files[0]]
        return CountMatrix.from_dense(arr.T if transpose else arr)

    if lower.endswith(".npy"):
        arr = np.load(path)
        return CountMatrix.from_dense(arr.T if transpose else arr)

    if lower.endswith(".h5ad"):
        try:
            import anndata
        except ImportError as e:  # pragma: no cover - optional dep
            raise ImportError("reading .h5ad requires the anndata package") from e
        ad = anndata.read_h5ad(path)
        x = ad.layers.get("counts", ad.X)
        if hasattr(x, "tocsr"):
            m = (x.T if transpose else x).tocsr()
            cm = CountMatrix(
                indptr=m.indptr.astype(np.int64),
                col=m.indices.astype(np.int32),
                val=m.data.astype(np.float32),
                shape=(int(m.shape[0]), int(m.shape[1])),
            )
        else:
            arr = np.asarray(x)
            cm = CountMatrix.from_dense(arr.T if transpose else arr)
        names = (np.asarray(ad.obs_names), np.asarray(ad.var_names))
        cm.cell_names, cm.gene_names = (names[1], names[0]) if transpose else names
        return cm

    raise ValueError(f"unsupported counts format: {path}")


def _read_tsv_rows(path: str) -> list:
    import gzip

    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        # rstrip \r too: CRLF files would otherwise attach invisible \r to
        # every name and break all downstream exact matching
        return [line.rstrip("\r\n").split("\t") for line in f if line.strip()]


def _read_tsv_column(path: str, column: int = 0) -> np.ndarray:
    rows = _read_tsv_rows(path)
    if not rows:
        return np.asarray([], dtype=object)
    # Decide the column once per file, from the WIDEST row: clamping per row
    # would silently mix id and symbol columns when a features file has
    # occasional short rows (ADVICE r4) — and clamping to the first row
    # would do the same file-wide whenever the first row happens to be the
    # truncated one. Any row too short for the chosen column is an error.
    col = min(column, max(len(r) for r in rows) - 1)
    short = [i for i, r in enumerate(rows) if len(r) <= col]
    if short:
        raise ValueError(
            f"{path!r}: rows {short[:5]} have fewer than {col + 1} columns "
            f"(file-wide column {col} chosen from the widest row)"
        )
    return np.asarray([r[col] for r in rows], dtype=object)


def load_10x(directory: str) -> CountMatrix:
    """Load a 10x Genomics Cell Ranger output directory.

    The standard trio — `matrix.mtx[.gz]` (genes x cells MatrixMarket),
    `barcodes.tsv[.gz]` (cell names) and `features.tsv[.gz]` (or the legacy
    `genes.tsv`) — is the ingestion path the reference reaches through
    Seurat's `Read10X` (reference README.md:30-38's Seurat workflow).
    Returns cells x genes CSR with names attached. Like Read10X's
    `gene.column = 2` default, gene_names are the symbol column when the
    features file has one (so symbol-based `variable_features` match),
    falling back to the id column.
    """

    def _find(*stems: str) -> Optional[str]:
        for stem in stems:
            for suffix in ("", ".gz"):
                p = os.path.join(directory, stem + suffix)
                if os.path.exists(p):
                    return p
        return None

    mtx = _find("matrix.mtx")
    if mtx is None:
        raise FileNotFoundError(f"no matrix.mtx[.gz] in {directory!r}")
    cm = load_counts(mtx, transpose=True)  # 10x ships genes x cells

    # A sidecar whose row count disagrees with the matrix is a truncated or
    # mismatched file; Seurat's Read10X errors on this. We keep loading (the
    # counts themselves are intact) but warn loudly instead of silently
    # dropping the names (ADVICE r4).
    import warnings

    barcodes = _find("barcodes.tsv")
    if barcodes is not None:
        names = _read_tsv_column(barcodes)
        if len(names) == cm.shape[0]:
            cm.cell_names = names
        else:
            warnings.warn(
                f"{barcodes!r} has {len(names)} rows but the matrix has "
                f"{cm.shape[0]} cells; ignoring cell names", stacklevel=2
            )
    features = _find("features.tsv", "genes.tsv")
    if features is not None:
        names = _read_tsv_column(features, column=1)
        if len(names) == cm.shape[1]:
            cm.gene_names = names
        else:
            warnings.warn(
                f"{features!r} has {len(names)} rows but the matrix has "
                f"{cm.shape[1]} genes; ignoring gene names", stacklevel=2
            )
    return cm
