"""Fixed log-spaced histogram buckets + quantile estimation.

The PR-1 ``Histogram`` kept only count/sum/min/max — memory-bounded and
hot-loop safe, but quantiles (the numbers a serving operator actually watches)
had to be recomputed ad hoc from raw samples held elsewhere. This module adds
the missing middle ground: a fixed ladder of log-spaced upper bounds (the
Prometheus ``le`` convention — bucket i counts observations ``<= bounds[i]``,
plus one overflow bucket for ``> bounds[-1]``). Memory stays O(len(bounds))
per histogram regardless of observation count, ``observe`` costs one bisect,
and ``quantile(q)`` is accurate to within the containing bucket's width.

Kept stdlib-only (no numpy, no jax) so obs/export.py and tools/report.py can
reuse the estimator on serialized snapshots from hosts without the stack.

Bounds default to :data:`DEFAULT_BOUNDS` — 100 µs to 128 s at 4 buckets per
decade (ratio ~1.78x) — sized for the latencies this package observes
(``serve_latency_seconds``, ``chunk_overlap_seconds``, phase timings).
Observations below the lowest bound land in bucket 0; the estimator uses the
tracked min/max to tighten the first and overflow buckets' open edges.
"""

from __future__ import annotations

from bisect import bisect_left
from typing import List, Optional, Sequence, Tuple


def log_bounds(
    lo: float = 1e-4, hi: float = 128.0, per_decade: int = 4
) -> Tuple[float, ...]:
    """Log-spaced ``le`` upper bounds from ``lo`` to at least ``hi``.

    Successive bounds differ by a factor of ``10**(1/per_decade)``; the ladder
    is generated multiplicatively and rounded to 10 significant digits so the
    same call always yields the identical (mergeable) tuple.
    """
    if not (lo > 0 and hi > lo):
        raise ValueError(f"need 0 < lo < hi; got lo={lo} hi={hi}")
    if per_decade < 1:
        raise ValueError(f"per_decade must be >= 1; got {per_decade}")
    ratio = 10.0 ** (1.0 / per_decade)
    out: List[float] = []
    b = float(lo)
    while True:
        out.append(float(f"{b:.10g}"))
        if out[-1] >= hi:
            return tuple(out)
        b *= ratio


DEFAULT_BOUNDS: Tuple[float, ...] = log_bounds()

# One (log) bucket step of the default ladder — tests and docs use it as the
# "within one bucket width" tolerance on quantile estimates.
DEFAULT_BUCKET_RATIO: float = 10.0 ** 0.25


def bucket_index(bounds: Sequence[float], value: float) -> int:
    """Index of the ``le`` bucket for ``value``: first i with
    ``value <= bounds[i]``, or ``len(bounds)`` (the +Inf overflow bucket)."""
    return bisect_left(bounds, value)


def merge_bucket_counts(
    bounds_a: Sequence[float],
    counts_a: Sequence[int],
    bounds_b: Sequence[float],
    counts_b: Sequence[int],
) -> Optional[List[int]]:
    """Elementwise sum of two bucket-count vectors when their ``le`` ladders
    match exactly; None on a mismatch (the caller keeps the exact streaming
    summary but loses quantiles — count that drop, don't hide it: the
    ``hist_merge_mismatch`` counter and metrics.py's one-time warning exist
    because the PR 4 behavior was a silent drop)."""
    if (
        not counts_a
        or not counts_b
        or tuple(bounds_a) != tuple(bounds_b)
        or len(counts_a) != len(counts_b)
    ):
        return None
    return [int(a) + int(b) for a, b in zip(counts_a, counts_b)]


def bucket_quantile(
    bounds: Sequence[float],
    counts: Sequence[int],
    q: float,
    lo: Optional[float] = None,
    hi: Optional[float] = None,
) -> Optional[float]:
    """Estimate the q-quantile from per-bucket counts.

    ``counts`` has ``len(bounds) + 1`` entries (the last is the overflow
    bucket). Finds the bucket holding the ceil(q * n)-th observation and
    interpolates linearly inside it; ``lo``/``hi`` (observed min/max, when
    known) tighten the open edges of the first and overflow buckets and clamp
    the result. Returns None for an empty histogram. The estimate is within
    the containing bucket's width of the exact sample quantile by
    construction — the bucket ratio is the precision knob.
    """
    if not (0.0 <= q <= 1.0):
        raise ValueError(f"quantile q must be in [0, 1]; got {q}")
    total = sum(counts)
    if total <= 0:
        return None
    if len(counts) != len(bounds) + 1:
        raise ValueError(
            f"counts must have len(bounds)+1 entries; got {len(counts)} "
            f"for {len(bounds)} bounds"
        )
    # rank of the target observation, 1-based; q=0 -> 1, q=1 -> total
    target = max(1, min(total, int(-(-q * total // 1))))
    cum = 0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        if cum + c >= target:
            edge_lo = bounds[i - 1] if i > 0 else (lo if lo is not None else 0.0)
            if i < len(bounds):
                edge_hi = bounds[i]
            else:  # overflow bucket: closed only when the max is known
                edge_hi = hi if hi is not None else bounds[-1]
            edge_lo = min(edge_lo, edge_hi)
            frac = (target - cum) / c
            est = edge_lo + frac * (edge_hi - edge_lo)
            if lo is not None:
                est = max(est, lo)
            if hi is not None:
                est = min(est, hi)
            return est
        cum += c
    return hi  # unreachable when counts sum to total
