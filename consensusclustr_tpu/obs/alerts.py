"""Declarative SLO alert engine over the metrics registries (ISSUE 14).

The PR 7 serving histograms can answer "what is p99 right now" — but only
if something asks. This module is the thing that asks: a small set of
declarative :class:`AlertRule`\\ s evaluated over live
``MetricsRegistry`` instances, turning the SLO signals into level-triggered
alerts a router can act on:

  * ``p99_bound``       — a histogram's p99 estimate above a bound;
  * ``rate``            — windowed bad/(bad+good) fraction above a
    threshold (the rejection-rate rule: rejected vs served requests);
  * ``burn_rate``       — the same windowed bad fraction expressed as a
    multiple of the allowed error budget (classic SLO burn-rate: budget
    0.01 burning at 10x means the monthly budget is gone in 3 days);
  * ``counter_increase`` — monotonicity watch: the counter moved within
    the window (``retries_exhausted``, ``aot_fallbacks`` — any increase is
    news).

Rule *names* are registered in ``obs.schema.ALERT_RULES`` (the ``*_ALERT``
literals below, validated both ways by tools/check_obs_schema.py).
Transitions emit ``alert_raised`` / ``alert_cleared`` events and maintain
the ``alerts_active`` gauge + ``alerts_raised`` counter; the engine's
``summary()`` block lands in ``RunRecord.alerts`` (schema v8), in every
bench rung, in each ``tools/loadgen.py --ladder`` step, and — via
``AssignmentService.health()`` — in ``/healthz``, which is the ROADMAP O3
per-replica drain signal.

Evaluation is pull-based and cheap (dict deltas over a throttled sample
ring): the serving loop evaluates once per micro-batch, ``health()`` on
every scrape, and batch runs once at record time. Like every obs layer,
evaluation never raises into the traced work.
"""

from __future__ import annotations

import dataclasses
import os
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from consensusclustr_tpu.obs.metrics import MetricsRegistry, global_metrics
from consensusclustr_tpu.obs.tracer import Tracer

# Rule names. Each ``*_ALERT`` literal is validated against
# obs.schema.ALERT_RULES by tools/check_obs_schema.py, both directions — a
# renamed rule is a test failure, not a dashboard scraping a dead name.
P99_ALERT = "serve_p99_high"
REJECTION_ALERT = "serve_rejection_rate_high"
BURN_ALERT = "slo_burn_rate_high"
EXHAUSTED_ALERT = "retries_exhausted_rising"
AOT_ALERT = "aot_fallbacks_rising"

_RULE_KINDS = ("p99_bound", "rate", "burn_rate", "counter_increase")

# Histogram-count pseudo-counter prefix: ``hist:serve_latency_seconds`` in a
# rule's ``good``/``bad`` reads that histogram's observation count — served
# requests are counted by the latency histogram, not a dedicated counter.
_HIST_PREFIX = "hist:"


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One declarative rule; which params matter depends on ``kind``."""

    name: str
    kind: str
    hist: str = ""           # p99_bound: histogram name
    bound_s: float = 0.0     # p99_bound: firing bound (seconds)
    min_count: int = 20      # p99_bound: observations before p99 is trusted
    bad: str = ""            # rate/burn_rate: numerator counter
    good: str = ""           # rate/burn_rate: denominator companion
    threshold: float = 0.05  # rate: firing fraction
    budget: float = 0.01     # burn_rate: allowed bad fraction (the budget)
    factor: float = 10.0     # burn_rate: burn multiple that fires
    counter: str = ""        # counter_increase: the watched counter
    window_s: float = 60.0   # rolling window for the windowed kinds
    min_events: int = 20     # rate/burn_rate: min bad+good window events

    def __post_init__(self) -> None:
        if self.kind not in _RULE_KINDS:
            raise ValueError(
                f"alert rule kind must be one of {_RULE_KINDS}; got "
                f"{self.kind!r}"
            )
        if not self.name:
            raise ValueError("alert rule name must be non-empty")
        if self.window_s <= 0:
            raise ValueError(f"window_s must be > 0; got {self.window_s}")


def default_alert_rules() -> Tuple[AlertRule, ...]:
    """The stock rule set. Env overrides for the two tunable bounds:
    ``CCTPU_ALERT_P99_S`` (default 30 s — far above any healthy micro-batch,
    so it only fires on a genuinely sick replica) and
    ``CCTPU_ALERT_REJECT_RATE`` (default 0.05 — a service shedding >5% of
    its traffic should be drained)."""
    p99_s = float(os.environ.get("CCTPU_ALERT_P99_S", "") or 30.0)
    reject = float(os.environ.get("CCTPU_ALERT_REJECT_RATE", "") or 0.05)
    served = _HIST_PREFIX + "serve_latency_seconds"
    return (
        AlertRule(
            P99_ALERT, "p99_bound",
            hist="serve_latency_seconds", bound_s=p99_s, min_count=50,
        ),
        AlertRule(
            REJECTION_ALERT, "rate",
            bad="serve_rejections", good=served, threshold=reject,
            window_s=60.0, min_events=20,
        ),
        AlertRule(
            BURN_ALERT, "burn_rate",
            bad="serve_rejections", good=served, budget=0.01, factor=10.0,
            window_s=300.0, min_events=50,
        ),
        AlertRule(
            EXHAUSTED_ALERT, "counter_increase",
            counter="retries_exhausted", window_s=300.0,
        ),
        AlertRule(
            AOT_ALERT, "counter_increase",
            counter="aot_fallbacks", window_s=300.0,
        ),
    )


class AlertEngine:
    """Level-triggered rule evaluation with raise/clear transitions.

    ``registries`` are read live (counters + histogram counts fold into one
    total per name); the tracer (when given) receives the transition events
    and owns the emission registry for the ``alerts_active`` gauge /
    ``alerts_raised`` counter. The sample ring is throttled (at most ~512
    samples per longest window) so a per-batch evaluation cadence stays
    O(1) memory however long the service lives.
    """

    def __init__(
        self,
        registries: Sequence[MetricsRegistry],
        rules: Optional[Sequence[AlertRule]] = None,
        tracer: Optional[Tracer] = None,
    ) -> None:
        self._regs: Tuple[MetricsRegistry, ...] = tuple(registries)
        self.rules: Tuple[AlertRule, ...] = tuple(
            rules if rules is not None else default_alert_rules()
        )
        self._tracer = tracer
        self.active: Dict[str, dict] = {}
        self.raised_total = 0
        self.cleared_total = 0
        self.last_alert: Optional[dict] = None
        windowed = [
            r.window_s for r in self.rules if r.kind != "p99_bound"
        ]
        self._max_window_s = max(windowed) if windowed else 60.0
        self._sample_gap_s = min(2.0, max(0.05, self._max_window_s / 512.0))
        # (t, {name: total}) ring; the head sample sits just outside the
        # longest window so every rule always has a delta base
        self._samples: "deque[Tuple[float, Dict[str, float]]]" = deque()

    # -- reading -------------------------------------------------------------

    def _totals(self) -> Dict[str, float]:
        vals: Dict[str, float] = {}
        for reg in self._regs:
            for name, c in list(reg.counters.items()):
                vals[name] = vals.get(name, 0.0) + c.value
            for name, h in list(reg.histograms.items()):
                key = _HIST_PREFIX + name
                vals[key] = vals.get(key, 0.0) + h.count
        return vals

    def _emit_metrics(self) -> MetricsRegistry:
        if self._tracer is not None:
            return self._tracer.metrics
        return self._regs[0] if self._regs else global_metrics()

    def _window_base(
        self, t: float, window_s: float
    ) -> Optional[Dict[str, float]]:
        """The newest sample at or outside ``t - window_s`` (else the oldest
        available — a partial window while the service is young)."""
        base: Optional[Dict[str, float]] = None
        for ts, vals in self._samples:
            if ts <= t - window_s:
                base = vals
            else:
                break
        if base is None and self._samples:
            base = self._samples[0][1]
        return base

    def _p99(self, rule: AlertRule) -> Optional[float]:
        best: Optional[float] = None
        for reg in self._regs:
            h = reg.histograms.get(rule.hist)
            if h is None or h.count < rule.min_count:
                continue
            try:
                q = h.quantile(0.99)
            except Exception:
                q = None
            if q is not None:
                best = q if best is None else max(best, q)
        return best

    def _eval_rule(
        self, rule: AlertRule, t: float, totals: Dict[str, float]
    ) -> Tuple[bool, Optional[float], float]:
        """(fired, observed value, firing threshold) for one rule."""
        if rule.kind == "p99_bound":
            p99 = self._p99(rule)
            return (p99 is not None and p99 > rule.bound_s, p99, rule.bound_s)
        base = self._window_base(t, rule.window_s) or {}
        if rule.kind == "counter_increase":
            delta = totals.get(rule.counter, 0.0) - base.get(rule.counter, 0.0)
            return (delta > 0, delta, 0.0)
        bad = totals.get(rule.bad, 0.0) - base.get(rule.bad, 0.0)
        good = totals.get(rule.good, 0.0) - base.get(rule.good, 0.0)
        events = bad + good
        if events < rule.min_events or events <= 0:
            return (False, None, rule.threshold)
        frac = bad / events
        if rule.kind == "rate":
            return (frac > rule.threshold, round(frac, 6), rule.threshold)
        burn = frac / rule.budget if rule.budget > 0 else float("inf")
        return (burn >= rule.factor, round(burn, 4), rule.factor)

    # -- evaluation ----------------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> Dict[str, dict]:
        """One evaluation pass: sample the registries, run every rule, fire
        raise/clear transitions, refresh the gauge. Returns the active-alert
        map. Never raises."""
        try:
            return self._evaluate(now)
        except Exception:
            return dict(self.active)

    def _evaluate(self, now: Optional[float]) -> Dict[str, dict]:
        t = time.monotonic() if now is None else float(now)
        totals = self._totals()
        if (
            not self._samples
            or t - self._samples[-1][0] >= self._sample_gap_s
        ):
            self._samples.append((t, totals))
            while (
                len(self._samples) >= 2
                and self._samples[1][0] <= t - self._max_window_s
            ):
                self._samples.popleft()
        mets = self._emit_metrics()
        for rule in self.rules:
            fired, value, threshold = self._eval_rule(rule, t, totals)
            was = rule.name in self.active
            if fired:
                info = {
                    "name": rule.name,
                    "kind": rule.kind,
                    "value": value,
                    "threshold": threshold,
                }
                if was:
                    info["since_s"] = self.active[rule.name].get(
                        "since_s", round(t, 4)
                    )
                else:
                    info["since_s"] = round(t, 4)
                    self.raised_total += 1
                    mets.counter("alerts_raised").inc()
                    self.last_alert = dict(info)
                    if self._tracer is not None:
                        self._tracer.event(
                            "alert_raised", name=rule.name, value=value,
                            threshold=threshold,
                        )
                self.active[rule.name] = info
            elif was:
                del self.active[rule.name]
                self.cleared_total += 1
                if self._tracer is not None:
                    self._tracer.event(
                        "alert_cleared", name=rule.name, value=value,
                    )
        mets.gauge("alerts_active").set(len(self.active))
        return dict(self.active)

    def summary(self) -> dict:
        """JSON-able block for ``RunRecord.alerts`` / bench rungs / ladder
        steps: a final evaluation plus the transition totals."""
        self.evaluate()
        return {
            "active": {k: dict(v) for k, v in sorted(self.active.items())},
            "raised_total": self.raised_total,
            "cleared_total": self.cleared_total,
            "last_alert": dict(self.last_alert) if self.last_alert else None,
            "rules": sorted(r.name for r in self.rules),
        }


def attach_alerts(
    tracer: Optional[Tracer],
    registries: Optional[Sequence[MetricsRegistry]] = None,
    rules: Optional[Sequence[AlertRule]] = None,
) -> Optional[AlertEngine]:
    """Hang an AlertEngine off ``tracer`` (idempotent — an attached engine
    is returned as-is) reading the tracer-local + process-global registries
    by default. ``RunRecord.from_tracer`` harvests
    ``tracer.alert_engine.summary()`` into the record's ``alerts`` block.
    None-safe for tracer-less callers."""
    if tracer is None:
        return None
    existing = getattr(tracer, "alert_engine", None)
    if isinstance(existing, AlertEngine):
        return existing
    regs: Sequence[MetricsRegistry] = (
        registries
        if registries is not None
        else (tracer.metrics, global_metrics())
    )
    engine = AlertEngine(regs, rules=rules, tracer=tracer)
    tracer.alert_engine = engine  # type: ignore[attr-defined]
    return engine


def alert_names(rules: Sequence[AlertRule]) -> List[str]:
    return sorted(r.name for r in rules)
