"""Deterministic work ledger: the noise-proof side of every perf claim.

Wall-clock on a shared CI host swings 0.17–1.1 boots/s on an identical
workload (docs/perf.md history) — a wall number alone cannot distinguish a
real regression from a busy neighbour. The pipeline itself is deterministic
end to end (seeded boots, fingerprinted labels), and the instrumentation
already counts the deterministic ingredients: ``counting_jit`` tallies
dispatches/compiles/flops/bytes into the process-global registry, the
pipeline counts boots into the tracer-local one. ``WorkLedger`` assembles
exactly those counters (``obs.schema.WORK_LEDGER_COUNTERS``) into a
per-run, per-top-level-phase block:

    {"counters": {name: delta-since-attach},
     "phases":   {root-span-name: {name: delta-while-that-phase-ran}}}

Same seeded workload ⇒ same ledger, on any host, however contended — which
is what makes it gateable exactly (``tools/bench_diff.py --gate work``: any
counter regression fails regardless of wall noise) while wall gates get to
be noise-aware. The block lands in ``RunRecord.work_ledger`` (schema v7)
and on every bench rung including the failure payload.

Attachment mirrors obs/resource.py's ResourceSampler: ``attach_ledger``
hangs the ledger off the tracer (idempotent) and registers a span-close
hook; per-phase attribution happens only at *root* span close (identity
scan of ``tracer.roots``), so the hook is one dict subtraction per
top-level phase — cheap enough to be always-on, unlike the opt-in sampler.

Caveats the exactness contract lives with: counters harvested from the
process-global registry (dispatches, compiles, …) see every thread in the
process, so concurrent background work (the async checkpoint writer, a
serving worker) lands in whatever phase is open when it increments — the
totals stay exact, the per-phase split is attribution, not isolation. And
``executable_compiles`` is deterministic only per process history: a warm
persistent cache still traces (trace count is what the counter measures),
but a second same-shape run in one process compiles 0. Bench rungs
therefore measure the ledger over a fixed post-warmup trial.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from consensusclustr_tpu.obs.metrics import global_metrics
from consensusclustr_tpu.obs.tracer import Tracer

# The ledger's counter set. Each ``*_WORK`` literal is validated against
# obs.schema.WORK_LEDGER_COUNTERS by tools/check_obs_schema.py, both
# directions (and the set must be a subset of METRIC_NAMES) — a renamed
# counter is a test failure, not a silently empty work gate.
DISPATCHES_WORK = "device_dispatches"
COMPILES_WORK = "executable_compiles"
FLOPS_WORK = "estimated_flops"
BYTES_WORK = "estimated_bytes_accessed"
DONATED_WORK = "donated_bytes"
BOOTS_WORK = "boots_completed"
FAULTS_WORK = "fault_injected"
RETRIES_WORK = "retry_attempts"
EXHAUSTED_WORK = "retries_exhausted"
QUARANTINED_WORK = "ckpt_quarantined"

# Serialization order of the ledger (stable across runs and tools).
LEDGER_COUNTERS = (
    DISPATCHES_WORK,
    COMPILES_WORK,
    FLOPS_WORK,
    BYTES_WORK,
    DONATED_WORK,
    BOOTS_WORK,
    FAULTS_WORK,
    RETRIES_WORK,
    EXHAUSTED_WORK,
    QUARANTINED_WORK,
)

# bench.py payload key -> ledger counter name, for the flat top-level keys
# bench rungs have emitted since schema v3 (kept for trend continuity; the
# structured block is ``work_ledger``). Single source of the mapping —
# bench.py imports this under its guarded-import convention and
# tools/check_obs_schema.py pins bench.py's fallback literal to it.
BENCH_DISPATCH_KEYS = {
    "device_dispatches": DISPATCHES_WORK,
    "executable_compiles": COMPILES_WORK,
    "donated_bytes": DONATED_WORK,
    "est_flops": FLOPS_WORK,
    "est_bytes": BYTES_WORK,
}


class WorkLedger:
    """Per-run deterministic work counters with top-level-phase attribution.

    Reads each ``LEDGER_COUNTERS`` name from both registries feeding the
    run (the process-global one counting_jit writes to, and the tracer's
    run-local one the pipeline writes to) and tracks deltas: since attach
    (``summary()["counters"]``) and per closed root span
    (``summary()["phases"]``). Repeated root names (``level`` per pass)
    accumulate. Never raises into the traced work.
    """

    def __init__(self, tracer: Tracer) -> None:
        self._tracer = tracer
        self._base = self._totals()
        self._last = dict(self._base)
        self._phases: Dict[str, Dict[str, int]] = {}

    def _totals(self) -> Dict[str, int]:
        vals: Dict[str, int] = {}
        for name in LEDGER_COUNTERS:
            total = 0.0
            for reg in (global_metrics(), self._tracer.metrics):
                c = reg.counters.get(name)
                if c is not None:
                    total += c.value
            vals[name] = int(total)
        return vals

    def on_span_close(self, span: Any) -> None:
        """Span-close hook: attribute the counter delta since the previous
        root close to this root span's name. Child spans are ignored —
        attribution is per top-level phase, matching ``phase_seconds``."""
        try:
            if not any(span is r for r in self._tracer.roots):
                return
            now = self._totals()
            phase = self._phases.setdefault(
                span.name, {k: 0 for k in LEDGER_COUNTERS}
            )
            for k in LEDGER_COUNTERS:
                phase[k] += max(0, now[k] - self._last[k])
            self._last = now
        except Exception:
            pass  # observability must never fail the traced work

    def summary(self) -> dict:
        """JSON-able ledger block: total deltas since attach + the per-phase
        attribution collected so far."""
        now = self._totals()
        return {
            "counters": {
                k: max(0, now[k] - self._base[k]) for k in LEDGER_COUNTERS
            },
            "phases": {
                name: dict(vals) for name, vals in self._phases.items()
            },
        }


def attach_ledger(tracer: Optional[Tracer]) -> Optional[WorkLedger]:
    """Hang a WorkLedger off ``tracer`` (idempotent — an already-attached
    ledger is returned as-is) and register its root-span-close hook.
    ``RunRecord.from_tracer`` harvests ``tracer.work_ledger.summary()``
    into the record's ``work_ledger`` block. None-safe for tracer-less
    callers."""
    if tracer is None:
        return None
    existing = getattr(tracer, "work_ledger", None)
    if isinstance(existing, WorkLedger):
        return existing
    ledger = WorkLedger(tracer)
    tracer.work_ledger = ledger  # type: ignore[attr-defined]
    tracer.add_span_close_hook(ledger.on_span_close)
    return ledger
