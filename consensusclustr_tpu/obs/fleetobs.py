"""Fleet-wide observability aggregation (ISSUE 19 tentpole, part b).

Every observability surface built in PRs 1–16 — tracer, run records,
Perfetto export, flight recorder, alerts — is strictly per-process: each
``AssignmentService`` replica and the ``FleetRouter`` itself owns a private
:class:`~consensusclustr_tpu.obs.tracer.Tracer` with its own epoch, its own
metric registry, and its own event stream. A request that is admitted by
the router, orphaned by a replica death and re-routed to a revival slot
therefore leaves *three unlinked fragments in three separate tracers*.

:class:`FleetRecord` is the merge: the router's RunRecord, every replica's
RunRecord — **including retired replicas** (revival-replaced or
swap-drained; the router keeps them precisely so their lanes stay
renderable), each stamped with its tracer's epoch offset from the router's
(``Tracer.epoch_offset_from``), so all timestamps rebase onto one shared
timeline — plus the router's retained hop-chain table (the fleet-scoped
``trace_id`` → ordered hops the router records per admission).

Consumers:

  * ``obs/export.py::fleet_chrome_trace`` — one Perfetto trace, one process
    lane per replica (the router gets its own), cross-replica
    ``ph:"s"/"t"/"f"`` flow links along each multi-hop chain, fleet gauges
    as counter tracks;
  * ``tools/timeline.py`` — the causally ordered incident timeline
    (stdlib-only: it folds the serialized dict, never this module);
  * ``tools/report.py`` / ``tools/chaos_audit.py`` / ``tools/loadgen.py`` —
    the reviewable incident artifact each fleet run can emit
    (``CCTPU_FLEET_TRACE_PATH``).

The FleetRecord is a NEW artifact kind (``"fleet_record"``) that *embeds*
RunRecords — the RunRecord layout itself is unchanged at schema v11.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from consensusclustr_tpu.obs.record import RunRecord
from consensusclustr_tpu.obs.schema import SCHEMA_VERSION

FLEET_RECORD_KIND = "fleet_record"


@dataclass
class FleetRecord:
    """One merged, schema-versioned snapshot of a whole fleet's telemetry.

    ``replicas`` entries are ``{"name", "retired", "epoch_offset_s",
    "record"}`` — ``epoch_offset_s`` is the replica tracer's birth relative
    to the router tracer's (positive = born later), the rebase every
    consumer applies to put all lanes on the router's clock. ``trace`` is
    the router's hop-chain table (``FleetRouter.trace_table()``).
    """

    schema: int = SCHEMA_VERSION
    generation: int = 0
    router: dict = field(default_factory=dict)
    replicas: List[dict] = field(default_factory=list)
    trace: dict = field(default_factory=dict)
    routed: Dict[str, int] = field(default_factory=dict)

    # -- construction --------------------------------------------------------

    @classmethod
    def from_router(cls, router, config=None) -> "FleetRecord":
        """Snapshot a live :class:`~consensusclustr_tpu.serve.router.
        FleetRouter`: its own record, every replica it ever owned (current
        rotation first, then retired slots), and the retained hop chains.
        Callable mid-run or post-close — tracers outlive their services."""
        from consensusclustr_tpu.utils.backend import default_backend

        backend = default_backend()
        router_rec = RunRecord.from_tracer(
            router.tracer, config=config, backend=backend,
            include_global_metrics=False,
        )
        replicas = []
        for name, svc, retired in router.replica_records():
            rec = RunRecord.from_tracer(
                svc.tracer, config=None, backend=backend,
                include_global_metrics=False,
            )
            replicas.append({
                "name": str(name),
                "retired": bool(retired),
                "epoch_offset_s": svc.tracer.epoch_offset_from(router.tracer),
                "record": rec.to_dict(),
            })
        return cls(
            schema=SCHEMA_VERSION,
            generation=int(router.generation),
            router=router_rec.to_dict(),
            replicas=replicas,
            trace=router.trace_table(),
            routed=dict(router.routed_per_replica()),
        )

    # -- (de)serialization ---------------------------------------------------

    def to_dict(self) -> dict:
        return {
            "kind": FLEET_RECORD_KIND,
            "schema": self.schema,
            "generation": self.generation,
            "router": self.router,
            "replicas": self.replicas,
            "trace": self.trace,
            "routed": self.routed,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    def write(self, path: str) -> str:
        """One whole-fleet JSON document (NOT JSONL — a FleetRecord is one
        incident artifact, not an append-stream of runs)."""
        with open(path, "w") as f:
            json.dump(self.to_dict(), f)
        return path

    @classmethod
    def from_dict(cls, d: dict) -> "FleetRecord":
        return cls(
            schema=int(d.get("schema") or 0),
            generation=int(d.get("generation") or 0),
            router=dict(d.get("router") or {}),
            replicas=list(d.get("replicas") or []),
            trace=dict(d.get("trace") or {}),
            routed=dict(d.get("routed") or {}),
        )

    @classmethod
    def load(cls, path: str) -> "FleetRecord":
        with open(path, encoding="utf-8") as f:
            return cls.from_dict(json.load(f))

    # -- rendering -----------------------------------------------------------

    def to_chrome_trace(self, path: str, metadata: Optional[dict] = None) -> str:
        """The merged Perfetto trace (ui.perfetto.dev): router + replica
        process lanes, cross-replica flow links, fleet counter tracks."""
        from consensusclustr_tpu.obs.export import write_fleet_chrome_trace

        return write_fleet_chrome_trace(path, self.to_dict(), metadata=metadata)

    def multi_hop_traces(self) -> List[dict]:
        """The re-routed requests: retained hop chains with >= 2 hops (the
        ones the fleet export draws cross-replica flow links for)."""
        return [
            tr for tr in (self.trace.get("traces") or ())
            if len(tr.get("hops") or ()) >= 2
        ]

    def summary(self) -> dict:
        """The compact block bench/loadgen payloads embed as
        ``fleet_trace``: chain retention plus the multi-hop (re-route)
        count — enough for tools/perf_history.py to trend."""
        traces = self.trace.get("traces") or ()
        return {
            "replicas": len(self.replicas),
            "retired": sum(1 for r in self.replicas if r.get("retired")),
            "traces": len(traces),
            "multi_hop": len(self.multi_hop_traces()),
            "dropped": int(self.trace.get("dropped") or 0),
        }
