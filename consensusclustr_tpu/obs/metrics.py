"""Metrics registry: counters, gauges, bucketed histograms.

Host-side (never traced) accounting for the quantities the pipeline already
knows but previously threw away: bootstraps completed, mesh fallbacks, best
silhouettes, compile-cache state, device memory. A registry is cheap plain
Python — safe to update from tight host loops — and snapshots to a flat
JSON-able dict that lands in the RunRecord.

Two scopes exist: the process-global registry (``global_metrics()``) for
things that outlive one run (persistent compile cache), and a per-``Tracer``
registry for run-local counts. ``RunRecord.from_tracer`` merges both.

Histograms carry fixed log-spaced bucket counts (obs/hist.py) in addition to
the streaming count/sum/min/max summary: memory stays bounded, ``observe``
stays one bisect, and ``quantile(q)`` answers the p50/p99 questions that
previously required keeping raw samples around. ``MetricsRegistry`` mutations
that change the name->instrument maps (creation, ``merge``) are lock-guarded:
the registry is written concurrently by ``AssignmentService`` worker threads
and the ``AsyncChunkWriter`` background thread, and an unguarded ``setdefault``
race can hand two threads distinct instruments for the same name (one of
which silently loses its observations).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Tuple

from consensusclustr_tpu.obs.hist import (
    DEFAULT_BOUNDS,
    bucket_index,
    bucket_quantile,
    merge_bucket_counts,
)

# One warning per process for bucket-ladder merge drops (ISSUE 7 satellite):
# the drop itself is counted per occurrence (``hist_merge_mismatch``), the
# log line fires once so a merge-heavy run cannot flood stderr.
_MERGE_MISMATCH_WARNED = False


def _warn_merge_mismatch(name: str) -> None:
    global _MERGE_MISMATCH_WARNED
    if _MERGE_MISMATCH_WARNED:
        return
    _MERGE_MISMATCH_WARNED = True
    try:
        from consensusclustr_tpu.utils.log import get_logger

        get_logger().warning(
            "histogram %r merged across mismatched bucket ladders: bucket "
            "counts dropped (summary stays exact, quantiles return None); "
            "counted in hist_merge_mismatch, warning once per process", name
        )
    except Exception:
        pass  # observability must never fail the merge


@dataclasses.dataclass
class Counter:
    """Monotonically increasing count."""

    value: float = 0.0

    def inc(self, by: float = 1.0) -> None:
        self.value += by


@dataclasses.dataclass
class Gauge:
    """Last-written value (set() wins; unset gauges serialize as None)."""

    value: Optional[float] = None

    def set(self, value: float) -> None:
        self.value = float(value)


@dataclasses.dataclass
class Histogram:
    """Streaming summary (count/sum/min/max) + fixed log-spaced ``le``
    buckets — memory-bounded, hot-loop safe (one bisect per observe), and
    quantile-capable without retaining raw samples."""

    count: int = 0
    sum: float = 0.0
    min: Optional[float] = None
    max: Optional[float] = None
    bounds: Tuple[float, ...] = DEFAULT_BOUNDS
    bucket_counts: List[int] = dataclasses.field(default_factory=list)

    def __post_init__(self) -> None:
        if not self.bucket_counts:
            self.bucket_counts = [0] * (len(self.bounds) + 1)

    def observe(self, value: float) -> None:
        value = float(value)
        self.count += 1
        self.sum += value
        self.min = value if self.min is None else min(self.min, value)
        self.max = value if self.max is None else max(self.max, value)
        self.bucket_counts[bucket_index(self.bounds, value)] += 1

    @property
    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    def quantile(self, q: float) -> Optional[float]:
        """Estimated q-quantile from the bucket counts (None when empty, or
        when a bounds-mismatched merge invalidated the buckets). Within one
        bucket width of the exact sample quantile — see obs/hist.py."""
        if not self.bucket_counts:
            return None
        return bucket_quantile(
            self.bounds, self.bucket_counts, q, lo=self.min, hi=self.max
        )


class MetricsRegistry:
    """Named counters/gauges/histograms with lazy creation and merge.

    Creation, ``merge`` and ``snapshot`` hold an internal lock (concurrent
    writers: serving worker threads, the async checkpoint writer). Instrument
    mutation (``inc``/``set``/``observe``) is intentionally not locked — each
    writer owns its instruments by convention and a hot-loop lock would cost
    more than it protects.
    """

    def __init__(self) -> None:
        self.counters: Dict[str, Counter] = {}
        self.gauges: Dict[str, Gauge] = {}
        self.histograms: Dict[str, Histogram] = {}
        self._lock = threading.Lock()

    def counter(self, name: str) -> Counter:
        try:
            return self.counters[name]
        except KeyError:
            with self._lock:
                return self.counters.setdefault(name, Counter())

    def gauge(self, name: str) -> Gauge:
        try:
            return self.gauges[name]
        except KeyError:
            with self._lock:
                return self.gauges.setdefault(name, Gauge())

    def histogram(self, name: str) -> Histogram:
        try:
            return self.histograms[name]
        except KeyError:
            with self._lock:
                return self.histograms.setdefault(name, Histogram())

    def merge(self, other: "MetricsRegistry") -> "MetricsRegistry":
        """Fold ``other`` into self: counters add, later gauges win (when
        set), histogram summaries and bucket counts combine. An empty
        receiver adopts the incoming bucket ladder; a genuine bounds
        mismatch drops the buckets (the summary stays exact, quantiles
        return None) — counted in ``hist_merge_mismatch`` and warned once
        per process (ISSUE 7 satellite: the PR 4 drop was silent). Returns
        self for chaining."""
        with self._lock:
            for name, c in other.counters.items():
                self.counters.setdefault(name, Counter()).inc(c.value)
            for name, g in other.gauges.items():
                if g.value is not None:
                    self.gauges.setdefault(name, Gauge()).set(g.value)
            for name, h in other.histograms.items():
                mine = self.histograms.setdefault(name, Histogram())
                fresh = mine.count == 0  # nothing observed: adopt their ladder
                mine.count += h.count
                mine.sum += h.sum
                for bound in ("min", "max"):
                    theirs = getattr(h, bound)
                    if theirs is None:
                        continue
                    ours = getattr(mine, bound)
                    pick = theirs if ours is None else (
                        min(ours, theirs) if bound == "min" else max(ours, theirs)
                    )
                    setattr(mine, bound, pick)
                if fresh and h.bucket_counts:
                    mine.bounds = tuple(h.bounds)
                    mine.bucket_counts = list(h.bucket_counts)
                    continue
                merged = merge_bucket_counts(
                    mine.bounds, mine.bucket_counts, h.bounds, h.bucket_counts
                )
                if merged is not None:
                    mine.bucket_counts = merged
                else:
                    mine.bucket_counts = []
                    # direct dict access: self._lock is held (non-reentrant),
                    # the counter() accessor would deadlock here
                    self.counters.setdefault(
                        "hist_merge_mismatch", Counter()
                    ).inc()
                    _warn_merge_mismatch(name)
        return self

    def snapshot(self) -> dict:
        """Flat JSON-able view; empty sections are dropped. Histograms carry
        their bucket ladder (``bounds`` + per-bucket ``bucket_counts``) so
        serialized records keep quantiles answerable — obs/export.py and
        tools/report.py re-estimate from exactly these fields."""
        with self._lock:
            out: dict = {}
            if self.counters:
                out["counters"] = {
                    k: c.value for k, c in sorted(self.counters.items())
                }
            if self.gauges:
                out["gauges"] = {k: g.value for k, g in sorted(self.gauges.items())}
            if self.histograms:
                out["histograms"] = {
                    k: {
                        "count": h.count, "sum": round(h.sum, 6),
                        "min": h.min, "max": h.max, "mean": h.mean,
                        **(
                            {
                                "bounds": list(h.bounds),
                                "bucket_counts": list(h.bucket_counts),
                            }
                            if h.bucket_counts
                            else {}
                        ),
                    }
                    for k, h in sorted(self.histograms.items())
                }
            return out

    def to_prom_text(self) -> str:
        """Prometheus text exposition (# HELP/# TYPE + samples) of the whole
        registry; histograms emit cumulative ``_bucket{le=...}`` series plus
        ``_sum``/``_count``. See obs/export.py for the format contract."""
        from consensusclustr_tpu.obs.export import prom_text_from_snapshot

        return prom_text_from_snapshot(self.snapshot())


_GLOBAL = MetricsRegistry()


def global_metrics() -> MetricsRegistry:
    """Process-wide registry (compile cache and other cross-run state)."""
    return _GLOBAL


def record_device_memory(registry: MetricsRegistry) -> None:
    """Gauge the first local device's live memory when the backend reports it
    (TPU/GPU do; XLA:CPU returns None) — never raises, never initializes a
    backend that the process hasn't already touched."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return
    if not stats:
        return
    if "bytes_in_use" in stats:
        registry.gauge("device_bytes_in_use").set(int(stats["bytes_in_use"]))
    if "peak_bytes_in_use" in stats:
        registry.gauge("device_peak_bytes_in_use").set(
            int(stats["peak_bytes_in_use"])
        )
