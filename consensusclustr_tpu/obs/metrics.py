"""Metrics registry: counters, gauges, histograms.

Host-side (never traced) accounting for the quantities the pipeline already
knows but previously threw away: bootstraps completed, mesh fallbacks, best
silhouettes, compile-cache state, device memory. A registry is cheap plain
Python — safe to update from tight host loops — and snapshots to a flat
JSON-able dict that lands in the RunRecord.

Two scopes exist: the process-global registry (``global_metrics()``) for
things that outlive one run (persistent compile cache), and a per-``Tracer``
registry for run-local counts. ``RunRecord.from_tracer`` merges both.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional


@dataclasses.dataclass
class Counter:
    """Monotonically increasing count."""

    value: float = 0.0

    def inc(self, by: float = 1.0) -> None:
        self.value += by


@dataclasses.dataclass
class Gauge:
    """Last-written value (set() wins; unset gauges serialize as None)."""

    value: Optional[float] = None

    def set(self, value: float) -> None:
        self.value = float(value)


@dataclasses.dataclass
class Histogram:
    """Streaming summary (count/sum/min/max) — no buckets, no raw samples,
    so hot loops can observe() without growing memory."""

    count: int = 0
    sum: float = 0.0
    min: Optional[float] = None
    max: Optional[float] = None

    def observe(self, value: float) -> None:
        value = float(value)
        self.count += 1
        self.sum += value
        self.min = value if self.min is None else min(self.min, value)
        self.max = value if self.max is None else max(self.max, value)

    @property
    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None


class MetricsRegistry:
    """Named counters/gauges/histograms with lazy creation and merge."""

    def __init__(self) -> None:
        self.counters: Dict[str, Counter] = {}
        self.gauges: Dict[str, Gauge] = {}
        self.histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        return self.counters.setdefault(name, Counter())

    def gauge(self, name: str) -> Gauge:
        return self.gauges.setdefault(name, Gauge())

    def histogram(self, name: str) -> Histogram:
        return self.histograms.setdefault(name, Histogram())

    def merge(self, other: "MetricsRegistry") -> "MetricsRegistry":
        """Fold ``other`` into self: counters add, later gauges win (when
        set), histogram summaries combine. Returns self for chaining."""
        for name, c in other.counters.items():
            self.counter(name).inc(c.value)
        for name, g in other.gauges.items():
            if g.value is not None:
                self.gauge(name).set(g.value)
        for name, h in other.histograms.items():
            mine = self.histogram(name)
            mine.count += h.count
            mine.sum += h.sum
            for bound in ("min", "max"):
                theirs = getattr(h, bound)
                if theirs is None:
                    continue
                ours = getattr(mine, bound)
                pick = theirs if ours is None else (
                    min(ours, theirs) if bound == "min" else max(ours, theirs)
                )
                setattr(mine, bound, pick)
        return self

    def snapshot(self) -> dict:
        """Flat JSON-able view; empty sections are dropped."""
        out: dict = {}
        if self.counters:
            out["counters"] = {k: c.value for k, c in sorted(self.counters.items())}
        if self.gauges:
            out["gauges"] = {k: g.value for k, g in sorted(self.gauges.items())}
        if self.histograms:
            out["histograms"] = {
                k: {
                    "count": h.count, "sum": round(h.sum, 6),
                    "min": h.min, "max": h.max, "mean": h.mean,
                }
                for k, h in sorted(self.histograms.items())
            }
        return out


_GLOBAL = MetricsRegistry()


def global_metrics() -> MetricsRegistry:
    """Process-wide registry (compile cache and other cross-run state)."""
    return _GLOBAL


def record_device_memory(registry: MetricsRegistry) -> None:
    """Gauge the first local device's live memory when the backend reports it
    (TPU/GPU do; XLA:CPU returns None) — never raises, never initializes a
    backend that the process hasn't already touched."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return
    if not stats:
        return
    if "bytes_in_use" in stats:
        registry.gauge("device_bytes_in_use").set(int(stats["bytes_in_use"]))
    if "peak_bytes_in_use" in stats:
        registry.gauge("device_peak_bytes_in_use").set(
            int(stats["peak_bytes_in_use"])
        )
