"""Resource profiling: host-RSS + device-memory sampling with per-phase
watermark attribution (ISSUE 6 tentpole).

The dense consensus accumulator is O(n²) host/device memory (ROADMAP O1:
6.9 GB RSS at 50k cells, ~2.7 TB extrapolated at 1M) — but until this module
the obs layer could not *see* memory: device ``memory_stats()`` was a
one-shot gauge pair and host RSS lived in an ad-hoc ``getrusage`` call.
:class:`ResourceSampler` closes that gap:

  * a background daemon thread samples host RSS (``/proc/self/statm``,
    stdlib + psutil-free, with a ``getrusage`` maxrss fallback on platforms
    without procfs — documented as a peak, not a current value) and the
    first local device's ``memory_stats()`` on a configurable interval;
  * every sample lands in a bounded time series of
    ``(t, rss_bytes, device_bytes_in_use)`` tuples (decimated 2:1 past
    ``CCTPU_RESOURCE_MAX_SAMPLES`` so week-long runs stay bounded) and
    updates the ``host_rss_bytes`` / ``host_peak_rss_bytes`` /
    ``device_bytes_in_use`` / ``device_peak_bytes_in_use`` gauges plus the
    ``resource_samples`` counter;
  * attached to a :class:`~consensusclustr_tpu.obs.tracer.Tracer`, a
    span-close hook stamps per-phase **watermarks** — the peak RSS/device
    bytes observed while the span ran — as ``rss_peak_bytes`` /
    ``device_peak_bytes`` span attrs (registered in
    ``obs.schema.RESOURCE_SPAN_ATTRS``), which is what ``tools/report.py``'s
    "== memory ==" table and the O1 peak-memory bench gate consume;
  * the series serializes into ``RunRecord.resource`` (schema v4) and
    ``obs/export.py`` renders it as Perfetto ``ph:"C"`` counter tracks
    alongside the span lanes.

Sampling is **off by default** (interval 0): tests and library users pay
zero overhead unless ``ClusterConfig.resource_sample_ms`` or
``$CCTPU_RESOURCE_SAMPLE_MS`` turns it on. The device read never initializes
a backend the process hasn't already brought up — a wedged TPU tunnel would
otherwise hang the sampler thread inside a C call.
"""

from __future__ import annotations

import bisect
import contextlib
import os
import sys
import threading
import time
from typing import Any, List, Optional, Tuple

from consensusclustr_tpu.obs.metrics import MetricsRegistry, global_metrics

# Span attrs stamped at close time; the literal values are validated against
# obs.schema.RESOURCE_SPAN_ATTRS by tools/check_obs_schema.py.
RSS_PEAK_ATTR = "rss_peak_bytes"
DEVICE_PEAK_ATTR = "device_peak_bytes"

DEFAULT_MAX_SAMPLES = 4096

_PAGE_SIZE: Optional[int] = None


def resolve_sample_ms(requested: Optional[int] = None) -> int:
    """Explicit arg > $CCTPU_RESOURCE_SAMPLE_MS > 0 (off).

    0 (or "off"/"none" in the env var) disables sampling entirely — the
    default, so the sampler is opt-in everywhere (docs/quirks.md).
    """
    if requested is None:
        env = os.environ.get("CCTPU_RESOURCE_SAMPLE_MS", "").strip().lower()
        if env in ("", "off", "none"):
            return 0
        requested = env
    v = int(requested)
    if v < 0:
        raise ValueError(
            f"resource_sample_ms must be >= 0 (0 = off); got {v}"
        )
    return v


def host_rss_bytes() -> int:
    """Current host resident-set size in bytes.

    ``/proc/self/statm`` field 2 (resident pages) x page size on Linux; the
    ``resource.getrusage`` ru_maxrss fallback elsewhere is a *peak*, not a
    current value — still monotone-correct for watermarks. 0 when neither
    source exists (the sampler then records an honest zero, never raises).
    """
    global _PAGE_SIZE
    try:
        with open("/proc/self/statm", "rb") as f:
            fields = f.read().split()
        if _PAGE_SIZE is None:
            _PAGE_SIZE = int(os.sysconf("SC_PAGE_SIZE"))
        return int(fields[1]) * _PAGE_SIZE
    except Exception:
        pass
    try:
        import resource as _resource

        # ru_maxrss is KB on Linux (moot: statm exists there), bytes on macOS
        v = int(_resource.getrusage(_resource.RUSAGE_SELF).ru_maxrss)
        return v if sys.platform == "darwin" else v * 1024
    except Exception:
        return 0


def device_memory_bytes() -> Tuple[Optional[int], Optional[int]]:
    """(bytes_in_use, peak_bytes_in_use) of the first local device, or
    (None, None) when unavailable (no jax, backend not yet initialized,
    XLA:CPU's empty stats). Deliberately refuses to *initialize* a backend:
    ``jax.local_devices()`` on a wedged serving tunnel hangs inside a C call
    where no timeout can reach, and a profiling thread must never be the
    thing that dials the accelerator first.
    """
    jax = sys.modules.get("jax")
    if jax is None:
        return (None, None)
    try:
        from jax._src import xla_bridge

        if not getattr(xla_bridge, "_backends", None):
            return (None, None)  # process hasn't touched a backend yet
    except Exception:
        pass  # private-API drift: fall through to the guarded call
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return (None, None)
    if not stats:
        return (None, None)
    in_use = stats.get("bytes_in_use")
    peak = stats.get("peak_bytes_in_use")
    return (
        int(in_use) if in_use is not None else None,
        int(peak) if peak is not None else None,
    )


class ResourceSampler:
    """Background host-RSS + device-memory sampler with span attribution.

    Lifecycle: ``start()`` takes one immediate sample (short runs always get
    a watermark) and spawns the daemon thread; ``stop()`` joins it and takes
    a closing sample; both are idempotent and a stopped sampler can be
    restarted (the series keeps accumulating — one sampler per Tracer even
    across recursion levels). ``sample_ms <= 0`` disables everything:
    ``start()`` is a no-op and the series stays empty.

    Thread safety: the sample list and peaks are lock-guarded (writer: the
    sampler thread; readers: span-close hooks on the pipeline thread and
    RunRecord serialization). Gauge updates ride the metrics registry's own
    conventions (one writer per instrument).
    """

    def __init__(
        self,
        sample_ms: Optional[int] = None,
        metrics: Optional[MetricsRegistry] = None,
        epoch: Optional[float] = None,
        max_samples: Optional[int] = None,
    ) -> None:
        self.sample_ms = resolve_sample_ms(sample_ms)
        self.metrics = metrics
        self.epoch = time.monotonic() if epoch is None else float(epoch)
        self.max_samples = int(
            max_samples
            if max_samples is not None
            else os.environ.get("CCTPU_RESOURCE_MAX_SAMPLES", DEFAULT_MAX_SAMPLES)
        )
        # (t_seconds_since_epoch, rss_bytes, device_bytes_in_use_or_None),
        # strictly time-ordered (single appender + lock)
        self.samples: List[Tuple[float, int, Optional[int]]] = []
        self._lock = threading.Lock()
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._peak_rss = 0
        self._peak_device: Optional[int] = None
        # decimation doubles the effective interval so the series stays
        # bounded without losing the envelope of long runs
        self._effective_ms = max(self.sample_ms, 1)
        self._attached: List[Any] = []

    # -- state ---------------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self.sample_ms > 0

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    @property
    def peak_rss_bytes(self) -> int:
        return self._peak_rss

    @property
    def peak_device_bytes(self) -> Optional[int]:
        return self._peak_device

    # -- sampling ------------------------------------------------------------

    def sample_now(self) -> Tuple[float, int, Optional[int]]:
        """Take one sample immediately (also valid while stopped): appends to
        the series, advances the peak watermarks, refreshes the gauges."""
        t = round(time.monotonic() - self.epoch, 4)
        rss = host_rss_bytes()
        dev, dev_peak = device_memory_bytes()
        with self._lock:
            self.samples.append((t, rss, dev))
            if len(self.samples) >= self.max_samples:
                self.samples = self.samples[::2]
                self._effective_ms *= 2
            self._peak_rss = max(self._peak_rss, rss)
            if dev is not None:
                cand = max(dev, dev_peak if dev_peak is not None else dev)
                self._peak_device = (
                    cand
                    if self._peak_device is None
                    else max(self._peak_device, cand)
                )
        mets = self.metrics if self.metrics is not None else global_metrics()
        mets.counter("resource_samples").inc()
        mets.gauge("host_rss_bytes").set(rss)
        mets.gauge("host_peak_rss_bytes").set(self._peak_rss)
        if dev is not None:
            mets.gauge("device_bytes_in_use").set(dev)
            mets.gauge("device_peak_bytes_in_use").set(self._peak_device)
        return (t, rss, dev)

    def _loop(self) -> None:
        while not self._stop_event.wait(self._effective_ms / 1000.0):
            try:
                self.sample_now()
            except Exception:
                pass  # profiling must never kill the run

    def start(self) -> "ResourceSampler":
        if not self.enabled or self.running:
            return self
        self._stop_event.clear()
        try:
            self.sample_now()  # short spans still see >= 1 sample
        except Exception:
            pass
        self._thread = threading.Thread(
            target=self._loop, name="cctpu-resource-sampler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> "ResourceSampler":
        stopped_thread = self._thread is not None
        if stopped_thread:
            self._stop_event.set()
            self._thread.join(timeout=5.0)
            self._thread = None
        if stopped_thread and self.enabled:
            try:
                self.sample_now()  # closing watermark (once per start/stop)
            except Exception:
                pass
        return self

    def __enter__(self) -> "ResourceSampler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- span attribution ----------------------------------------------------

    def attach(self, tracer: Any) -> "ResourceSampler":
        """Bind to a Tracer: adopt its epoch (so sample ``t`` aligns with
        span ``t0``) and metrics registry, register the span-close watermark
        hook, and expose self as ``tracer.resource_sampler`` (where
        ``RunRecord.from_tracer`` picks the series up). Idempotent."""
        if tracer is None or tracer in self._attached:
            return self
        if self.metrics is None:
            self.metrics = tracer.metrics
        if not self.samples:
            self.epoch = tracer.epoch
        tracer.resource_sampler = self
        tracer.add_span_close_hook(self._on_span_close)
        self._attached.append(tracer)
        return self

    def _window(
        self, t0: float, t1: float
    ) -> List[Tuple[float, int, Optional[int]]]:
        with self._lock:
            lo = bisect.bisect_left(self.samples, (t0,))
            hi = bisect.bisect_right(
                self.samples, (t1, float("inf"), float("inf"))
            )
            return self.samples[lo:hi]

    def _on_span_close(self, span: Any) -> None:
        """Stamp the peak RSS/device watermark observed while ``span`` was
        open. Spans shorter than the interval force one sample at close so
        every phase gets attributed."""
        if not self.enabled:
            return
        t0 = float(span.t0)
        t1 = t0 + float(span.seconds or 0.0)
        window = self._window(t0, t1)
        if not window:
            if not self.running and not self.samples:
                return  # never started: stay silent, not half-attributed
            try:
                window = [self.sample_now()]
            except Exception:
                return
        span.attrs[RSS_PEAK_ATTR] = int(max(s[1] for s in window))
        device = [s[2] for s in window if s[2] is not None]
        if device:
            span.attrs[DEVICE_PEAK_ATTR] = int(max(device))

    # -- serialization -------------------------------------------------------

    def series_dict(self) -> dict:
        """JSON-able summary for ``RunRecord.resource`` (schema v4): the
        bounded sample series plus the run-wide peak watermarks."""
        with self._lock:
            samples = list(self.samples)
        return {
            "sample_ms": self.sample_ms,
            "n_samples": len(samples),
            "rss_peak_bytes": int(self._peak_rss),
            "device_peak_bytes": (
                int(self._peak_device) if self._peak_device is not None else None
            ),
            "samples": [
                [t, int(rss), int(dev) if dev is not None else None]
                for t, rss, dev in samples
            ],
        }


def start_for(tracer: Any, sample_ms: Optional[int] = None) -> Optional[ResourceSampler]:
    """Attach + start a sampler on ``tracer`` when the resolved interval is
    on; None otherwise. The caller owns the matching ``stop()`` (api.py wraps
    the run in try/finally)."""
    if tracer is None or resolve_sample_ms(sample_ms) <= 0:
        return None
    return ResourceSampler(sample_ms, epoch=tracer.epoch).attach(tracer).start()


@contextlib.contextmanager
def resource_sampling(tracer: Any, sample_ms: Optional[int] = None):
    """Bracket a region with resource sampling on ``tracer``.

    Reuses the tracer's existing sampler when one is attached (restarting it
    if a previous bracket stopped it — recursion levels keep extending one
    series) and only stops what this call itself started, so an outer
    api-level sampler keeps running across inner pipeline brackets. Yields
    the sampler, or None when sampling is off.
    """
    sampler = getattr(tracer, "resource_sampler", None) if tracer is not None else None
    if sampler is None:
        if tracer is None or resolve_sample_ms(sample_ms) <= 0:
            yield None
            return
        sampler = ResourceSampler(sample_ms, epoch=tracer.epoch).attach(tracer)
    started = False
    if sampler.enabled and not sampler.running:
        sampler.start()
        started = True
    try:
        yield sampler
    finally:
        if started:
            sampler.stop()
