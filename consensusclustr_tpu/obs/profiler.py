"""Span-tagged sampling profiler (ISSUE 16 tentpole front 2).

A stdlib-only daemon thread samples ``sys._current_frames()`` at
``CCTPU_PROFILE_HZ`` and folds each thread's stack into a bounded weighted
map of collapsed call paths. When a :class:`~consensusclustr_tpu.obs.tracer.
Tracer` is attached, each sample is prefixed with that thread's current
open-span path (``span:<name>`` frames), so a flamegraph shows *which phase*
the host was spinning in, not just which function — the tracer tells you a
span took 40 s, the profiler tells you the 40 s was spent inside
``_harvest_cost`` re-lowering rather than in the dispatch itself.

Opt-in and off by default: ``resolve_profile_hz`` treats an unset/zero knob
as disabled, ``SamplingProfiler.start`` is a no-op when disabled, and the
tracer's span path publishing only happens while a profiler is attached —
the unarmed run does one attribute check per span push/pop and NOTHING else
(the off-is-free pin in tests/test_profiler.py, PR 8/14 style).

Memory is bounded: at most ``CCTPU_PROFILE_MAX_NODES`` distinct folded
stacks are retained; samples landing on new stacks past the cap increment a
``dropped`` counter instead of allocating. The per-frame depth is capped the
same way the flight recorder caps thread stacks.

Armed profilers register in a process-global list so the flight recorder
(obs/flight.py) can ride the current summary into ``postmortem.json`` — a
stall dump then shows where the process was actually spinning.
"""

from __future__ import annotations

import os
import sys
import threading
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

DEFAULT_MAX_NODES = 4096
_FRAME_DEPTH_CAP = 64  # frames kept per sampled stack (leaf-most preserved)

_active_lock = threading.Lock()
_ACTIVE: List["SamplingProfiler"] = []


def resolve_profile_hz(explicit: Optional[float] = None) -> float:
    """Effective sampling rate in Hz: explicit argument (ClusterConfig)
    wins, else the CCTPU_PROFILE_HZ environment knob, else 0.0 (off)."""
    if explicit is not None:
        try:
            return max(0.0, float(explicit))
        except (TypeError, ValueError):
            return 0.0
    raw = os.environ.get("CCTPU_PROFILE_HZ", "").strip().lower()
    if raw in ("", "0", "off", "none", "no", "false"):
        return 0.0
    try:
        return max(0.0, float(raw))
    except ValueError:
        return 0.0


def _resolve_max_nodes(explicit: Optional[int] = None) -> int:
    if explicit is not None:
        return max(16, int(explicit))
    raw = os.environ.get("CCTPU_PROFILE_MAX_NODES", "").strip()
    try:
        return max(16, int(raw)) if raw else DEFAULT_MAX_NODES
    except ValueError:
        return DEFAULT_MAX_NODES


class SamplingProfiler:
    """Bounded folded-stack sampler over ``sys._current_frames()``.

    Lifecycle mirrors obs/resource.py's ResourceSampler: construct with an
    (optional) explicit rate, ``attach`` a tracer for span tagging,
    ``start``/``stop`` the daemon thread; every step is a no-op when the
    resolved rate is 0 so call sites never need to branch on the knob.
    """

    def __init__(self, hz: Optional[float] = None,
                 max_nodes: Optional[int] = None) -> None:
        self._hz = resolve_profile_hz(hz)
        self._max_nodes = _resolve_max_nodes(max_nodes)
        self._stacks: Dict[Tuple[str, ...], int] = {}
        self._samples = 0
        self._dropped = 0
        self._lock = threading.Lock()
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # {thread_ident: open-span path} — shared with attached tracers,
        # written by their span() push/pop, read at sample time
        self.span_paths: Dict[int, str] = {}
        self._tracers: List[object] = []

    # -- lifecycle -----------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._hz > 0

    @property
    def hz(self) -> float:
        return self._hz

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def attach(self, tracer) -> object:
        """Publish ``tracer``'s open-span paths into this profiler
        (idempotent, no-op when disabled). Returns the tracer."""
        if tracer is None or not self.enabled:
            return tracer
        if getattr(tracer, "profiler", None) is self:
            return tracer
        tracer.profiler = self
        publish = getattr(tracer, "publish_span_paths", None)
        if publish is not None:
            publish(self.span_paths)
            self._tracers.append(tracer)
        return tracer

    def start(self) -> "SamplingProfiler":
        if not self.enabled or self.running:
            return self
        self._stop_event.clear()
        self._thread = threading.Thread(
            target=self._loop, name="cctpu-profiler", daemon=True
        )
        self._thread.start()
        with _active_lock:
            if self not in _ACTIVE:
                _ACTIVE.append(self)
        return self

    def stop(self) -> None:
        """Stop sampling, join the thread, detach span publishing. The
        folded stacks survive — ``summary()`` stays valid after stop."""
        thread, self._thread = self._thread, None
        if thread is not None:
            self._stop_event.set()
            thread.join(timeout=5)
        with _active_lock:
            if self in _ACTIVE:
                _ACTIVE.remove(self)
        for tracer in self._tracers:
            publish = getattr(tracer, "publish_span_paths", None)
            if publish is not None:
                publish(None)
        self._tracers = []

    def _loop(self) -> None:
        interval = 1.0 / self._hz
        me = threading.get_ident()
        while not self._stop_event.wait(interval):
            try:
                self.sample_now(skip=me)
            except Exception:
                pass  # observability must never fail the profiled work

    # -- sampling ------------------------------------------------------------

    def sample_now(self, skip: Optional[int] = None) -> None:
        """Take one sample of every live thread (minus ``skip``, normally
        the profiler thread itself). Public so tests and one-shot callers
        can sample deterministically without the daemon thread."""
        frames = sys._current_frames()
        span_paths = self.span_paths
        with self._lock:
            self._samples += 1
            for ident, frame in frames.items():
                if ident == skip:
                    continue
                stack = _fold_stack(frame)
                tag = span_paths.get(ident)
                if tag:
                    stack = tuple(
                        f"span:{part}" for part in tag.split("/")
                    ) + stack
                if stack in self._stacks:
                    self._stacks[stack] += 1
                elif len(self._stacks) < self._max_nodes:
                    self._stacks[stack] = 1
                else:
                    self._dropped += 1

    # -- output --------------------------------------------------------------

    def summary(self, top: Optional[int] = None) -> dict:
        """The RunRecord ``profile`` block: folded stacks ranked by weight
        (root-first frame lists), plus the sampling bookkeeping a reader
        needs to judge coverage (samples taken, stacks dropped at the
        node cap)."""
        with self._lock:
            items = sorted(
                self._stacks.items(), key=lambda kv: (-kv[1], kv[0])
            )
            n_unique = len(items)
            if top is not None:
                items = items[:top]
            return {
                "hz": self._hz,
                "samples": self._samples,
                "unique_stacks": n_unique,
                "dropped": self._dropped,
                "max_nodes": self._max_nodes,
                "stacks": [
                    {"frames": list(frames), "weight": weight}
                    for frames, weight in items
                ],
            }


def _fold_stack(frame) -> Tuple[str, ...]:
    """Collapse one frame chain into root-first ``file.py:function`` parts,
    leaf-most _FRAME_DEPTH_CAP frames kept."""
    parts: List[str] = []
    f = frame
    while f is not None and len(parts) < _FRAME_DEPTH_CAP:
        code = f.f_code
        parts.append(f"{os.path.basename(code.co_filename)}:{code.co_name}")
        f = f.f_back
    parts.reverse()
    return tuple(parts)


def active_profiles(top: int = 50) -> List[dict]:
    """Summaries of every armed profiler — what the flight recorder rides
    into postmortem.json so a stall dump shows the hot stacks."""
    with _active_lock:
        profs = list(_ACTIVE)
    return [p.summary(top=top) for p in profs]


def start_profiler_for(tracer, hz: Optional[float] = None
                       ) -> Optional[SamplingProfiler]:
    """Arm a profiler for ``tracer`` when the resolved rate is non-zero;
    returns the running profiler, or None when profiling is off (the
    caller's stop path can just ``if prof: prof.stop()``)."""
    prof = SamplingProfiler(hz=hz)
    if not prof.enabled:
        return None
    prof.attach(tracer)
    prof.start()
    return prof


@contextmanager
def profiling(tracer=None, hz: Optional[float] = None):
    """Context-managed arm/stop around a block (tests, ad-hoc scripts)."""
    prof = start_profiler_for(tracer, hz=hz)
    try:
        yield prof
    finally:
        if prof is not None:
            prof.stop()
