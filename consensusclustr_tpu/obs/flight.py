"""Black-box flight recorder + stall watchdog (ISSUE 14 tentpole).

Every obs layer before this one explains a run *after* it finishes — run
records, Perfetto traces, the work ledger. Nothing captured state at the
moment a process died (a SIGTERM'd host, a worker past its restart limit),
and nothing could see a live wedge: the serving tunnel kills calls that
stall past ~2 min (consensus/pipeline.py) and the process never learns why.
Two pieces close that gap:

  * :class:`FlightRecorder` — bounded ring buffers of recent closed spans,
    events, per-root-phase metric deltas and the last-N log lines, fed by
    the same tracer hooks the ledger/sampler use. **Always on** (the one
    obs layer that is, docs/quirks.md: it only ever *writes* on failure —
    the steady-state cost is a few deque appends per span/event). On
    unhandled exception (``sys.excepthook`` chain), fatal signal
    (SIGTERM/SIGINT handler chain), serving give-up
    (``AssignmentService._fail_all``), retry exhaustion
    (resilience/retry.py) or a watchdog stall it dumps everything — plus
    all-thread stack traces and a live merged metrics snapshot — as one
    schema-versioned ``postmortem.json`` (rendered/diffed by
    tools/postmortem.py, path recorded in ``RunRecord.postmortem_path``).
    ``CCTPU_NO_FLIGHT=1`` is the kill switch for the whole layer.

  * :class:`StallWatchdog` — one lazy daemon thread arming per-phase /
    per-chunk / per-batch deadlines (derived from the ``phase_seconds`` /
    ``boot_chunk_seconds`` / ``serve_latency_seconds`` histograms via
    ``p99 x CCTPU_STALL_FACTOR``, floored by ``CCTPU_STALL_FLOOR_S`` /
    ``ClusterConfig.stall_floor_s`` and the per-site floors the call sites
    pass). Expiry emits a ``stall_detected`` event + ``stalls_detected``
    counter, dumps a ``stall`` post-mortem (with the wedged thread's stack
    in it), and runs an optional ``escalate`` callback so a caller can hand
    the wedge to the PR 10 supervision path. Detection only: the watchdog
    never interrupts the watched work.

Dump paths resolve ``CCTPU_POSTMORTEM_PATH`` (exact file) >
``CCTPU_POSTMORTEM_DIR`` (one numbered file per dump) > a per-pid file in
the system temp dir — the default never litters a working directory, and
the chosen path always lands in the ``postmortem_dump`` event and
``RunRecord.postmortem_path``. Everything here is exception-swallowed:
observability must never fail the traced work, least of all while it is
already failing.
"""

from __future__ import annotations

import collections
import contextlib
import json
import logging
import os
import signal
import sys
import tempfile
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional

from consensusclustr_tpu.obs.metrics import (
    Histogram,
    MetricsRegistry,
    global_metrics,
)
from consensusclustr_tpu.obs.schema import SCHEMA_VERSION
from consensusclustr_tpu.obs.tracer import Tracer, tracer_of

# Dump-reason vocabulary. Each ``*_FLIGHT`` literal is validated against
# obs.schema.FLIGHT_EVENT_KINDS by tools/check_obs_schema.py, both
# directions — a renamed reason is a test failure, not a dump
# tools/postmortem.py can't classify.
EXCEPTION_FLIGHT = "exception"
SIGNAL_FLIGHT = "signal"
FAIL_ALL_FLIGHT = "fail_all"
RETRIES_FLIGHT = "retries_exhausted"
STALL_FLIGHT = "stall"
MANUAL_FLIGHT = "manual"

# Version of the dump layout itself (inside the obs SCHEMA_VERSION stamp):
# bump when the postmortem.json key set changes shape.
# v2 (ISSUE 16): optional ``profile`` key — when a sampling profiler
# (obs/profiler.py) is armed at dump time, its folded hot-stack summary
# rides the dump so a stall post-mortem shows where the process was
# actually spinning, not just where each thread stood at death.
FLIGHT_DUMP_VERSION = 2

# Ring capacities: recent-history tails, not archives — the RunRecord keeps
# the full streams. ~256 events/spans is minutes of pipeline history and
# every event of a failing batch; 64 metric deltas covers any realistic
# phase count; 100 log lines matches a terminal scrollback.
DEFAULT_RING_CAPACITY = 256
DEFAULT_SNAPSHOT_CAPACITY = 64
DEFAULT_LOG_LINES = 100

DEFAULT_STALL_FLOOR_S = 120.0   # the serving tunnel kills at ~2 min
DEFAULT_STALL_FACTOR = 8.0      # deadline = max(floor, p99 * factor)
_MIN_HIST_COUNT = 8             # observations before p99 is trusted
_STACK_FRAME_CAP = 50           # per-thread frames serialized in a dump

_LOG_RING_MARK = "_cctpu_flight_ring"


def flight_enabled() -> bool:
    """The layer's kill switch: on unless ``CCTPU_NO_FLIGHT`` is set (the
    recorder only writes on failure, so on-by-default costs ring appends)."""
    return not os.environ.get("CCTPU_NO_FLIGHT", "").strip()


def resolve_postmortem_path(seq: int = 0) -> str:
    """Where the next dump goes: ``CCTPU_POSTMORTEM_PATH`` (exact file,
    overwritten — last dump wins) > ``CCTPU_POSTMORTEM_DIR`` (numbered per
    dump) > one per-pid file in the temp dir (overwritten)."""
    path = os.environ.get("CCTPU_POSTMORTEM_PATH", "").strip()
    if path:
        return path
    d = os.environ.get("CCTPU_POSTMORTEM_DIR", "").strip()
    if d:
        return os.path.join(d, f"postmortem-{os.getpid()}-{seq}.json")
    return os.path.join(
        tempfile.gettempdir(), f"cctpu-postmortem-{os.getpid()}.json"
    )


def thread_stacks() -> Dict[str, List[str]]:
    """All live threads' current stacks, formatted. The core of every dump:
    at SIGTERM/stall time this is the only record of *where* each thread
    was (frames capped so a deep recursion can't bloat the dump)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    for ident, frame in sys._current_frames().items():
        lines = traceback.format_stack(frame)[-_STACK_FRAME_CAP:]
        out[f"{names.get(ident, '?')}:{ident}"] = [
            ln.rstrip("\n") for ln in lines
        ]
    return out


class _RingHandler(logging.Handler):
    """logging.Handler feeding the recorder's last-N-log-lines ring."""

    def __init__(self, ring: "collections.deque") -> None:
        super().__init__()
        self._ring = ring
        self.setFormatter(
            logging.Formatter("%(levelname)s %(name)s: %(message)s")
        )
        setattr(self, _LOG_RING_MARK, True)

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self._ring.append(self.format(record))
        except Exception:
            pass


class FlightRecorder:
    """Bounded rings of recent observability state + the dump path.

    Feeding is push-based: :func:`attach_flight` wires a tracer's event
    stream and span-close hook into the rings (and pushes a per-counter
    delta snapshot at every root-span close), and the constructor hangs a
    ring handler off the package logger. All rings are ``deque(maxlen=...)``
    — steady-state cost is appends, memory is bounded forever.
    """

    def __init__(
        self,
        capacity: int = DEFAULT_RING_CAPACITY,
        snapshot_capacity: int = DEFAULT_SNAPSHOT_CAPACITY,
        log_lines: int = DEFAULT_LOG_LINES,
        attach_log_handler: bool = True,
    ) -> None:
        self.events: "collections.deque" = collections.deque(maxlen=capacity)
        self.spans: "collections.deque" = collections.deque(maxlen=capacity)
        self.snapshots: "collections.deque" = collections.deque(
            maxlen=snapshot_capacity
        )
        self.log_lines: "collections.deque" = collections.deque(
            maxlen=log_lines
        )
        self.epoch = time.monotonic()
        self.last_dump_path: Optional[str] = None
        self.last_dump_reason: Optional[str] = None
        self.dumps = 0
        self._tracers: List[Tracer] = []
        self._last_counters: Dict[str, float] = {}
        self._dump_lock = threading.Lock()
        if attach_log_handler:
            try:
                from consensusclustr_tpu.utils.log import get_logger

                logger = get_logger()
                if not any(
                    getattr(h, _LOG_RING_MARK, False) for h in logger.handlers
                ):
                    logger.addHandler(_RingHandler(self.log_lines))
            except Exception:
                pass

    # -- feeding -------------------------------------------------------------

    def note_event(self, rec: dict) -> None:
        self.events.append(rec)

    def note_span(self, span: Any) -> None:
        rec = {
            "name": getattr(span, "name", "?"),
            "t0": getattr(span, "t0", None),
            "seconds": getattr(span, "seconds", None),
        }
        if not getattr(span, "ok", True):
            rec["ok"] = False
            rec["error"] = getattr(span, "error", None)
        self.spans.append(rec)

    def _counter_totals(self) -> Dict[str, float]:
        vals: Dict[str, float] = {}
        for reg in self._registries():
            for name, c in list(reg.counters.items()):
                vals[name] = vals.get(name, 0.0) + c.value
        return vals

    def note_phase_delta(self, phase: str) -> None:
        """Push one metric-delta snapshot (counter movement since the last
        push, attributed to ``phase``) — called at root-span close."""
        now = self._counter_totals()
        delta = {
            k: v - self._last_counters.get(k, 0.0)
            for k, v in now.items()
            if v != self._last_counters.get(k, 0.0)
        }
        self._last_counters = now
        self.snapshots.append({
            "t": round(time.monotonic() - self.epoch, 4),
            "phase": phase,
            "counters": delta,
        })

    def track(self, tracer: Tracer) -> None:
        """Merge ``tracer``'s registry into every future dump's metrics
        snapshot (attach_flight calls this; idempotent)."""
        if tracer is not None and not any(
            tracer is t for t in self._tracers
        ):
            self._tracers.append(tracer)

    def _registries(self) -> List[MetricsRegistry]:
        return [global_metrics()] + [t.metrics for t in self._tracers]

    # -- dumping -------------------------------------------------------------

    def dump(
        self,
        reason: str,
        detail: Optional[dict] = None,
        path: Optional[str] = None,
    ) -> Optional[str]:
        """Write the black box: rings + all-thread stacks + a live merged
        metrics snapshot, atomically (tmp + replace), as one JSON object.
        Returns the path, or None on any failure — a dying process must
        never die harder because its post-mortem couldn't be written."""
        try:
            with self._dump_lock:
                path = path or resolve_postmortem_path(self.dumps)
                reg = MetricsRegistry()
                for r in self._registries():
                    reg.merge(r)
                payload = {
                    "schema": SCHEMA_VERSION,
                    "flight_dump_version": FLIGHT_DUMP_VERSION,
                    "reason": reason,
                    "detail": dict(detail or {}),
                    "pid": os.getpid(),
                    "time_unix": time.time(),
                    "uptime_s": round(time.monotonic() - self.epoch, 4),
                    "dump_seq": self.dumps,
                    "threads": thread_stacks(),
                    "events": list(self.events),
                    "spans": list(self.spans),
                    "metric_deltas": list(self.snapshots),
                    "log_lines": list(self.log_lines),
                    "metrics": reg.snapshot(),
                }
                # armed sampling profilers ride the dump (dump layout v2);
                # lazy + guarded — a dying process must not die harder
                # because the profiler layer misbehaved
                try:
                    from consensusclustr_tpu.obs.profiler import (
                        active_profiles,
                    )

                    profs = active_profiles(top=50)
                    if profs:
                        payload["profile"] = profs[0]
                        if len(profs) > 1:
                            payload["profile"]["extra_profilers"] = (
                                len(profs) - 1
                            )
                except Exception:
                    pass
                d = os.path.dirname(path)
                if d:
                    os.makedirs(d, exist_ok=True)
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "w") as f:
                    json.dump(payload, f, default=str)
                os.replace(tmp, path)
                self.last_dump_path = path
                self.last_dump_reason = reason
                self.dumps += 1
            global_metrics().counter("postmortem_dumps").inc()
            for tr in self._tracers:
                try:
                    tr.event("postmortem_dump", reason=reason, path=path)
                except Exception:
                    pass
            try:
                from consensusclustr_tpu.utils.log import get_logger

                get_logger().warning(
                    "flight recorder: %s post-mortem written to %s",
                    reason, path,
                )
            except Exception:
                pass
            return path
        except Exception:
            return None


_RECORDER: Optional[FlightRecorder] = None
_RECORDER_LOCK = threading.Lock()
_HOOKS_INSTALLED = False
_PREV_EXCEPTHOOK: Optional[Callable] = None
_PREV_SIGNAL: Dict[int, Any] = {}


def global_flight() -> Optional[FlightRecorder]:
    """The process-wide recorder (created + crash-hooks installed on first
    use); None when ``CCTPU_NO_FLIGHT`` disarms the layer."""
    global _RECORDER
    if not flight_enabled():
        return None
    if _RECORDER is None:
        with _RECORDER_LOCK:
            if _RECORDER is None:
                _RECORDER = FlightRecorder()
                _install_crash_hooks(_RECORDER)
    return _RECORDER


def _install_crash_hooks(recorder: FlightRecorder) -> None:
    """Chain sys.excepthook and the SIGTERM/SIGINT handlers: dump first,
    then hand control to whatever was installed before us. Signal install
    is main-thread-only by CPython contract — elsewhere the excepthook and
    explicit dump triggers still cover the layer."""
    global _HOOKS_INSTALLED, _PREV_EXCEPTHOOK
    if _HOOKS_INSTALLED:
        return
    _HOOKS_INSTALLED = True

    _PREV_EXCEPTHOOK = sys.excepthook

    def _excepthook(tp, val, tb):
        recorder.dump(
            EXCEPTION_FLIGHT,
            {"error": tp.__name__, "message": str(val)[:500]},
        )
        if _PREV_EXCEPTHOOK is not None:
            _PREV_EXCEPTHOOK(tp, val, tb)

    sys.excepthook = _excepthook

    def _on_signal(signum, frame):
        try:
            name = signal.Signals(signum).name
        except Exception:
            name = str(signum)
        recorder.dump(SIGNAL_FLIGHT, {"signal": name})
        prev = _PREV_SIGNAL.get(signum)
        if callable(prev):
            prev(signum, frame)
        else:
            # default disposition: restore it and re-deliver, so the
            # process still dies with the signal's own exit status
            try:
                signal.signal(
                    signum, prev if prev is not None else signal.SIG_DFL
                )
                os.kill(os.getpid(), signum)
            except Exception:
                raise SystemExit(128 + signum)

    for signum in (signal.SIGTERM, signal.SIGINT):
        try:
            _PREV_SIGNAL[signum] = signal.signal(signum, _on_signal)
        except (ValueError, OSError):
            pass  # not the main thread / unsupported platform


def attach_flight(tracer: Optional[Tracer]) -> Optional[FlightRecorder]:
    """Wire ``tracer`` into the process recorder (idempotent): its events
    and closed spans feed the rings, every root-span close pushes a metric
    -delta snapshot, and its registry joins the dump-time snapshot merge.
    Exposes the recorder as ``tracer.flight`` (where
    ``RunRecord.from_tracer`` picks up ``postmortem_path``). None-safe and
    None when the layer is disarmed."""
    recorder = global_flight()
    if tracer is None or recorder is None:
        return recorder
    if getattr(tracer, "flight", None) is recorder:
        return recorder
    recorder.track(tracer)
    tracer.flight = recorder  # type: ignore[attr-defined]

    orig_event = tracer.event

    def _event(kind: str, **fields: Any) -> None:
        orig_event(kind, **fields)
        try:
            recorder.note_event({
                "t": round(time.monotonic() - tracer.epoch, 4),
                "kind": kind, **fields,
            })
        except Exception:
            pass

    tracer.event = _event  # type: ignore[method-assign]

    def _on_span_close(span: Any) -> None:
        try:
            recorder.note_span(span)
            if any(span is r for r in tracer.roots):
                recorder.note_phase_delta(span.name)
        except Exception:
            pass

    tracer.add_span_close_hook(_on_span_close)
    return recorder


def dump_on_failure(reason: str, log: Any = None, **detail: Any) -> Optional[str]:
    """Fire-and-forget dump trigger for failure paths (retry exhaustion,
    serving give-up): dumps iff the layer is armed, never raises. The
    tracer behind ``log`` (when given) is tracked first so its metrics land
    in the snapshot."""
    try:
        recorder = global_flight()
        if recorder is None:
            return None
        tr = tracer_of(log)
        if tr is not None:
            recorder.track(tr)
        return recorder.dump(reason, dict(detail))
    except Exception:
        return None


# -- stall watchdog ----------------------------------------------------------


def resolve_stall_floor_s(requested: Optional[float] = None) -> float:
    """Explicit arg / ClusterConfig.stall_floor_s > $CCTPU_STALL_FLOOR_S >
    120 s (the serving tunnel's own kill horizon)."""
    if requested is None:
        env = os.environ.get("CCTPU_STALL_FLOOR_S", "").strip()
        requested = float(env) if env else DEFAULT_STALL_FLOOR_S
    v = float(requested)
    if v <= 0:
        raise ValueError(f"stall floor must be > 0 seconds; got {v}")
    return v


def stall_deadline_s(
    hist: Optional[Histogram] = None,
    floor_s: Optional[float] = None,
    factor: Optional[float] = None,
) -> float:
    """A watch deadline: ``max(floor, p99(hist) * factor)``. The histogram
    term adapts to the workload once enough observations exist (a chunk
    that normally takes 70 s gets ~9 min, not the floor); the floor keeps
    cold starts from arming hair-trigger deadlines."""
    floor = resolve_stall_floor_s(floor_s)
    if factor is None:
        env = os.environ.get("CCTPU_STALL_FACTOR", "").strip()
        factor = float(env) if env else DEFAULT_STALL_FACTOR
    derived = 0.0
    if hist is not None and hist.count >= _MIN_HIST_COUNT:
        try:
            q = hist.quantile(0.99)
            if q is not None:
                derived = float(q) * float(factor)
        except Exception:
            derived = 0.0
    return max(floor, derived)


class _Watch:
    """One armed deadline; ``tick()`` re-arms it (per chunk / per batch)."""

    __slots__ = ("name", "deadline_s", "tracer", "escalate", "armed_at",
                 "fired", "closed")

    def __init__(self, name, deadline_s, tracer, escalate) -> None:
        self.name = name
        self.deadline_s = float(deadline_s)
        self.tracer = tracer
        self.escalate = escalate
        self.armed_at = time.monotonic()
        self.fired = False
        self.closed = False

    def tick(self) -> None:
        self.armed_at = time.monotonic()
        self.fired = False

    def close(self) -> None:
        self.closed = True


class _NullWatch:
    """Inert handle when the layer is disarmed — call sites stay branch-free."""

    def tick(self) -> None:
        pass

    def close(self) -> None:
        pass


class StallWatchdog:
    """One daemon thread over all armed watches: sleeps until the earliest
    deadline, fires each expiry exactly once per arm (a ``tick()`` re-arms).
    Detection only — the watched work is never interrupted; firing emits
    the ``stall_detected`` event + counter, writes a ``stall`` post-mortem
    (the wedged thread's stack is in the all-thread dump) and runs the
    watch's ``escalate`` callback, all exception-swallowed."""

    def __init__(self, recorder: Optional[FlightRecorder] = None) -> None:
        self._recorder = recorder
        self._watches: List[_Watch] = []
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def watch(
        self,
        name: str,
        deadline_s: float,
        tracer: Optional[Tracer] = None,
        escalate: Optional[Callable[[], None]] = None,
    ) -> _Watch:
        w = _Watch(name, deadline_s, tracer, escalate)
        with self._lock:
            self._watches.append(w)
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, name="cctpu-stall-watchdog", daemon=True
                )
                self._thread.start()
        self._wake.set()
        return w

    def _loop(self) -> None:
        while True:
            # clear FIRST: a watch() landing after the scan below re-wakes
            # the sleep instead of being lost to the clear
            self._wake.clear()
            now = time.monotonic()
            next_due: Optional[float] = None
            with self._lock:
                self._watches = [w for w in self._watches if not w.closed]
                due = [
                    w for w in self._watches
                    if not w.fired and now - w.armed_at >= w.deadline_s
                ]
                for w in self._watches:
                    if w.fired:
                        continue
                    t = w.armed_at + w.deadline_s
                    next_due = t if next_due is None else min(next_due, t)
            for w in due:
                w.fired = True
                self._fire(w, now - w.armed_at)
            if due:
                continue  # re-scan: firing took time, deadlines moved
            # no armed watch: park until the next watch()/tick() wakes us
            timeout = (
                None if next_due is None
                else max(0.01, next_due - time.monotonic())
            )
            self._wake.wait(timeout)

    def _fire(self, w: _Watch, waited_s: float) -> None:
        try:
            mets = w.tracer.metrics if w.tracer is not None else global_metrics()
            mets.counter("stalls_detected").inc()
            if w.tracer is not None:
                w.tracer.event(
                    "stall_detected", name=w.name,
                    deadline_s=round(w.deadline_s, 4),
                    waited_s=round(waited_s, 4),
                )
            recorder = self._recorder or global_flight()
            if recorder is not None:
                if w.tracer is not None:
                    recorder.track(w.tracer)
                recorder.dump(
                    STALL_FLIGHT,
                    {
                        "watch": w.name,
                        "deadline_s": round(w.deadline_s, 4),
                        "waited_s": round(waited_s, 4),
                    },
                )
            if w.escalate is not None:
                w.escalate()
        except Exception:
            pass  # the watchdog must never fail the watched work


_WATCHDOG: Optional[StallWatchdog] = None
_WATCHDOG_LOCK = threading.Lock()
_NULL_WATCH = _NullWatch()


def global_watchdog() -> StallWatchdog:
    global _WATCHDOG
    if _WATCHDOG is None:
        with _WATCHDOG_LOCK:
            if _WATCHDOG is None:
                _WATCHDOG = StallWatchdog()
    return _WATCHDOG


@contextlib.contextmanager
def stall_watch(
    log: Any = None,
    name: str = "work",
    deadline_s: Optional[float] = None,
    hist: Optional[Histogram] = None,
    floor_s: Optional[float] = None,
    factor: Optional[float] = None,
    escalate: Optional[Callable[[], None]] = None,
):
    """Arm a deadline around a block; yields a handle whose ``tick()``
    re-arms it (call once per chunk/batch inside a loop). Inert (yields a
    no-op handle) when ``CCTPU_NO_FLIGHT`` disarms the layer — the off path
    costs one env check."""
    if not flight_enabled():
        yield _NULL_WATCH
        return
    if deadline_s is None:
        deadline_s = stall_deadline_s(hist, floor_s, factor)
    w = global_watchdog().watch(
        name, deadline_s, tracer=tracer_of(log), escalate=escalate
    )
    try:
        yield w
    finally:
        w.close()
