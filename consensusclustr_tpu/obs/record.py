"""RunRecord: the serialized unit of observability.

One record = one top-level run (a ``consensus_clust`` call, a bench config, a
null-test campaign): schema version, config fingerprint, backend, the span
tree, the flat event stream, and a metrics snapshot. Serialized as one JSON
object per line (JSONL) so long-lived processes append records and
``tools/report.py`` renders any of them later.

Kept deliberately jax-free at import time: report tooling and post-hoc
analysis load records without touching a backend.

Schema v11 (ISSUE 19) added no RunRecord fields: fleet-wide tracing lives
in a NEW artifact kind (obs/fleetobs.py ``FleetRecord``) that embeds one
RunRecord per fleet lane *unchanged* — this module stays the single
serializer for both.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Dict, List, Optional

from consensusclustr_tpu.obs.metrics import MetricsRegistry
from consensusclustr_tpu.obs.schema import SCHEMA_VERSION
from consensusclustr_tpu.obs.tracer import Span, Tracer


def _jsonable(x: Any):
    """json.dumps default: numpy scalars/arrays -> python, else str."""
    try:
        import numpy as np

        if isinstance(x, (np.integer,)):
            return int(x)
        if isinstance(x, (np.floating,)):
            return float(x)
        if isinstance(x, np.ndarray):
            return x.tolist()
    except Exception:
        pass
    return str(x)


def config_fingerprint(cfg: Any) -> Optional[str]:
    """Short stable hash of a config's field values (dataclass, dict, or any
    attr-bearing object); arrays and exotic values hash via their str form."""
    if cfg is None:
        return None
    if dataclasses.is_dataclass(cfg) and not isinstance(cfg, type):
        d = {f.name: getattr(cfg, f.name) for f in dataclasses.fields(cfg)}
    elif isinstance(cfg, dict):
        d = cfg
    else:
        d = dict(vars(cfg))
    blob = json.dumps(d, sort_keys=True, default=_jsonable)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def _config_dict(cfg: Any) -> Optional[dict]:
    if cfg is None:
        return None
    if dataclasses.is_dataclass(cfg) and not isinstance(cfg, type):
        d = {f.name: getattr(cfg, f.name) for f in dataclasses.fields(cfg)}
    elif isinstance(cfg, dict):
        d = cfg
    else:
        d = dict(vars(cfg))
    # round-trip through JSON so the record is self-contained plain data
    return json.loads(json.dumps(d, default=_jsonable))


@dataclasses.dataclass
class RunRecord:
    """Schema-versioned snapshot of one run's observability state."""

    schema: int = SCHEMA_VERSION
    backend: Optional[str] = None
    config_fingerprint: Optional[str] = None
    wall_s: Optional[float] = None
    spans: List[Span] = dataclasses.field(default_factory=list)
    events: List[dict] = dataclasses.field(default_factory=list)
    metrics: dict = dataclasses.field(default_factory=dict)
    config: Optional[dict] = None
    # schema v4: ResourceSampler series (obs/resource.py series_dict) —
    # sample_ms, n_samples, rss/device peak watermarks, [t, rss, dev] rows.
    # None on older records and on runs with sampling off (the default).
    resource: Optional[dict] = None
    # schema v6: numerics block (obs/fingerprint.py NumericsMonitor summary)
    # — level, non-finite total, and the ordered checkpoint fingerprint
    # stream tools/parity_audit.py diffs across regimes. None on older
    # records and on runs with numerics off (the default).
    numerics: Optional[dict] = None
    # schema v7: deterministic work ledger (obs/ledger.py WorkLedger
    # summary) — total WORK_LEDGER_COUNTERS deltas since attach plus the
    # per-top-level-phase attribution. None only on older records; current
    # runs attach the ledger unconditionally (it is one dict subtraction
    # per root span).
    work_ledger: Optional[dict] = None
    # schema v8: path of the flight-recorder post-mortem dump, if one was
    # written during this run (obs/flight.py). None on clean runs — the
    # recorder only ever writes on failure — and on older records.
    postmortem_path: Optional[str] = None
    # schema v8: SLO alert engine summary (obs/alerts.py AlertEngine
    # summary) — active alerts at record time, raise/clear totals, and the
    # last alert raised. None on older records and tracer-less runs.
    alerts: Optional[dict] = None
    # schema v9: per-program cost attribution (utils/compile_cache.py
    # program_profile) — ranked per-counting_jit-program rows whose
    # est_flops/est_bytes sum to the global estimated_* counters. None on
    # older records and runs that dispatched no counted program.
    program_profile: Optional[dict] = None
    # schema v9: sampling-profiler summary (obs/profiler.py
    # SamplingProfiler.summary) — span-tagged folded hot stacks. None on
    # older records and whenever CCTPU_PROFILE_HZ/profile_hz is off (the
    # default: profiling is opt-in, attribution above is always-on).
    profile: Optional[dict] = None

    @classmethod
    def from_tracer(
        cls,
        tracer: Tracer,
        config: Any = None,
        backend: Optional[str] = None,
        include_global_metrics: bool = True,
    ) -> "RunRecord":
        reg = MetricsRegistry()
        if include_global_metrics:
            from consensusclustr_tpu.obs.metrics import global_metrics

            # Re-sample the compile_cache_entries gauge so the record shows
            # the POST-run cache state, not the stale enable-time count
            # (ISSUE 13 satellite). Lazy + guarded: this module stays
            # importable without jax, and observability never fails a run.
            try:
                from consensusclustr_tpu.utils.compile_cache import (
                    refresh_cache_entries_gauge,
                )

                refresh_cache_entries_gauge()
            except Exception:
                pass
            reg.merge(global_metrics())
        reg.merge(tracer.metrics)
        sampler = getattr(tracer, "resource_sampler", None)
        resource = None
        if sampler is not None and getattr(sampler, "samples", None):
            try:
                resource = sampler.series_dict()
            except Exception:
                resource = None
        monitor = getattr(tracer, "numerics", None)
        numerics = None
        if monitor is not None:
            try:
                numerics = monitor.summary()
            except Exception:
                numerics = None
        ledger = getattr(tracer, "work_ledger", None)
        work_ledger = None
        if ledger is not None:
            try:
                work_ledger = ledger.summary()
            except Exception:
                work_ledger = None
        flight = getattr(tracer, "flight", None)
        postmortem_path = None
        if flight is not None:
            postmortem_path = getattr(flight, "last_dump_path", None)
        engine = getattr(tracer, "alert_engine", None)
        alerts = None
        if engine is not None:
            try:
                alerts = engine.summary()
            except Exception:
                alerts = None
        # per-program attribution is process-global (like the metrics
        # registry merged above); lazy + guarded so this module stays
        # importable without jax
        program_profile = None
        try:
            from consensusclustr_tpu.utils.compile_cache import (
                program_profile as _program_profile,
            )

            block = _program_profile()
            if block.get("n_programs"):
                program_profile = block
        except Exception:
            program_profile = None
        profiler = getattr(tracer, "profiler", None)
        profile = None
        if profiler is not None:
            try:
                profile = profiler.summary(top=200)
            except Exception:
                profile = None
        return cls(
            schema=SCHEMA_VERSION,
            backend=backend,
            config_fingerprint=config_fingerprint(config),
            wall_s=tracer.elapsed(),
            spans=list(tracer.roots),
            events=list(tracer.events),
            metrics=reg.snapshot(),
            config=_config_dict(config),
            resource=resource,
            numerics=numerics,
            work_ledger=work_ledger,
            postmortem_path=postmortem_path,
            alerts=alerts,
            program_profile=program_profile,
            profile=profile,
        )

    def phase_seconds(self) -> Dict[str, float]:
        """Top-level phase breakdown (root-span seconds summed by name)."""
        out: Dict[str, float] = {}
        for sp in self.spans:
            if sp.seconds is not None:
                out[sp.name] = round(out.get(sp.name, 0.0) + sp.seconds, 4)
        return out

    def to_dict(self) -> dict:
        d = {
            "schema": self.schema,
            "backend": self.backend,
            "config_fingerprint": self.config_fingerprint,
            "wall_s": self.wall_s,
            "phases": self.phase_seconds(),
            "spans": [s.to_dict() for s in self.spans],
            "events": self.events,
            "metrics": self.metrics,
            "config": self.config,
        }
        if self.resource is not None:
            d["resource"] = self.resource
        if self.numerics is not None:
            d["numerics"] = self.numerics
        if self.work_ledger is not None:
            d["work_ledger"] = self.work_ledger
        if self.postmortem_path is not None:
            d["postmortem_path"] = self.postmortem_path
        if self.alerts is not None:
            d["alerts"] = self.alerts
        if self.program_profile is not None:
            d["program_profile"] = self.program_profile
        if self.profile is not None:
            d["profile"] = self.profile
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), default=_jsonable)

    def write(self, path: str) -> None:
        """Append this record as one JSONL line."""
        with open(path, "a") as f:
            f.write(self.to_json() + "\n")

    def to_chrome_trace(self, path: str) -> str:
        """Export the span tree + event stream as Chrome/Perfetto trace-event
        JSON (obs/export.py); the written file loads in ui.perfetto.dev.
        Returns ``path``."""
        from consensusclustr_tpu.obs.export import write_chrome_trace

        return write_chrome_trace(
            path,
            [s.to_dict() for s in self.spans],
            self.events,
            metadata={
                "schema": self.schema,
                "backend": self.backend,
                "config_fingerprint": self.config_fingerprint,
                "wall_s": self.wall_s,
            },
            resource=self.resource,
            numerics=self.numerics,
        )

    @classmethod
    def from_dict(cls, d: dict) -> "RunRecord":
        return cls(
            schema=int(d.get("schema", 0)),
            backend=d.get("backend"),
            config_fingerprint=d.get("config_fingerprint"),
            wall_s=d.get("wall_s"),
            spans=[Span.from_dict(s) for s in d.get("spans", [])],
            events=list(d.get("events", [])),
            metrics=dict(d.get("metrics", {})),
            config=d.get("config"),
            resource=d.get("resource"),
            numerics=d.get("numerics"),
            work_ledger=d.get("work_ledger"),
            postmortem_path=d.get("postmortem_path"),
            alerts=d.get("alerts"),
            program_profile=d.get("program_profile"),
            profile=d.get("profile"),
        )


def load_records(path: str) -> List[RunRecord]:
    """All RunRecords in a JSONL (or single-object JSON) file."""
    out: List[RunRecord] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(RunRecord.from_dict(json.loads(line)))
    return out
