"""Device-side numeric fingerprints: the values axis of observability.

PRs 1/4/6/7 instrumented time, memory and request lifecycle; this module
(ISSUE 8 tentpole) observes the *numbers*. The repo runs the same math under
several compute regimes (dense vs Pallas co-clustering, fused vs looped
grid, any pipeline depth, x64 vs x32 hosts) whose agreement was pinned only
in unit tests — at runtime nothing watched the values, so a silent
divergence on a real workload stayed invisible until labels were wrong.

A fingerprint is a few scalars per call, computed ON DEVICE (jittable, no
host copy of the array):

  * an order-independent 64-bit checksum of the array's bit pattern —
    elements are canonicalized to 32-bit lanes, bitcast to uint32, and
    reduced through two independent wrapping-sum lanes (sum is commutative,
    so any chunking/streaming of the same elements checksums identically);
  * shape, dtype, min, max, mean, NaN count, Inf count.

Checkpoints are stamped at the named pipeline stages registered in
``obs/schema.py::NUMERIC_CHECKPOINTS`` under an opt-in level
(``CCTPU_NUMERICS`` env / ``ClusterConfig.numerics``):

  * ``off``   (default) — ``numeric_checkpoint`` returns before touching the
    array (callable payloads are never invoked): zero device dispatches,
    zero host work.
  * ``watch`` — NaN/Inf watchdog only: one small reduction per float array;
    non-finite values increment the ``numerics_nonfinite`` counter, tag the
    open span and emit a ``numerics_nonfinite`` event.
  * ``audit`` — full fingerprints: recorded in the tracer-attached
    ``NumericsMonitor`` (the RunRecord ``numerics`` block, schema v6),
    emitted as ``numeric_fingerprint`` instant events, and stamped on the
    enclosing span's ``fingerprints`` attr. ``tools/parity_audit.py`` diffs
    two regimes' checkpoint streams and names the first divergence.

``CCTPU_NUMERICS_INJECT=bf16:<checkpoint>`` (or ``attach_numerics(...,
inject=...)``) deliberately downgrades float arrays through bfloat16 at ONE
named checkpoint before fingerprinting — the self-test proving the parity
auditor catches a precision downgrade where it was planted.

Import-light like its obs/ siblings: jax loads lazily inside the functions,
so report tooling importing the package stays backend-free.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Tuple

from consensusclustr_tpu.obs.tracer import Tracer, metrics_of, tracer_of

# Checkpoint-name constants (tools/check_obs_schema.py validates every
# ``*_CKPT`` literal here against obs.schema.NUMERIC_CHECKPOINTS, both
# directions — call sites import these, so a rename cannot silently orphan a
# checkpoint).
NORM_CKPT = "norm"                      # post-normalization matrix
HVG_CKPT = "hvg"                        # HVG-subset matrix feeding PCA
PCA_CKPT = "pca"                        # PCA embedding
BOOT_LABELS_CKPT = "boot_labels"        # per-chunk aligned boot labels
COCLUSTER_CKPT = "cocluster"            # streamed co-cluster count carries
CONSENSUS_DIST_CKPT = "consensus_dist"  # consensus distance / kNN graph
LABELS_CKPT = "labels"                  # final labels

# Span-attr constants (validated against obs.schema.NUMERIC_SPAN_ATTRS).
FINGERPRINT_ATTR = "fingerprints"
NONFINITE_ATTR = "numerics_nonfinite"

NUMERICS_LEVELS = ("off", "watch", "audit")

# Audit checkpoint records kept per monitor: a long-lived process (serving,
# huge boot counts) must not grow the RunRecord unboundedly — the counters
# keep counting past the cap, only the per-checkpoint detail stops.
NUMERICS_RECORD_CAP = 100_000

_GOLDEN = 0x9E3779B9       # second-lane whitener (golden-ratio constant)
_MIX_MULT = 2654435761     # Knuth multiplicative-hash constant (mod 2^32)


def resolve_numerics(value: Optional[str] = None) -> str:
    """Resolve the numerics level: explicit ``value`` (ClusterConfig field)
    beats the ``CCTPU_NUMERICS`` env var beats ``off``. Falsy spellings
    ("", "0", "none", "false") mean off; anything else unknown raises."""
    v = value if value is not None else os.environ.get("CCTPU_NUMERICS", "")
    v = str(v).strip().lower()
    if v in ("", "0", "none", "false"):
        return "off"
    if v not in NUMERICS_LEVELS:
        raise ValueError(
            f"numerics level must be one of {NUMERICS_LEVELS}; got {v!r}"
        )
    return v


def parse_inject(spec: Optional[str]) -> Optional[Tuple[str, str]]:
    """Parse an injection spec "bf16:<checkpoint>" -> (mode, checkpoint);
    None/"" -> None. Unknown modes or checkpoints raise loudly — a typo'd
    injection would otherwise "prove" the auditor by never firing."""
    if not spec:
        return None
    mode, sep, name = str(spec).partition(":")
    mode = mode.strip().lower()
    name = name.strip()
    if not sep or mode != "bf16":
        raise ValueError(
            f"inject spec must be 'bf16:<checkpoint>'; got {spec!r}"
        )
    from consensusclustr_tpu.obs.schema import NUMERIC_CHECKPOINTS

    if name not in NUMERIC_CHECKPOINTS:
        raise ValueError(
            f"inject names unknown checkpoint {name!r} "
            f"(known: {', '.join(sorted(NUMERIC_CHECKPOINTS))})"
        )
    return mode, name


# -- the jittable fingerprint -------------------------------------------------


def _words_u32(x):
    """uint32 word view of ``x``'s values. 4-byte dtypes bitcast directly;
    everything else canonicalizes to a 32-bit lane first (floats -> float32,
    ints/bools -> int32) so the checksum is well-defined on any input — a
    *dtype* difference between regimes still surfaces through the recorded
    ``dtype`` field even when the canonicalized bits agree."""
    import jax
    import jax.numpy as jnp

    if x.dtype.itemsize == 4:
        pass
    elif jnp.issubdtype(x.dtype, jnp.floating):
        x = x.astype(jnp.float32)
    else:
        x = x.astype(jnp.int32)
    return jax.lax.bitcast_convert_type(x, jnp.uint32).reshape(-1)


def fingerprint_scalars(x) -> Dict[str, Any]:
    """Device-side fingerprint scalars of one array — jittable (traceable
    inside an enclosing jit; dtype branching is static). Returns a dict of
    0-d arrays: ``s1``/``s2`` (uint32 checksum lanes), ``min``/``max``/
    ``mean`` (float32 view), ``nan``/``inf`` (int32 counts; 0 for exact
    dtypes). The two checksum lanes are independent commutative reductions,
    so the combined 64-bit checksum is invariant under any element order or
    chunking of the same multiset of values."""
    import jax.numpy as jnp

    x = jnp.asarray(x)
    w = _words_u32(x)
    s1 = jnp.sum(w, dtype=jnp.uint32)
    s2 = jnp.sum(
        (w ^ jnp.uint32(_GOLDEN)) * jnp.uint32(_MIX_MULT), dtype=jnp.uint32
    )
    xf = x.astype(jnp.float32)
    out = {
        "s1": s1,
        "s2": s2,
        "min": jnp.min(xf),
        "max": jnp.max(xf),
        "mean": jnp.mean(xf),
    }
    if jnp.issubdtype(x.dtype, jnp.inexact):
        out["nan"] = jnp.sum(jnp.isnan(xf), dtype=jnp.int32)
        out["inf"] = jnp.sum(jnp.isinf(xf), dtype=jnp.int32)
    else:
        zero = jnp.int32(0)
        out["nan"] = zero
        out["inf"] = zero
    return out


_FP_JIT = None


def _fp_jit():
    """The jitted fingerprint entry (deliberately plain ``jax.jit``, not
    counting_jit: fingerprints must not perturb the PR 5 dispatch counters
    they exist to audit alongside)."""
    global _FP_JIT
    if _FP_JIT is None:
        import jax

        _FP_JIT = jax.jit(fingerprint_scalars)  # graftlint: noqa[GL004] fingerprint hashing deliberately runs outside the work ledger (obs must not perturb what it measures)
    return _FP_JIT


def _nonfinite_jit():
    global _NF_JIT
    if _NF_JIT is None:
        import jax
        import jax.numpy as jnp

        def nf(x):
            return jnp.sum(~jnp.isfinite(x), dtype=jnp.int32)

        _NF_JIT = jax.jit(nf)  # graftlint: noqa[GL004] fingerprint hashing deliberately runs outside the work ledger (obs must not perturb what it measures)
    return _NF_JIT


_NF_JIT = None


def array_fingerprint(x, jit: bool = True) -> Dict[str, Any]:
    """Host-side fingerprint dict of one array: ``checksum`` (16-hex-digit,
    64-bit), ``shape``, ``dtype``, ``min``/``max``/``mean``, ``nan_count``/
    ``inf_count``. Only the scalar results cross to host. ``jit=False`` runs
    the same trace eagerly (pinned identical in tests/test_numerics.py)."""
    import jax.numpy as jnp

    x = jnp.asarray(x)
    if x.size == 0:
        return {
            "checksum": f"{0:016x}", "shape": list(x.shape),
            "dtype": str(x.dtype), "min": None, "max": None, "mean": None,
            "nan_count": 0, "inf_count": 0,
        }
    vals = (_fp_jit() if jit else fingerprint_scalars)(x)
    s1, s2 = int(vals["s1"]), int(vals["s2"])

    def _finite(v):
        # NaN/Inf stats serialize as None (strict-JSON hostile otherwise);
        # the nan_count/inf_count fields carry the signal
        import math

        f = float(v)
        return f if math.isfinite(f) else None

    return {
        "checksum": f"{(s1 << 32) | s2:016x}",
        "shape": list(x.shape),
        "dtype": str(x.dtype),
        "min": _finite(vals["min"]),
        "max": _finite(vals["max"]),
        "mean": _finite(vals["mean"]),
        "nan_count": int(vals["nan"]),
        "inf_count": int(vals["inf"]),
    }


def merge_fingerprints(parts: List[Dict[str, Any]]) -> Dict[str, Any]:
    """One fingerprint for a multi-array checkpoint (e.g. the agree+union
    co-cluster carries): checksums XOR (still order-independent), stats
    combine (size-weighted mean), shapes/dtypes list per part."""
    if len(parts) == 1:
        return dict(parts[0])
    csum = 0
    total = 0
    w_mean = 0.0
    mins = [p["min"] for p in parts if p["min"] is not None]
    maxs = [p["max"] for p in parts if p["max"] is not None]
    for p in parts:
        csum ^= int(p["checksum"], 16)
        n = 1
        for d in p["shape"]:
            n *= int(d)
        if p["mean"] is not None:
            w_mean += p["mean"] * n
            total += n
    return {
        "checksum": f"{csum:016x}",
        "shape": [p["shape"] for p in parts],
        "dtype": [p["dtype"] for p in parts],
        "min": min(mins) if mins else None,
        "max": max(maxs) if maxs else None,
        "mean": (w_mean / total) if total else None,
        "nan_count": sum(int(p["nan_count"]) for p in parts),
        "inf_count": sum(int(p["inf_count"]) for p in parts),
    }


# -- the tracer-attached monitor ----------------------------------------------


class NumericsMonitor:
    """Per-run numerics state, attached to a Tracer as ``tracer.numerics``
    (the same attachment pattern as ``tracer.resource_sampler``):
    ``checkpoints`` is the ordered audit stream ``tools/parity_audit.py``
    diffs, ``nonfinite_total`` the watchdog tally. ``summary()`` is the
    RunRecord ``numerics`` block (schema v6)."""

    def __init__(
        self,
        level: str = "audit",
        inject: Optional[Tuple[str, str]] = None,
    ) -> None:
        if level not in ("watch", "audit"):
            raise ValueError(f"monitor level must be watch|audit; got {level!r}")
        self.level = level
        self.inject = inject
        self.checkpoints: List[dict] = []
        self.nonfinite_total = 0
        self.dropped = 0  # audit records past NUMERICS_RECORD_CAP

    def summary(self) -> dict:
        out: dict = {
            "level": self.level,
            "nonfinite": int(self.nonfinite_total),
            "checkpoints": list(self.checkpoints),
        }
        if self.inject is not None:
            out["inject"] = ":".join(self.inject)
        if self.dropped:
            out["dropped"] = int(self.dropped)
        return out


def attach_numerics(
    tracer: Optional[Tracer],
    level: Optional[str] = None,
    inject: Optional[str] = None,
) -> Optional[NumericsMonitor]:
    """Attach a NumericsMonitor to ``tracer`` per the resolved level; returns
    it (None when off or tracer-less — numeric_checkpoint is then a no-op).
    ``inject`` defaults to the ``CCTPU_NUMERICS_INJECT`` env spec so the
    parity auditor's planted-downgrade self-test needs no plumbing through
    the pipeline."""
    lvl = resolve_numerics(level)
    if lvl == "off" or tracer is None:
        return None
    spec = inject if inject is not None else os.environ.get("CCTPU_NUMERICS_INJECT")
    mon = NumericsMonitor(lvl, parse_inject(spec))
    tracer.numerics = mon
    return mon


def _resolve_arrays(arrays) -> List[Any]:
    """Expand lazy payloads: callables are invoked (only past the level
    gate — with numerics off they never run), and may return one array or a
    tuple/list of arrays; None entries drop."""
    out: List[Any] = []
    for a in arrays:
        if a is None:
            continue
        if callable(a):
            a = a()
        if a is None:
            continue
        if isinstance(a, (tuple, list)):
            out.extend(x for x in a if x is not None)
        else:
            out.append(a)
    return out


def _apply_inject(mon: NumericsMonitor, name: str, arrays: List[Any]) -> List[Any]:
    if mon.inject is None or mon.inject[1] != name:
        return arrays
    import jax.numpy as jnp

    out = []
    for a in arrays:
        a = jnp.asarray(a)
        if jnp.issubdtype(a.dtype, jnp.floating):
            # the deliberate precision downgrade: round-trip through bf16
            a = a.astype(jnp.bfloat16).astype(a.dtype)
        out.append(a)
    return out


def numeric_checkpoint(log: Any, name: str, *arrays: Any) -> Optional[dict]:
    """Stamp checkpoint ``name`` with the fingerprint of ``arrays`` on the
    log's tracer-attached NumericsMonitor. ``arrays`` entries may be arrays
    or zero-arg callables returning them (lazy: with numerics off — no
    monitor attached — this function returns before resolving anything, so
    the default path pays nothing and dispatches nothing). Returns the audit
    record (or None in watch/off mode). Never raises: numerics observability
    must not fail the observed pipeline."""
    tr = tracer_of(log)
    mon: Optional[NumericsMonitor] = getattr(tr, "numerics", None) if tr else None
    if mon is None:
        return None
    try:
        return _checkpoint_impl(tr, mon, name, arrays)
    except Exception:
        return None


def _checkpoint_impl(
    tr: Tracer, mon: NumericsMonitor, name: str, arrays
) -> Optional[dict]:
    import jax.numpy as jnp

    resolved = _resolve_arrays(arrays)
    if not resolved:
        return None
    mets = metrics_of(tr)
    sp = tr.current_span()

    if mon.level == "watch":
        # watchdog only: one small reduction per float array, nothing recorded
        nonfinite = 0
        for a in resolved:
            a = jnp.asarray(a)
            if jnp.issubdtype(a.dtype, jnp.inexact) and a.size:
                nonfinite += int(_nonfinite_jit()(a))
        if nonfinite:
            _flag_nonfinite(tr, mets, sp, mon, name, nonfinite)
        return None

    resolved = _apply_inject(mon, name, resolved)
    fp = merge_fingerprints(
        [array_fingerprint(a) for a in resolved]
    )
    nonfinite = int(fp["nan_count"]) + int(fp["inf_count"])
    if nonfinite:
        _flag_nonfinite(tr, mets, sp, mon, name, nonfinite)
    mets.counter("numerics_checkpoints").inc()
    rec = {
        "seq": len(mon.checkpoints) + mon.dropped,
        "name": name,
        "t": round(time.monotonic() - tr.epoch, 4),
        "span": tr.span_path() or None,
        **fp,
    }
    if len(mon.checkpoints) < NUMERICS_RECORD_CAP:
        mon.checkpoints.append(rec)
    else:
        mon.dropped += 1
    tr.event(
        "numeric_fingerprint",
        checkpoint=name,
        checksum=fp["checksum"],
        nan_count=fp["nan_count"],
        inf_count=fp["inf_count"],
    )
    if sp is not None:
        sp.attrs.setdefault(FINGERPRINT_ATTR, {})[name] = fp["checksum"]
    return rec


def _flag_nonfinite(tr, mets, sp, mon, name: str, count: int) -> None:
    mon.nonfinite_total += count
    mets.counter("numerics_nonfinite").inc(count)
    if sp is not None:
        sp.attrs[NONFINITE_ATTR] = int(sp.attrs.get(NONFINITE_ATTR, 0)) + count
    tr.event("numerics_nonfinite", checkpoint=name, count=int(count))
