"""Telemetry exporters: Chrome/Perfetto trace events + Prometheus text.

Two operator-facing serializations of the obs/ state (ISSUE 4 tentpole):

  * :func:`chrome_trace_events` — a ``Span`` tree (live ``Tracer.roots`` via
    ``Span.to_dict``, or the ``spans`` of a persisted RunRecord) as
    trace-event JSON: ``ph: "X"`` complete events with microsecond ``ts`` /
    ``dur``, one ``tid`` lane per top-level phase name, span attrs as
    ``args``, and the flat event stream as ``ph: "i"`` instants. A schema-v4
    ``resource`` block (the obs/resource.py sampler series) additionally
    renders as ``ph: "C"`` **counter tracks** — ``host_rss_mb``,
    ``host_peak_rss_mb`` and (when the backend reports memory)
    ``device_mb`` — clamped into the span lanes' time range so the memory
    timeline lines up under the phases that caused it. The output of
    :func:`write_chrome_trace` loads directly in ``ui.perfetto.dev`` /
    ``chrome://tracing``.
  * :func:`prom_text_from_snapshot` — a ``MetricsRegistry.snapshot()`` dict
    in the Prometheus text exposition format (version 0.0.4): ``# HELP`` /
    ``# TYPE`` headers, counters as ``_total``, histograms as cumulative
    ``_bucket{le="..."}`` series plus ``_sum``/``_count``. This is what the
    ``AssignmentService`` ``/metrics`` endpoint serves.
  * :func:`fleet_chrome_trace` (ISSUE 19) — a serialized FleetRecord
    (obs/fleetobs.py) as ONE merged trace: each embedded RunRecord rendered
    through :func:`chrome_trace_events` then rebased by its epoch offset onto
    its own process lane (router = pid 1, replicas 2+, retired lanes kept),
    cross-replica ``ph:"s"/"t"/"f"`` flow links along every multi-hop request
    chain (failover re-routes, revival hand-offs), and fleet gauges as
    counter tracks replayed from the router's event stream.

Everything here operates on plain JSON-shaped dicts and stdlib types — no
jax, no numpy — so ``tools/report.py`` can load this file directly (by path,
package not required) on hosts without the accelerator stack. Sibling
modules (hist.py for quantiles, schema.py for metric help text) are imported
normally when the package is available and bootstrapped by file path when not.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional, Sequence


def _sibling(module: str):
    """Import a sibling obs/ module, falling back to a direct file load when
    the package is not importable (standalone tools/report.py usage)."""
    try:
        import importlib

        return importlib.import_module(f"consensusclustr_tpu.obs.{module}")
    except Exception:
        import importlib.util
        import os

        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), f"{module}.py"
        )
        spec = importlib.util.spec_from_file_location(f"_cctpu_obs_{module}", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod


# -- Chrome / Perfetto trace events ------------------------------------------

TRACE_PID = 1


def _span_dict(span: Any) -> dict:
    """Accept either a serialized span dict or a live Span object."""
    return span if isinstance(span, dict) else span.to_dict()


def _us(seconds: float) -> int:
    return int(round(seconds * 1e6))


def counter_track_events(
    resource: dict, hi_us: Optional[int] = None
) -> List[dict]:
    """``ph: "C"`` counter events for a RunRecord ``resource`` block.

    Two host tracks always (current RSS + running peak watermark, both MB)
    plus a ``device_mb`` track when samples carry device bytes. Timestamps
    are clamped into ``[0, hi_us]`` when given — the sampler keeps ticking
    past the last span close, and counters dangling beyond the lanes would
    stretch the viewport.
    """
    out: List[dict] = []
    peak_mb = 0.0
    for row in resource.get("samples") or ():
        try:
            t = float(row[0] or 0.0)
            rss = float(row[1])
            dev = row[2] if len(row) > 2 else None
        except (TypeError, ValueError, IndexError):
            continue
        ts = max(0, _us(t))
        if hi_us is not None:
            ts = min(ts, hi_us)
        mb = round(rss / 1e6, 3)
        peak_mb = max(peak_mb, mb)
        base = {"cat": "resource", "ph": "C", "ts": ts, "pid": TRACE_PID}
        out.append({"name": "host_rss_mb", **base, "args": {"mb": mb}})
        out.append({"name": "host_peak_rss_mb", **base, "args": {"mb": peak_mb}})
        if dev is not None:
            out.append({
                "name": "device_mb", **base,
                "args": {"mb": round(float(dev) / 1e6, 3)},
            })
    return out


FLOW_EVENT_NAME = "serve_request"
FLOW_LANE_NAME = "serve_requests"
NUMERICS_LANE_NAME = "numerics"


def numerics_lane_events(numerics: dict, tid: int) -> List[dict]:
    """``ph:"i"`` instants for a RunRecord ``numerics`` block (schema v6):
    one instant per audit checkpoint on a dedicated lane, named by the
    checkpoint itself (the generic event stream carries the same stamps as
    ``numeric_fingerprint`` instants on tid 0 — this lane gives them
    checkpoint names and their own track so a parity investigation can
    eyeball the stream order)."""
    out: List[dict] = []
    for ck in numerics.get("checkpoints") or ():
        try:
            ts = _us(float(ck.get("t") or 0.0))
        except (TypeError, ValueError):
            continue
        args = {
            k: ck[k]
            for k in ("checksum", "shape", "dtype", "mean", "nan_count",
                      "inf_count", "span")
            if ck.get(k) is not None
        }
        out.append({
            "name": str(ck.get("name", "?")), "cat": "numerics", "ph": "i",
            "ts": ts, "pid": TRACE_PID, "tid": tid, "s": "t",
            **({"args": args} if args else {}),
        })
    return out


def chrome_trace_events(
    spans: Iterable[Any],
    events: Iterable[dict] = (),
    resource: Optional[dict] = None,
    numerics: Optional[dict] = None,
) -> List[dict]:
    """Trace-event list for a span tree (+ optional flat event stream and
    resource-sampler counter tracks).

    Lanes: every distinct top-level span name gets its own ``tid`` (first-seen
    order, 1-based); descendants inherit the root's lane, so nesting renders
    as stack depth inside one track. ``tid`` 0 carries the flat events as
    instants. Children are clamped into their parent's interval — span
    timestamps are rounded independently at capture time, and the trace
    contract (events on one tid must nest) is stricter than the tree's.
    A ``resource`` block appends :func:`counter_track_events` clamped to the
    span lanes' end.

    Request flow links (ISSUE 7): a span carrying a ``request_ids`` attr (the
    AssignmentService ``serve_batch`` spans) anchors each listed id; every
    ``serve_request`` event whose ``req_id`` is anchored renders as (a) a
    residency slice on a dedicated ``serve_requests`` lane from its submit
    instant to its batch's start — the queue+batch-formation wait made
    visible — and (b) a Perfetto flow pair (``ph:"s"`` at the submit instant,
    ``ph:"f"``/``bp:"e"`` at the batch span) with ``id`` = the request id, so
    ui.perfetto.dev draws an arrow from each request to the batch that served
    it. Unanchored events (request still in flight, or records past the
    service's lifecycle cap) keep their plain instants and link nothing.
    """
    out: List[dict] = [
        {
            "name": "process_name", "ph": "M", "pid": TRACE_PID, "tid": 0,
            "args": {"name": "consensusclustr_tpu"},
        },
    ]
    lanes: Dict[str, int] = {}
    # request id -> (batch-span start us, batch-span tid): flow-finish anchors
    anchors: Dict[int, tuple] = {}

    def lane_for(root_name: str) -> int:
        if root_name not in lanes:
            lanes[root_name] = len(lanes) + 1
            out.append({
                "name": "thread_name", "ph": "M", "pid": TRACE_PID,
                "tid": lanes[root_name], "args": {"name": root_name},
            })
        return lanes[root_name]

    def emit(span: dict, tid: int, lo_us: int, hi_us: Optional[int]) -> None:
        ts = max(_us(float(span.get("t0") or 0.0)), lo_us)
        seconds = span.get("seconds")
        dur = _us(float(seconds)) if seconds is not None else 0
        if hi_us is not None:
            ts = min(ts, hi_us)
            dur = min(dur, hi_us - ts)
        dur = max(dur, 0)
        args = dict(span.get("attrs") or {})
        if seconds is None:
            args["open"] = True
        if not span.get("ok", True):
            args["ok"] = False
            args["error"] = span.get("error")
        ev = {
            "name": span.get("name", "?"), "cat": "span", "ph": "X",
            "ts": ts, "dur": dur, "pid": TRACE_PID, "tid": tid,
        }
        if args:
            ev["args"] = args
        out.append(ev)
        for rid in args.get("request_ids") or ():
            try:
                anchors.setdefault(int(rid), (ts, tid))
            except (TypeError, ValueError):
                pass
        for child in span.get("children", []):
            emit(_span_dict(child), tid, ts, ts + dur)

    for root in spans:
        d = _span_dict(root)
        emit(d, lane_for(d.get("name", "?")), 0, None)

    if any(lanes) or events:
        out.append({
            "name": "thread_name", "ph": "M", "pid": TRACE_PID, "tid": 0,
            "args": {"name": "events"},
        })
    for ev in events:
        rec = {
            "name": str(ev.get("kind", "event")), "cat": "event", "ph": "i",
            "ts": _us(float(ev.get("t") or 0.0)), "pid": TRACE_PID, "tid": 0,
            "s": "p",
        }
        args = {k: v for k, v in ev.items() if k not in ("kind", "t")}
        if args:
            rec["args"] = args
        out.append(rec)
        if rec["name"] == FLOW_EVENT_NAME and "req_id" in args:
            try:
                rid = int(args["req_id"])
            except (TypeError, ValueError):
                continue
            if rid not in anchors:
                continue
            a_ts, a_tid = anchors[rid]
            ts = rec["ts"]
            a_ts = max(a_ts, ts)  # independent rounding can reorder by <1 tick
            req_tid = lane_for(FLOW_LANE_NAME)
            base = {"name": FLOW_EVENT_NAME, "cat": "serve", "pid": TRACE_PID}
            out.append({  # residency slice: submit -> serving batch start
                **base, "ph": "X", "ts": ts, "dur": max(a_ts - ts, 1),
                "tid": req_tid, "args": {"req_id": rid},
            })
            out.append({**base, "ph": "s", "id": rid, "ts": ts, "tid": req_tid})
            out.append({
                **base, "ph": "f", "bp": "e", "id": rid, "ts": a_ts,
                "tid": a_tid,
            })
    if numerics and numerics.get("checkpoints"):
        out.extend(numerics_lane_events(numerics, lane_for(NUMERICS_LANE_NAME)))
    if resource:
        ends = [
            e["ts"] + e.get("dur", 0) for e in out if e.get("ph") in ("X", "i")
        ]
        out.extend(counter_track_events(resource, max(ends) if ends else None))
    return out


def chrome_trace(
    spans: Iterable[Any],
    events: Iterable[dict] = (),
    metadata: Optional[dict] = None,
    resource: Optional[dict] = None,
    numerics: Optional[dict] = None,
) -> dict:
    """The full trace-object form ({"traceEvents": [...]}) Perfetto loads."""
    doc = {
        "traceEvents": chrome_trace_events(
            spans, events, resource=resource, numerics=numerics
        ),
        "displayTimeUnit": "ms",
    }
    if metadata:
        doc["metadata"] = metadata
    return doc


def write_chrome_trace(
    path: str,
    spans: Iterable[Any],
    events: Iterable[dict] = (),
    metadata: Optional[dict] = None,
    resource: Optional[dict] = None,
    numerics: Optional[dict] = None,
) -> str:
    """Serialize :func:`chrome_trace` to ``path``; returns the path."""
    with open(path, "w") as f:
        json.dump(
            chrome_trace(
                spans, events, metadata=metadata, resource=resource,
                numerics=numerics,
            ),
            f,
        )
    return path


# -- fleet merge (ISSUE 19): one trace across router + every replica ---------

FLEET_FLOW_NAME = "fleet_trace"
FLEET_HOP_LANE = "fleet_hops"
FLEET_HOP_TID = 99
FLEET_ROUTER_PROCESS = "fleet_router"


def _shift_record_events(
    record: dict, pid: int, process_name: str, shift_us: int
) -> List[dict]:
    """One embedded RunRecord's :func:`chrome_trace_events`, rebased onto the
    fleet clock: ``pid`` reassigned to this lane, every non-metadata timestamp
    shifted by the record's epoch offset, and ``cat:"serve"`` flow ids
    namespaced by pid — per-replica ``req_id`` counters all start at 1, and
    colliding flow ids would let Perfetto draw arrows between unrelated
    requests on different lanes."""
    out: List[dict] = []
    for e in chrome_trace_events(
        record.get("spans") or (),
        record.get("events") or (),
        resource=record.get("resource"),
        numerics=record.get("numerics"),
    ):
        e = dict(e)
        e["pid"] = pid
        if e.get("ph") == "M":
            if e.get("name") == "process_name":
                e["args"] = {"name": process_name}
        else:
            e["ts"] = int(e.get("ts", 0)) + shift_us
            if e.get("cat") == "serve" and "id" in e:
                e["id"] = pid * 1_000_000 + int(e["id"])
        out.append(e)
    return out


def fleet_flow_events(
    fleet: dict, pid_of: Dict[str, int], shift_us: int = 0
) -> List[dict]:
    """Cross-replica flow links for every retained multi-hop chain.

    Each hop renders as a mini ``ph:"X"`` slice on a dedicated ``fleet_hops``
    lane (fixed ``tid`` 99) of the replica it landed on, spanning from the
    hop's admission-relative route time to the next hop (or, for the final
    hop, its replica-measured serve latency). The slices are chained with a
    Perfetto multi-step flow — ``ph:"s"`` at the first hop, ``ph:"t"`` at
    intermediate hops, ``ph:"f"``/``bp:"e"`` at the last — sharing
    ``cat:"fleet"`` and ``id`` = the fleet-scoped trace id, so a failover
    re-route or revival hand-off draws as one arrow sequence hopping across
    process lanes. Single-hop chains are skipped: those requests already
    render via the per-replica ``serve`` flow pairs."""
    out: List[dict] = []
    named: set = set()
    for tr in (fleet.get("trace") or {}).get("traces") or ():
        hops = tr.get("hops") or ()
        if len(hops) < 2:
            continue
        try:
            t_admit = float(tr.get("t_admit") or 0.0)
            flow_id = int(tr["trace_id"])
        except (TypeError, ValueError, KeyError):
            continue
        chain = []
        for k, hop in enumerate(hops):
            pid = pid_of.get(str(hop.get("replica")))
            if pid is None:
                continue
            t = float(hop.get("t") or 0.0)
            if k + 1 < len(hops):
                dur_s = max(float(hops[k + 1].get("t") or 0.0) - t, 0.0)
            else:
                dur_s = max(float(hop.get("serve_latency_s") or 0.0), 0.0)
            chain.append((pid, _us(t_admit + t) + shift_us, max(_us(dur_s), 1), hop))
        if len(chain) < 2:
            continue
        for k, (pid, ts, dur, hop) in enumerate(chain):
            if pid not in named:
                named.add(pid)
                out.append({
                    "name": "thread_name", "ph": "M", "pid": pid,
                    "tid": FLEET_HOP_TID, "args": {"name": FLEET_HOP_LANE},
                })
            args = {
                k2: hop[k2]
                for k2 in ("trace_id", "hop", "replica", "kind", "req_id",
                           "outcome", "error", "serve_latency_s")
                if hop.get(k2) is not None
            }
            base = {
                "name": FLEET_FLOW_NAME, "cat": "fleet", "pid": pid,
                "tid": FLEET_HOP_TID,
            }
            out.append({**base, "ph": "X", "ts": ts, "dur": dur, "args": args})
            ph = "s" if k == 0 else ("f" if k == len(chain) - 1 else "t")
            flow = {**base, "ph": ph, "id": flow_id, "ts": ts}
            if ph == "f":
                flow["bp"] = "e"
            out.append(flow)
    return out


def fleet_counter_events(router_rec: dict, shift_us: int = 0) -> List[dict]:
    """Fleet gauges as ``ph:"C"`` counter tracks on the router lane, replayed
    from the router's event stream: configured fleet size (``fleet_start``
    name-list / ``fleet_swap`` count), a healthy-replica track that dips on
    ``fleet_replica_down`` and recovers on ``fleet_replica_revived``, and a
    cumulative failover count stepping at each ``fleet_failover``."""
    out: List[dict] = []
    size: Optional[int] = None
    healthy: Optional[int] = None
    failovers = 0

    def emit(ts: int, name: str, value: int) -> None:
        out.append({
            "name": name, "cat": "fleet", "ph": "C", "ts": ts, "pid": 1,
            "args": {name.rsplit("_", 1)[-1]: value},
        })

    for ev in router_rec.get("events") or ():
        kind = ev.get("kind")
        try:
            ts = _us(float(ev.get("t") or 0.0)) + shift_us
        except (TypeError, ValueError):
            continue
        if kind == "fleet_start":
            size = healthy = len(ev.get("replicas") or ())
        elif kind == "fleet_swap":
            try:
                size = int(ev.get("replicas") or 0) or size
            except (TypeError, ValueError):
                pass
            healthy = size
        elif kind == "fleet_replica_down":
            healthy = max((healthy if healthy is not None else 1) - 1, 0)
        elif kind == "fleet_replica_revived":
            healthy = min(
                (healthy if healthy is not None else 0) + 1,
                size if size is not None else 1 << 30,
            )
        elif kind == "fleet_failover":
            failovers += 1
            emit(ts, "fleet_failovers", failovers)
            continue
        else:
            continue
        if size is not None:
            emit(ts, "fleet_replicas", size)
        if healthy is not None:
            emit(ts, "fleet_healthy_replicas", healthy)
    return out


def fleet_trace_events(fleet: dict) -> List[dict]:
    """Merged trace-event list for a serialized FleetRecord dict.

    Process lanes: the router is ``pid`` 1 (``fleet_router``); replicas get
    ``pid`` 2+ in record order, retired lanes labeled ``(retired)`` so a
    revival's dead predecessor and a swap's drained generation stay visible
    next to their successors. All timestamps rebase onto the earliest epoch
    in the fleet (replicas are built before the router in ``build_fleet``,
    so the *minimum* offset — possibly negative — anchors ts 0; Perfetto
    clamps negative timestamps). On top of the per-record lanes:
    :func:`fleet_flow_events` (cross-replica hop chains) and
    :func:`fleet_counter_events` (fleet gauges)."""
    router_rec = fleet.get("router") or {}
    replicas = list(fleet.get("replicas") or ())
    base = min(
        [0.0] + [float(r.get("epoch_offset_s") or 0.0) for r in replicas]
    )
    router_shift = _us(0.0 - base)
    out = _shift_record_events(router_rec, 1, FLEET_ROUTER_PROCESS, router_shift)
    pid_of: Dict[str, int] = {}
    for i, rep in enumerate(replicas):
        pid = 2 + i
        name = str(rep.get("name") or f"replica{i}")
        pid_of.setdefault(name, pid)
        label = f"replica:{name}" + (" (retired)" if rep.get("retired") else "")
        out.extend(_shift_record_events(
            rep.get("record") or {}, pid, label,
            _us(float(rep.get("epoch_offset_s") or 0.0) - base),
        ))
    # hop timestamps are admission-relative on the *router* clock
    out.extend(fleet_flow_events(fleet, pid_of, router_shift))
    out.extend(fleet_counter_events(router_rec, router_shift))
    return out


def fleet_chrome_trace(fleet: dict, metadata: Optional[dict] = None) -> dict:
    """The full trace-object form for a FleetRecord dict."""
    doc = {
        "traceEvents": fleet_trace_events(fleet),
        "displayTimeUnit": "ms",
        "metadata": {
            "fleet_schema": fleet.get("schema"),
            "generation": fleet.get("generation"),
            "replicas": len(fleet.get("replicas") or ()),
            **(metadata or {}),
        },
    }
    return doc


def write_fleet_chrome_trace(
    path: str, fleet: dict, metadata: Optional[dict] = None
) -> str:
    """Serialize :func:`fleet_chrome_trace` to ``path``; returns the path."""
    with open(path, "w") as f:
        json.dump(fleet_chrome_trace(fleet, metadata=metadata), f)
    return path


# -- Prometheus text exposition ----------------------------------------------

PROM_PREFIX = "cctpu_"
PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _fmt(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return f"{f:.10g}"


def _esc_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _help_map() -> Dict[str, str]:
    try:
        return dict(_sibling("schema").METRIC_HELP)
    except Exception:
        return {}


def prom_quantile(hist: dict, q: float) -> Optional[float]:
    """Quantile estimate from a serialized histogram snapshot dict (the
    ``bounds``/``bucket_counts`` fields); None for empty or bucket-less
    (pre-schema-2) snapshots."""
    bounds = hist.get("bounds")
    counts = hist.get("bucket_counts")
    if not bounds or not counts:
        return None
    return _sibling("hist").bucket_quantile(
        bounds, counts, q, lo=hist.get("min"), hi=hist.get("max")
    )


def prom_text_from_snapshot(
    snapshot: dict, help_map: Optional[Dict[str, str]] = None
) -> str:
    """Render a ``MetricsRegistry.snapshot()`` dict as Prometheus text.

    Every series is prefixed ``cctpu_``; counters get the conventional
    ``_total`` suffix; unset gauges are omitted (absence, not 0 — a serving
    dashboard must not read "queue empty" from "never measured"); histogram
    ``_bucket`` series are cumulative with a terminal ``le="+Inf"`` equal to
    ``_count``. Ends with a trailing newline as the exposition format
    requires.
    """
    if help_map is None:
        help_map = _help_map()
    lines: List[str] = []

    def head(name: str, kind: str, base: str) -> None:
        h = help_map.get(base)
        if h:
            lines.append(f"# HELP {name} {_esc_help(h)}")
        lines.append(f"# TYPE {name} {kind}")

    for base, v in (snapshot.get("counters") or {}).items():
        name = f"{PROM_PREFIX}{base}_total"
        head(name, "counter", base)
        lines.append(f"{name} {_fmt(v)}")
    for base, v in (snapshot.get("gauges") or {}).items():
        if v is None:
            continue
        name = f"{PROM_PREFIX}{base}"
        head(name, "gauge", base)
        lines.append(f"{name} {_fmt(v)}")
    for base, h in (snapshot.get("histograms") or {}).items():
        name = f"{PROM_PREFIX}{base}"
        head(name, "histogram", base)
        bounds: Sequence[float] = h.get("bounds") or ()
        counts: Sequence[int] = h.get("bucket_counts") or ()
        if bounds and counts:
            cum = 0
            for bound, c in zip(bounds, counts):
                cum += int(c)
                lines.append(
                    f'{name}_bucket{{le="{_fmt(bound)}"}} {cum}'
                )
            lines.append(f'{name}_bucket{{le="+Inf"}} {int(h.get("count", 0))}')
        lines.append(f"{name}_sum {_fmt(h.get('sum', 0.0))}")
        lines.append(f"{name}_count {int(h.get('count', 0))}")
    return "\n".join(lines) + "\n"
