"""Hierarchical spans + flat events: the run-wide tracer.

Generalizes ``utils/profiling.phase`` (wall-clock with block-until-ready
semantics) into a parent/child span tree, so a run record can answer "where
did the time go" per phase AND per nesting level (a null test inside the
significance gate inside level 2). JAX dispatch is async: assign a span's
output arrays to ``span.value`` and the timer blocks on them at exit, the
same sink contract ``phase`` established.

Spans are host-side and cheap (one dataclass + two clock reads); the optional
``annotate=True`` additionally enters a ``jax.profiler.TraceAnnotation`` so
the same name shows up inside device traces (TensorBoard / Perfetto).

``Tracer.event`` carries the original flat LevelLog record stream; events
emitted inside a span are stamped with the span path so the two views join.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from consensusclustr_tpu.obs.metrics import MetricsRegistry, global_metrics


@dataclasses.dataclass
class Span:
    """One timed region; ``children`` nest, ``value`` is the async-dispatch
    sink (blocked on at exit, never serialized)."""

    name: str
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    t0: float = 0.0                  # start, seconds since tracer epoch
    seconds: Optional[float] = None  # None while the span is open
    ok: bool = True
    error: Optional[str] = None
    children: List["Span"] = dataclasses.field(default_factory=list)
    value: Any = None

    def set(self, **attrs: Any) -> None:
        self.attrs.update(attrs)

    def to_dict(self) -> dict:
        d: dict = {"name": self.name, "t0": self.t0, "seconds": self.seconds}
        if not self.ok:
            d["ok"] = False
            d["error"] = self.error
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Span":
        return cls(
            name=d.get("name", "?"),
            attrs=dict(d.get("attrs", {})),
            t0=float(d.get("t0", 0.0)),
            seconds=d.get("seconds"),
            ok=bool(d.get("ok", True)),
            error=d.get("error"),
            children=[cls.from_dict(c) for c in d.get("children", [])],
        )

    def walk(self, depth: int = 0):
        yield depth, self
        for c in self.children:
            yield from c.walk(depth + 1)


class Tracer:
    """Collects a span tree, a flat event list, and a metrics registry for
    one run.

    Thread model (ISSUE 7): the open-span stack is **thread-local** — each
    thread nests its own spans and sees only its own span path, so the
    serving worker's per-batch spans can never splice into (or pop) a span
    another thread holds open, and an event emitted from a client thread is
    never stamped with some other thread's span path. The shared collections
    (``roots``, ``events``) take only GIL-atomic appends; there is still no
    lock in the hot path (SURVEY §7.1 — the pipeline's host control is one
    thread, and serving adds exactly one span-writing worker)."""

    def __init__(
        self,
        progress: bool = False,
        annotate: bool = False,
        metrics: Optional[MetricsRegistry] = None,
    ) -> None:
        self.progress = progress
        self.annotate = annotate
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.roots: List[Span] = []
        self.events: List[dict] = []
        self.epoch = time.monotonic()
        self._local = threading.local()
        # span-close hooks (obs/resource.py watermark attribution): called
        # with the closed Span after ``seconds`` is set; exceptions swallowed
        self._span_close_hooks: List[Any] = []
        # published {thread_ident: open-span-path} map — None unless a
        # sampling profiler attached one (obs/profiler.py). The unarmed
        # span() path pays exactly one attribute check (off-is-free pin).
        self._span_paths: Optional[Dict[int, str]] = None

    @property
    def _stack(self) -> List[Span]:
        """This thread's open-span stack (created on first touch)."""
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def add_span_close_hook(self, fn: Any) -> None:
        """Register ``fn(span)`` to run whenever a span closes (after its
        ``seconds`` is final, before the stack pops) — the ResourceSampler
        uses this to stamp per-phase memory watermark attrs."""
        self._span_close_hooks.append(fn)

    def publish_span_paths(self, mapping: Optional[Dict[int, str]]) -> None:
        """Attach (detach with None) a shared {thread_ident: span-path} map
        that ``span()`` keeps current on push/pop — the sampling profiler's
        cross-thread view of the thread-local stacks (obs/profiler.py tags
        samples with it). Only the armed path does dict work."""
        self._span_paths = mapping

    def _publish_path(self) -> None:
        m = self._span_paths
        if m is None:
            return
        ident = threading.get_ident()
        stack = self._stack
        if stack:
            m[ident] = "/".join(s.name for s in stack)
        else:
            m.pop(ident, None)

    # -- spans ---------------------------------------------------------------

    @contextlib.contextmanager
    def span(
        self, name: str, annotate: Optional[bool] = None, **attrs: Any
    ) -> Iterator[Span]:
        sp = Span(
            name=name, attrs=dict(attrs),
            t0=round(time.monotonic() - self.epoch, 4),
        )
        (self._stack[-1].children if self._stack else self.roots).append(sp)
        self._stack.append(sp)
        if self._span_paths is not None:
            self._publish_path()
        ann = None
        if self.annotate if annotate is None else annotate:
            try:
                import jax

                ann = jax.profiler.TraceAnnotation(name)
                ann.__enter__()
            except Exception:
                ann = None
        t0 = time.perf_counter()
        try:
            yield sp
        except BaseException as e:
            sp.ok = False
            sp.error = type(e).__name__
            raise
        finally:
            if sp.ok and sp.value is not None:
                try:
                    import jax

                    jax.block_until_ready(sp.value)
                except Exception:
                    pass
            sp.value = None
            sp.seconds = round(time.perf_counter() - t0, 4)
            if ann is not None:
                try:
                    ann.__exit__(None, None, None)
                except Exception:
                    pass
            for hook in self._span_close_hooks:
                try:
                    hook(sp)
                except Exception:
                    pass  # observability must never fail the traced work
            self._stack.pop()
            if self._span_paths is not None:
                self._publish_path()
            if not self._stack:
                # top-level phase timings ride the bucketed histogram path so
                # RunRecords / /metrics can answer phase-duration quantiles
                self.metrics.histogram("phase_seconds").observe(sp.seconds)
            if self.progress:
                self._emit({
                    "t": sp.t0, "kind": "span", "name": self.span_path(sp.name),
                    "seconds": sp.seconds,
                    **({} if sp.ok else {"ok": False, "error": sp.error}),
                })

    def current_span(self) -> Optional[Span]:
        """This thread's innermost open span (None at top level) — the span
        numeric checkpoints (obs/fingerprint.py) stamp their attrs on."""
        stack = self._stack
        return stack[-1] if stack else None

    def span_path(self, leaf: Optional[str] = None) -> str:
        parts = [s.name for s in self._stack]
        if leaf is not None and (not parts or parts[-1] != leaf):
            parts.append(leaf)
        return "/".join(parts)

    # -- flat events (LevelLog contract) -------------------------------------

    def event(self, kind: str, **fields: Any) -> None:
        rec = {"t": round(time.monotonic() - self.epoch, 4), "kind": kind, **fields}
        if self._stack:
            rec.setdefault("span", self.span_path())
        self.events.append(rec)
        if self.progress:
            self._emit(rec)

    # -- aggregation ---------------------------------------------------------

    def phase_seconds(self) -> Dict[str, float]:
        """Top-level phase breakdown: root-span seconds summed by name."""
        out: Dict[str, float] = {}
        for sp in self.roots:
            if sp.seconds is not None:
                out[sp.name] = round(out.get(sp.name, 0.0) + sp.seconds, 4)
        return out

    def elapsed(self) -> float:
        return round(time.monotonic() - self.epoch, 4)

    def epoch_offset_from(self, other: "Tracer") -> float:
        """Seconds between this tracer's epoch and ``other``'s (positive
        when this tracer was born later). Span ``t0``/event ``t`` stamps
        are epoch-relative, so adding this offset rebases them onto
        ``other``'s timeline — the fleet-merge primitive (obs/fleetobs.py):
        every replica's record shifts onto the router's clock so one merged
        trace orders events across processes-worth of tracers."""
        return round(self.epoch - other.epoch, 6)

    @staticmethod
    def _emit(rec: dict) -> None:
        import json

        from consensusclustr_tpu.utils.log import _jsonable, get_logger

        get_logger().info(json.dumps(rec, default=_jsonable))


@contextlib.contextmanager
def _null_span(name: str, **attrs: Any) -> Iterator[Span]:
    # detached Span: callers can .set()/.value without a tracer in scope
    yield Span(name=name, attrs=dict(attrs))


def tracer_of(log: Any) -> Optional[Tracer]:
    """The Tracer behind a LevelLog shim (or a bare Tracer); None otherwise."""
    if isinstance(log, Tracer):
        return log
    tr = getattr(log, "tracer", None)
    return tr if isinstance(tr, Tracer) else None


def maybe_span(log: Any, name: str, **attrs: Any):
    """Span on the log's tracer, or an inert detached span when ``log`` is
    None / tracer-less — lets library code instrument unconditionally."""
    tr = tracer_of(log)
    if tr is None:
        return _null_span(name, **attrs)
    return tr.span(name, **attrs)


def metrics_of(log: Any) -> MetricsRegistry:
    """The log's run-local registry, or the process-global one."""
    tr = tracer_of(log)
    return tr.metrics if tr is not None else global_metrics()
