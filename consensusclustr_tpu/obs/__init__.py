"""Run-wide observability: hierarchical spans, metrics, JSONL run records.

Three pieces (ISSUE 1 tentpole), all host-side and import-light:

  * ``Tracer``/``Span`` — parent/child timed regions with the async-dispatch
    sink contract of ``utils/profiling.phase`` (assign outputs to
    ``span.value`` and the timer blocks on them) and optional
    ``jax.profiler.TraceAnnotation`` pass-through;
  * ``MetricsRegistry`` — counters/gauges/histograms (run-local on the
    tracer, plus a process-global registry for cross-run state like the
    persistent compile cache);
  * ``RunRecord`` — schema-versioned JSONL serialization of span tree +
    events + metrics + config fingerprint, rendered by ``tools/report.py``.

``utils.log.LevelLog`` is a thin compatibility shim over ``Tracer`` — every
existing ``log.event(...)`` call site feeds the same record stream.
``obs/schema.py`` registers all legal event/span/metric names;
``tools/check_obs_schema.py`` statically enforces the registry.
"""

from consensusclustr_tpu.obs.metrics import (
    MetricsRegistry,
    global_metrics,
    record_device_memory,
)
from consensusclustr_tpu.obs.record import (
    RunRecord,
    config_fingerprint,
    load_records,
)
from consensusclustr_tpu.obs.schema import (
    EVENT_KINDS,
    METRIC_NAMES,
    SCHEMA_VERSION,
    SPAN_NAMES,
)
from consensusclustr_tpu.obs.tracer import (
    Span,
    Tracer,
    maybe_span,
    metrics_of,
    tracer_of,
)

__all__ = [
    "EVENT_KINDS",
    "METRIC_NAMES",
    "MetricsRegistry",
    "RunRecord",
    "SCHEMA_VERSION",
    "SPAN_NAMES",
    "Span",
    "Tracer",
    "config_fingerprint",
    "global_metrics",
    "load_records",
    "maybe_span",
    "metrics_of",
    "record_device_memory",
    "tracer_of",
]
