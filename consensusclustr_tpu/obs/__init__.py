"""Run-wide observability: hierarchical spans, metrics, JSONL run records.

Three pieces (ISSUE 1 tentpole), all host-side and import-light:

  * ``Tracer``/``Span`` — parent/child timed regions with the async-dispatch
    sink contract of ``utils/profiling.phase`` (assign outputs to
    ``span.value`` and the timer blocks on them) and optional
    ``jax.profiler.TraceAnnotation`` pass-through;
  * ``MetricsRegistry`` — counters/gauges/histograms (run-local on the
    tracer, plus a process-global registry for cross-run state like the
    persistent compile cache);
  * ``RunRecord`` — schema-versioned JSONL serialization of span tree +
    events + metrics + config fingerprint, rendered by ``tools/report.py``.

``utils.log.LevelLog`` is a thin compatibility shim over ``Tracer`` — every
existing ``log.event(...)`` call site feeds the same record stream.
``obs/schema.py`` registers all legal event/span/metric names;
``tools/check_obs_schema.py`` statically enforces the registry.

The export layer (ISSUE 4 tentpole) turns that state into standard operator
surfaces: ``obs/hist.py`` gives every ``Histogram`` fixed log-spaced buckets
and a ``quantile(q)`` estimator; ``obs/export.py`` renders any span tree as
Chrome/Perfetto trace-event JSON (``RunRecord.to_chrome_trace`` /
``tools/report.py --trace``) and any metrics snapshot as Prometheus text
(``MetricsRegistry.to_prom_text``, served live by ``AssignmentService`` when
``CCTPU_SERVE_METRICS_PORT`` enables the scrape endpoint).

The resource layer (ISSUE 6 tentpole, ``obs/resource.py``) adds a background
``ResourceSampler`` (host RSS + device memory, off by default via
``CCTPU_RESOURCE_SAMPLE_MS`` / ``ClusterConfig.resource_sample_ms``): spans
gain ``rss_peak_bytes``/``device_peak_bytes`` watermark attrs at close, the
RunRecord carries the sample series (schema v4), and the Perfetto export
renders it as ``ph:"C"`` counter tracks under the span lanes.

The numerics layer (ISSUE 8 tentpole, ``obs/fingerprint.py``) observes the
*values*: device-side array fingerprints (order-independent 64-bit checksum
+ shape/dtype/min/max/mean/nan/inf scalars) stamped at the named pipeline
checkpoints in ``schema.NUMERIC_CHECKPOINTS`` under the opt-in
``CCTPU_NUMERICS`` / ``ClusterConfig.numerics`` level (``off``/``watch``/
``audit``; off is genuinely free). The RunRecord carries the checkpoint
stream (schema v6) and ``tools/parity_audit.py`` diffs two compute regimes'
streams, naming the first divergent checkpoint.

The work ledger (ISSUE 12 tentpole, ``obs/ledger.py``) is the deterministic
side of every perf claim: ``WorkLedger`` assembles the
``schema.WORK_LEDGER_COUNTERS`` (dispatches, compiles, estimated
flops/bytes, donated bytes, boots, faults/retries) into total +
per-top-level-phase deltas, attached unconditionally (one dict subtraction
per root span) and stamped into ``RunRecord.work_ledger`` (schema v7).
Same seeded workload ⇒ same ledger on any host — ``tools/bench_diff.py
--gate work`` gates it exactly while wall gates are noise-aware, and
``tools/perf_history.py`` renders the committed BENCH_*.json trajectory.

The failure layer (ISSUE 14 tentpole, ``obs/flight.py`` + ``obs/alerts.py``)
observes the system *while it is failing*: ``FlightRecorder`` keeps bounded
rings (events, spans, metric deltas, log tail) always on and dumps a
schema-versioned ``postmortem.json`` with all-thread stacks on unhandled
exception, SIGTERM/SIGINT, ``_fail_all``, and retry exhaustion
(``tools/postmortem.py`` renders/diffs dumps); ``StallWatchdog`` /
``stall_watch`` arm per-phase/per-batch deadlines from the live latency
histograms and fire ``stall_detected`` + a stack dump on a live wedge; and
``AlertEngine`` evaluates declarative SLO rules (p99 bound, rejection rate,
burn rate, counter monotonicity — ``schema.ALERT_RULES``) into
``alert_raised``/``alert_cleared`` events, the ``alerts_active`` gauge, and
the ``/healthz`` body. ``RunRecord`` gains ``postmortem_path``/``alerts``
(schema v8). Kill switch: ``CCTPU_NO_FLIGHT=1``.

The profiling layer (ISSUE 16 tentpole, ``obs/profiler.py`` +
``utils/compile_cache.py``) answers *which program and which stack*:
per-program cost attribution is always on (every ``counting_jit`` entry
point gets dispatches/compiles/est-flops/est-bytes/donated-bytes/dispatch-
wall rows summing to the global counters, ``RunRecord.program_profile``,
schema v9), while the span-tagged ``SamplingProfiler`` is opt-in
(``CCTPU_PROFILE_HZ`` / ``ClusterConfig.profile_hz``; off is pinned free):
a daemon thread folds ``sys._current_frames()`` into bounded weighted
stacks prefixed with each thread's open-span path
(``RunRecord.profile``), exported as collapsed-stack text or speedscope
JSON by ``tools/flamegraph.py``, and ridden into ``postmortem.json`` by
the flight recorder when armed.

The fleet-tracing layer (ISSUE 19 tentpole, ``obs/fleetobs.py``) merges the
per-process fragments a multi-replica fleet scatters: ``FleetRecord`` (new
artifact kind ``"fleet_record"``, schema v11) embeds the router's and every
replica's RunRecord — retired generations included — each with its tracer's
epoch offset, plus the router's retained per-request hop chains
(``trace_id`` minted at admission, hops appended at every route / failover /
revival). ``obs/export.py::write_fleet_chrome_trace`` renders it as one
Perfetto trace with per-replica process lanes, cross-replica flow links and
fleet counter tracks; ``tools/timeline.py`` folds it into the causal
incident timeline.
"""

from consensusclustr_tpu.obs.alerts import (
    AlertEngine,
    AlertRule,
    attach_alerts,
    default_alert_rules,
)
from consensusclustr_tpu.obs.flight import (
    FlightRecorder,
    StallWatchdog,
    attach_flight,
    dump_on_failure,
    flight_enabled,
    global_flight,
    global_watchdog,
    stall_watch,
)

from consensusclustr_tpu.obs.export import (
    chrome_trace_events,
    prom_text_from_snapshot,
    write_chrome_trace,
    write_fleet_chrome_trace,
)
from consensusclustr_tpu.obs.fleetobs import (
    FLEET_RECORD_KIND,
    FleetRecord,
)
from consensusclustr_tpu.obs.fingerprint import (
    NumericsMonitor,
    array_fingerprint,
    attach_numerics,
    numeric_checkpoint,
    resolve_numerics,
)
from consensusclustr_tpu.obs.hist import (
    DEFAULT_BOUNDS,
    bucket_quantile,
    log_bounds,
)
from consensusclustr_tpu.obs.ledger import (
    LEDGER_COUNTERS,
    WorkLedger,
    attach_ledger,
)
from consensusclustr_tpu.obs.metrics import (
    Histogram,
    MetricsRegistry,
    global_metrics,
    record_device_memory,
)
from consensusclustr_tpu.obs.profiler import (
    SamplingProfiler,
    active_profiles,
    profiling,
    resolve_profile_hz,
    start_profiler_for,
)
from consensusclustr_tpu.obs.record import (
    RunRecord,
    config_fingerprint,
    load_records,
)
from consensusclustr_tpu.obs.resource import (
    ResourceSampler,
    resource_sampling,
)
from consensusclustr_tpu.obs.schema import (
    EVENT_KINDS,
    METRIC_NAMES,
    SCHEMA_VERSION,
    SPAN_NAMES,
)
from consensusclustr_tpu.obs.tracer import (
    Span,
    Tracer,
    maybe_span,
    metrics_of,
    tracer_of,
)

__all__ = [
    "AlertEngine",
    "AlertRule",
    "DEFAULT_BOUNDS",
    "EVENT_KINDS",
    "FLEET_RECORD_KIND",
    "FleetRecord",
    "FlightRecorder",
    "Histogram",
    "LEDGER_COUNTERS",
    "METRIC_NAMES",
    "MetricsRegistry",
    "NumericsMonitor",
    "ResourceSampler",
    "RunRecord",
    "SCHEMA_VERSION",
    "SamplingProfiler",
    "SPAN_NAMES",
    "Span",
    "StallWatchdog",
    "Tracer",
    "WorkLedger",
    "active_profiles",
    "array_fingerprint",
    "attach_alerts",
    "attach_flight",
    "attach_ledger",
    "attach_numerics",
    "bucket_quantile",
    "chrome_trace_events",
    "config_fingerprint",
    "default_alert_rules",
    "dump_on_failure",
    "flight_enabled",
    "global_flight",
    "global_metrics",
    "global_watchdog",
    "load_records",
    "log_bounds",
    "maybe_span",
    "metrics_of",
    "numeric_checkpoint",
    "profiling",
    "prom_text_from_snapshot",
    "record_device_memory",
    "resolve_numerics",
    "resolve_profile_hz",
    "resource_sampling",
    "stall_watch",
    "start_profiler_for",
    "tracer_of",
    "write_chrome_trace",
    "write_fleet_chrome_trace",
]
