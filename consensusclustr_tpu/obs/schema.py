"""Observability schema registry (the contract tools/check_obs_schema.py
enforces).

Every event kind, span name and metric name used anywhere in the package (and
bench.py) must be registered here. The static check scans the sources for
literal ``.event("...")`` / ``.span("...")`` / ``.counter("...")`` calls and
fails the tier-1 suite on any name missing from these sets — a typo'd metric
name is a test failure, not a silently empty dashboard.

SCHEMA_VERSION stamps every RunRecord and bench JSON line (``obs_schema``) so
BENCH_*.json trajectories across PRs stay machine-comparable: a consumer can
refuse to diff phase breakdowns produced under different schemas. Bump it when
a registered name changes meaning, is removed, or the RunRecord layout
changes shape.
"""

from __future__ import annotations

# v2 (ISSUE 4): Histogram serialization gained ``bounds``/``bucket_counts``
# fields (log-spaced le buckets, obs/hist.py); ``phase_seconds`` histogram and
# the ``serve_metrics`` event were added; METRIC_HELP (below) became part of
# the registry contract.
# v3 (ISSUE 5): dispatch/compile accounting — ``device_dispatches``,
# ``executable_compiles`` and ``donated_bytes`` counters (sourced by
# utils/compile_cache.counting_jit, emitted per bench rung, rendered by
# tools/report.py's "== dispatch ==" table). See docs/quirks.md.
# v4 (ISSUE 6): resource profiling — RunRecord gained the optional
# ``resource`` block (the obs/resource.py ResourceSampler time series of
# (t, rss_bytes, device_bytes) samples), spans carry per-phase
# ``rss_peak_bytes``/``device_peak_bytes`` watermark attrs
# (RESOURCE_SPAN_ATTRS below, stamped by the sampler's span-close hook),
# the Perfetto export renders the series as ``ph:"C"`` counter tracks,
# and counting_jit harvests XLA cost_analysis into the
# ``estimated_flops``/``estimated_bytes_accessed`` counters. See
# docs/quirks.md "Observability schema v3 → v4".
# v5 (ISSUE 7): request-lifecycle tracing — every AssignmentService request
# carries a monotonically issued id plus enqueue/dequeue/dispatch/complete
# timestamps; submit→result latency decomposes into the
# ``queue_wait_seconds`` / ``batch_wait_seconds`` / ``device_seconds``
# histograms (their per-request sum equals ``serve_latency_seconds`` by
# construction), each micro-batch closes a ``serve_batch`` span carrying its
# request-id list + queue-age-at-dispatch attrs, each accepted submit emits a
# ``serve_request`` instant event, and obs/export.py links the two with
# Perfetto flow events (``ph:"s"``/``ph:"f"``). ``hist_merge_mismatch``
# counts histogram bucket ladders dropped on merge (previously silent). See
# docs/quirks.md "Observability schema v4 → v5".
# v6 (ISSUE 8): numerics observability — RunRecord gained the optional
# ``numerics`` block (obs/fingerprint.py NumericsMonitor summary: level,
# non-finite total, and the ordered checkpoint stream of device-side array
# fingerprints — order-independent 64-bit checksum + shape/dtype/min/max/
# mean/nan/inf scalars, stamped at the NUMERIC_CHECKPOINTS below under the
# opt-in ``CCTPU_NUMERICS`` / ``ClusterConfig.numerics`` level). ``audit``
# checkpoints ride the event stream as ``numeric_fingerprint`` instants and
# stamp the enclosing span's ``fingerprints`` attr; the ``watch`` NaN/Inf
# watchdog increments ``numerics_nonfinite`` and tags the offending span.
# bench rungs carry ``labels_fingerprint`` and tools/parity_audit.py diffs
# two regimes' checkpoint streams. See docs/quirks.md
# "Observability schema v5 → v6".
# ISSUE 9 (sparse consensus) deliberately did NOT bump the version: the
# ``candidates`` span, the CONSENSUS_SPAN_ATTRS regime attrs and the bench
# ``sparse_consensus`` sub-rung are purely additive (same precedent as the
# serve/ names landing inside v1), no registered name changed meaning and
# the RunRecord grew no field. See docs/quirks.md "Consensus regimes and
# the sparse_knn auto-switch".
# ISSUE 10 (resilience) is additive too — no bump: the FAULT_SITES registry
# below, the retry/quarantine/supervision counters and events, and the
# ``retry_backoff_seconds`` histogram are new names with no change to any
# existing one; the RunRecord layout is untouched. See docs/quirks.md
# "Fault injection, retries and checkpoint integrity".
# v7 (ISSUE 12): deterministic work ledger — RunRecord gained the
# ``work_ledger`` block (obs/ledger.py WorkLedger summary: total counter
# deltas since attach plus a per-top-level-phase attribution of the
# WORK_LEDGER_COUNTERS below, harvested at root-span close). Every bench
# rung — including the failure payload — now carries ``work_ledger``,
# ``env_health`` (loadavg before/during/after, nproc, cgroup cpu quota,
# probe_s, spin-calibration contention ratio) and, on the default rung,
# ``wall_trials`` (per-trial walls, median, MAD, robust CV). The ledger is
# the deterministic side of every perf claim: tools/bench_diff.py gates it
# exactly (``--gate work``) while wall gates became noise-aware, and
# tools/perf_history.py walks the committed BENCH_*.json series with
# ledger-vs-wall divergence annotations. See docs/quirks.md
# "Observability schema v6 → v7".
# ISSUE 13 (Pallas SNN kernel + int16 lanes + AOT warm start) is additive —
# no bump: the SNN_IMPLS registry below, the snn_impl/snn_rev_edges_dropped
# consensus-span attrs, the ``snn_rev_edges_dropped`` counter, the AOT
# executable-cache counters and the ``aot_warm_start`` event are new names
# with no change to any existing one; the RunRecord layout is untouched and
# the bench ``warm_start`` rung is a new block (same precedent as ISSUE 9/10).
# v8 (ISSUE 14): failure-time observability — RunRecord gained the optional
# ``postmortem_path`` (where the obs/flight.py black-box recorder wrote its
# last schema-versioned post-mortem dump, None when nothing failed) and
# ``alerts`` (obs/alerts.py AlertEngine summary: active alerts, raise/clear
# totals, last alert) fields. New names: the ``stall_detected`` /
# ``alert_raised`` / ``alert_cleared`` / ``postmortem_dump`` events, the
# ``stalls_detected`` / ``alerts_raised`` / ``postmortem_dumps`` counters and
# the ``alerts_active`` gauge, plus the FLIGHT_EVENT_KINDS (dump-reason
# vocabulary) and ALERT_RULES (declarative SLO rule names) registries below.
# Every bench rung — including the failure payload — now carries ``alerts``
# and ``postmortem_path`` keys, and /healthz reports ``alerts_active`` /
# ``last_alert``. See docs/quirks.md "Observability schema v7 → v8".
# v9 (ISSUE 16): deep profiling — RunRecord gained the ``program_profile``
# block (utils/compile_cache.py per-program cost attribution: for every
# counting_jit entry point, dispatches / compiles / est_flops / est_bytes /
# donated_bytes / host-side dispatch wall plus per-shape-bucket cost rows,
# always on, rows summing to the global estimated_* counters by
# construction) and the optional ``profile`` block (obs/profiler.py
# span-tagged sampling profiler summary — opt-in via CCTPU_PROFILE_HZ /
# ClusterConfig.profile_hz, off is pinned free). New registries below:
# PROGRAM_NAMES (the decorated entry-point vocabulary) and
# PROGRAM_PROFILE_FIELDS (the row field names), both validated by
# tools/check_obs_schema.py / GL001. Every bench rung — including the
# failure payload — now carries ``program_profile``; armed profiles ride
# flight-recorder dumps as an optional ``profile`` key. See docs/quirks.md
# "Observability schema v8 → v9".
# v10 (ISSUE 18): the fleet layer — serve/router.py's FleetRouter puts N
# AssignmentService replicas behind health-keyed least-loaded admission,
# serve/fleet.py builds them, serve/control.py is the opt-in alert-driven
# ControlPolicy (CCTPU_FLEET_CONTROL, off is pinned free). New names: the
# fleet_* counters/gauges below (routing, rejection, failover, swap and
# control accounting — per-replica *gauges* carry the routed-to replica's
# state; the full per-replica split lives in FleetRouter.health()["routed"]
# because registry instruments are label-less by design), the fleet_*
# events, the ``fleet_swap`` span, and the CCTPU_FLEET_* knobs. Every bench
# payload — including the failure rung — now carries the ``fleet_slo``
# block plus top-level ``fleet_p99_ms`` / ``fleet_rejection_rate`` /
# ``fleet_routed`` / ``fleet_swap_compiles`` keys (zero-shape
# ``_FLEET_SLO_ZERO`` on failure). The RunRecord *layout* is unchanged —
# the bump marks the payload keys and the name vocabulary so bench_diff
# treats v9/v10 artifacts as schema-incomparable. See docs/quirks.md
# "Observability schema v9 → v10".
# v11 (ISSUE 19): fleet-wide distributed tracing — the FleetRouter mints a
# fleet-scoped ``trace_id`` at admission (router-minted, NOT replica-minted:
# a replica can die before it would mint anything, and only the router sees
# every hop of a request that crosses replicas) and records an ordered hop
# chain (initial route / failover re-route / revival slot) per request,
# threaded through ``AssignmentService.submit`` → the ``serve_request``
# event → the ``serve_batch`` span → ``AssignResult.timing["trace"]``.
# obs/fleetobs.py merges the router's and every replica's (live AND
# retired) RunRecords into one ``FleetRecord`` whose Perfetto export
# (obs/export.py fleet_* functions) gives each replica its own process
# lane, draws cross-replica ``ph:"s"/"t"/"f"`` flow links along each
# multi-hop chain, and renders fleet gauges as counter tracks;
# tools/timeline.py folds the merged events into a causally ordered
# incident timeline (render/diff, bench_diff exit codes). New names: the
# ``fleet_traces_dropped`` counter and the ``CCTPU_FLEET_TRACE_*`` knobs
# (hop-chain retention cap + the incident-artifact path loadgen and
# chaos_audit write). The RunRecord layout is unchanged; the FleetRecord is
# a NEW artifact kind ("fleet_record") that embeds RunRecords. Bench
# payloads gain the top-level ``fleet_trace`` block (zero shape ``{}`` on
# failure). See docs/quirks.md "Observability schema v10 → v11".
# ISSUE 20 (byte diet) is additive — no bump: the LEIDEN_IMPLS registry,
# the ``leiden_impl`` consensus-span attr and the CCTPU_LEIDEN_IMPL /
# CCTPU_BOOTS_PER_PROGRAM knobs are new names with no change to any
# existing one; the narrow-lane dtype changes (int16 SNN half-weights,
# uint16 co-cluster carries) are invisible at the schema boundary — every
# fingerprinted artifact widens to the historical f32 integer values first
# (same precedent as ISSUE 13's int16 lanes). See docs/quirks.md
# "The byte diet (ISSUE 20)".
SCHEMA_VERSION = 11

# ``LevelLog.event`` / ``Tracer.event`` kinds — the flat, append-only record
# stream (the original LevelLog contract, SURVEY §5).
EVENT_KINDS = frozenset({
    # api.py level driver
    "level_start",
    "too_small",
    "prep",
    "regressed",
    "interactive_pc_num",
    "pca",
    "pca_failed",
    "null_test_skipped",
    "level_done",
    "subcluster_failed",
    "failed_test",
    "run_record_write_failed",
    # consensus/pipeline.py + parallel/step.py
    "boots",
    "boots_resumed",
    "mesh_fallback",
    "mesh_auto_boot_only",
    "consensus",
    "consensus_distributed",
    "no_boot_result",
    "merged",
    # nulltest/
    "null_sims",
    "null_test",
    "split_retest",
    # utils/profiling.py
    "phase",
    # serve/service.py
    "serve_start",
    "serve_drain",
    "serve_metrics",   # /metrics + /healthz HTTP exporter came up (port attr)
    "serve_request",   # one accepted submit (req_id + rows attrs) — the
                       # request's flow-event anchor in the Perfetto export
    # obs/fingerprint.py (ISSUE 8)
    "numeric_fingerprint",   # one audit-mode checkpoint fingerprint
    "numerics_nonfinite",    # watchdog: NaN/Inf observed at a checkpoint
    # resilience/ (ISSUE 10)
    "retry",                 # one retried attempt at a fault site (site,
                             # attempt, error, backoff_s attrs)
    "retries_exhausted",     # a site gave up; the original exception follows
    "ckpt_quarantined",      # a corrupt/unreadable checkpoint chunk was
                             # renamed aside and will be recomputed
    "serve_worker_restart",  # the serving worker died unexpectedly and the
                             # supervisor restarted it
    # serve/service.py + utils/compile_cache.py (ISSUE 13)
    "aot_warm_start",        # warm-up finished its AOT pass (hits/saved/
                             # buckets attrs — hits == buckets is a fully
                             # warm cross-process start)
    # obs/flight.py + obs/alerts.py (ISSUE 14)
    "stall_detected",        # the watchdog saw a watch scope exceed its
                             # deadline (name, deadline_s, waited_s attrs;
                             # an all-thread stack dump follows)
    "postmortem_dump",       # the flight recorder wrote a post-mortem
                             # (reason from FLIGHT_EVENT_KINDS + path attrs)
    "alert_raised",          # an ALERT_RULES rule transitioned to firing
                             # (name, value, threshold attrs)
    "alert_cleared",         # a previously firing rule recovered
    # serve/router.py fleet layer (ISSUE 18)
    "fleet_start",           # router up (replicas list + control-armed attrs)
    "fleet_drain",           # router closed; routed-per-replica split attr
    "fleet_replica_down",    # a health scrape took a replica out of rotation
                             # (replica + status attrs)
    "fleet_replica_revived", # a dead slot was respawned from the template
    "fleet_failover",        # a replica died holding accepted requests; they
                             # re-queued as orphans (replica + error attrs)
    "fleet_swap",            # zero-downtime version swap completed
                             # (generation, swap_compiles, wall_s attrs)
    "fleet_control",         # a ControlPolicy pressure-class transition on
                             # one replica (replica, reason, deadline attrs)
})

# Hierarchical span names (``Tracer.span`` / ``maybe_span``).
SPAN_NAMES = frozenset({
    # api.py run phases (top level of a consensus_clust RunRecord)
    "ingest",
    "level",
    "iterate",
    "assemble",
    # api.py within-level phases
    "prep",
    "regress",
    "pca",
    "consensus",
    "significance",
    # consensus/pipeline.py
    "boots",
    "candidates",       # sparse_knn regime: the PC-space candidate-set build
    "cocluster",
    "consensus_grid",
    "merge",
    "consensus_distributed",
    # nulltest/
    "null_test",
    "null_sims",        # one pipelined chunk loop (per adaptive round)
    "null_sim_chunk",
    # serve/service.py
    "serve_warmup",     # bucket-ladder compile pass at service load
    "serve_batch",      # one micro-batch: request_ids list, bucket, rows,
                        # queue-age-at-dispatch attrs (the flow-event target)
    # serve/router.py (ISSUE 18)
    "fleet_swap",       # the whole hot-swap window: standby build -> atomic
                        # flip -> old-generation drain (swap_compiles attr is
                        # the pinned zero)
})

# Metric name -> one-line help text. This IS the metric registry: the name
# set below derives from it, the Prometheus exporter (obs/export.py) emits
# each entry as the series' # HELP line, and tools/check_obs_schema.py fails
# the suite if a name is registered without help (or vice versa).
METRIC_HELP = {
    "boots_completed": "counter: bootstraps actually computed (not resumed)",
    "boots_resumed": "counter: bootstraps loaded from checkpoint",
    "leiden_iters": "counter: community-detection local-move iterations dispatched",
    "null_sims_completed": "counter: null-model simulations finished",
    "mesh_fallbacks": "counter: sharded levels that fell back to single-chip",
    "silhouette_best": "gauge: last consensus silhouette",
    "compile_cache_enabled": "gauge: 1 when the persistent XLA cache is active",
    "compile_cache_entries": "gauge: cache-dir entries at enable time (warm-cache proxy)",
    "device_bytes_in_use": "gauge: jax device memory_stats() at record time",
    "device_peak_bytes_in_use": "gauge: peak device memory, when the backend reports it",
    "boot_chunk_seconds": "histogram: dispatch->fetch latency per computed boot chunk",
    "inflight_chunks": "gauge: high-water mark of concurrently in-flight pipelined chunks",
    "chunk_overlap_seconds": "histogram: per chunk, seconds between dispatch and the host blocking on its fetch",
    "phase_seconds": "histogram: wall seconds per closed top-level pipeline phase span",
    # serve/ — the online assignment subsystem
    "serve_latency_seconds": "histogram: submit -> result per request",
    # request-lifecycle decomposition (ISSUE 7): per request, these three sum
    # to serve_latency_seconds by construction (same clock reads)
    "queue_wait_seconds": "histogram: submit -> worker dequeue per request (time spent in the bounded queue)",
    "batch_wait_seconds": "histogram: worker dequeue -> batch dispatch per request (batch-formation wait)",
    "device_seconds": "histogram: batch dispatch -> results on host, per request (device + transfer share)",
    "queue_depth": "gauge: request-queue occupancy at last submit/dequeue",
    "batch_occupancy": "gauge: rows/bucket fill of the last micro-batch",
    "serve_compile": "counter: bucket-shape first dispatches (XLA compiles)",
    "serve_rejections": "counter: queue-full backpressure rejections (each RetryableRejection carries a retry_after_s hint from the observed drain rate)",
    "compile_cache_enable_calls": "counter: enable_persistent_cache invocations (idempotency telemetry)",
    # dispatch/compile accounting (utils/compile_cache.counting_jit, ISSUE 5)
    "device_dispatches": "counter: top-level pipeline executable launches (counting_jit-wrapped entry programs)",
    "executable_compiles": "counter: traces of top-level entry programs (one per shape bucket)",
    "donated_bytes": "counter: bytes of operand buffers donated for in-place executable updates",
    # resource profiling (obs/resource.py ResourceSampler, ISSUE 6)
    "host_rss_bytes": "gauge: host resident-set size at the last resource sample (/proc/self/statm)",
    "host_peak_rss_bytes": "gauge: peak host RSS watermark observed by the resource sampler",
    "resource_samples": "counter: resource-sampler ticks taken (host RSS + device memory reads)",
    # cost-model accounting (utils/compile_cache.counting_jit, ISSUE 6)
    "estimated_flops": "counter: summed one-execution XLA cost_analysis flops of compiled entry programs",
    "estimated_bytes_accessed": "counter: summed one-execution XLA cost_analysis bytes accessed of compiled entry programs",
    # registry self-observability (ISSUE 7 satellite): merge drops bucket
    # ladders on a bounds mismatch — previously silent, now counted
    "hist_merge_mismatch": "counter: histogram merges that dropped bucket counts on a bounds-ladder mismatch",
    # numerics observability (obs/fingerprint.py, ISSUE 8)
    "numerics_nonfinite": "counter: NaN/Inf values observed at numeric checkpoints (watch/audit watchdog)",
    "numerics_checkpoints": "counter: numeric checkpoint fingerprints recorded (audit mode)",
    # resilience layer (resilience/, ISSUE 10)
    "fault_injected": "counter: deliberately planted faults that fired (CCTPU_FAULT_INJECT; always 0 in production)",
    "retry_attempts": "counter: fault-site attempts retried after a failure (resilience/retry.py)",
    "retries_exhausted": "counter: fault-site calls that gave up after the last attempt (the original exception surfaced)",
    "retry_backoff_seconds": "histogram: per retried attempt, the backoff slept before it (capped exponential + seeded jitter)",
    "ckpt_quarantined": "counter: checkpoint chunks renamed aside as corrupt/unreadable at resume (recomputed, not resumed)",
    "serve_worker_restarts": "counter: serving worker threads restarted by the supervisor after an unexpected death",
    # SNN build observability (ISSUE 13): reverse-edge slot collisions in the
    # fixed-width [n, 2k] symmetrised graph — edges whose reverse copy lost
    # the at[].max slot race and contribute weight in one direction only
    "snn_rev_edges_dropped": "counter: SNN reverse edges dropped to slot collisions in the fixed-width symmetrised graph",
    # cross-process AOT executable cache (utils/compile_cache.py, ISSUE 13)
    "aot_cache_hits": "counter: serving executables deserialized from the AOT cache (warm start — no trace)",
    "aot_cache_misses": "counter: AOT cache lookups with no entry (cold start — trace + serialize)",
    "aot_cache_saves": "counter: compiled serving executables serialized into the AOT cache",
    "aot_fallbacks": "counter: present-but-unloadable AOT entries that fell back to trace (loud: warns per entry)",
    # failure-time observability (obs/flight.py + obs/alerts.py, ISSUE 14)
    "stalls_detected": "counter: watchdog deadline expiries (a watch scope ran past its armed deadline)",
    "postmortem_dumps": "counter: flight-recorder post-mortem dumps written (exception/signal/fail_all/retries_exhausted/stall)",
    "alerts_raised": "counter: SLO alert rule raise transitions (obs/alerts.py AlertEngine)",
    "alerts_active": "gauge: currently firing SLO alert rules (0 on a healthy replica — the /healthz drain signal)",
    # fleet layer (serve/router.py, ISSUE 18) — registry instruments are
    # label-less, so the per-replica gauges carry the *routed-to* replica's
    # state at admission; the full per-replica split is in
    # FleetRouter.health()["routed"] and the bench fleet_slo rung
    "fleet_requests_routed": "counter: requests admitted and routed to a replica by the FleetRouter",
    "fleet_rejections": "counter: fleet-wide rejections (every admitting replica rejected — true saturation)",
    "fleet_failovers": "counter: accepted requests orphaned by a replica death and re-queued for re-routing",
    "fleet_replica_unhealthy": "counter: admission passes that skipped a replica on a not-ok health scrape",
    "fleet_replicas": "gauge: replicas currently in rotation",
    "fleet_replica_queue_depth": "gauge: queue occupancy of the routed-to replica at admission",
    "fleet_replica_inflight": "gauge: in-flight requests of the routed-to replica at admission",
    "fleet_swaps": "counter: zero-downtime reference swaps completed (swap_reference)",
    "fleet_swap_compiles": "counter: fresh executable compiles during swap windows (pinned 0 when the AOT cache is warm)",
    "fleet_control_sheds": "counter: requests shed at the router door by an armed ControlPolicy under burn pressure",
    "fleet_control_decisions": "counter: ControlPolicy pressure-class transitions applied to a replica",
    # fleet-wide distributed tracing (ISSUE 19): hop chains are retained per
    # trace_id up to CCTPU_FLEET_TRACE_CAP; admissions past the cap still
    # serve (and still carry a trace_id) but record no chain
    "fleet_traces_dropped": "counter: admitted requests whose hop chain was not retained (past CCTPU_FLEET_TRACE_CAP)",
}

# Metrics registry names (counters, gauges, histograms).
METRIC_NAMES = frozenset(METRIC_HELP)

# Span attrs stamped by the ResourceSampler's span-close hook
# (obs/resource.py). tools/check_obs_schema.py validates the *_ATTR literals
# defined there against this set, both directions — a renamed watermark attr
# is a test failure, not a silently empty "== memory ==" table.
RESOURCE_SPAN_ATTRS = frozenset({
    "rss_peak_bytes",     # peak host RSS (bytes) observed while the span ran
    "device_peak_bytes",  # peak device bytes_in_use while the span ran
})

# Named numeric checkpoints (ISSUE 8): the points in the pipeline where
# obs/fingerprint.py stamps an array fingerprint under audit mode (and runs
# the NaN/Inf watchdog under watch). tools/check_obs_schema.py validates the
# ``*_CKPT`` literals in obs/fingerprint.py against this set, both
# directions, and that every checkpoint literal tools/parity_audit.py names
# is registered — a renamed checkpoint is a test failure, not a parity audit
# that silently stops covering a pipeline stage.
NUMERIC_CHECKPOINTS = frozenset({
    "norm",            # post-normalization expression matrix (dense path)
    "hvg",             # HVG-subset matrix that feeds PCA
    "pca",             # PCA embedding (the boot grid's input geometry)
    "boot_labels",     # per-chunk aligned bootstrap label rows
    "cocluster",       # streamed co-clustering count carries (agree+union)
    "consensus_dist",  # consensus distance matrix (dense) / kNN (blockwise)
    "labels",          # final labels (consensus-merged, then assignments)
})

# Span attrs stamped by obs/fingerprint.py (validated by
# tools/check_obs_schema.py against the ``*_ATTR`` literals there, both
# directions — same contract as RESOURCE_SPAN_ATTRS).
NUMERIC_SPAN_ATTRS = frozenset({
    "fingerprints",          # audit: {checkpoint: checksum} on the open span
    "numerics_nonfinite",    # watchdog: NaN/Inf count tagged on the span
})

# Named fault sites (ISSUE 10): the points where resilience/inject.py can
# plant a deterministic failure (CCTPU_FAULT_INJECT=<site>:<kind>[:<arg>])
# and resilience/retry.py wraps the work in the bounded-backoff policy.
# tools/check_obs_schema.py validates the ``*_SITE`` literals in
# resilience/inject.py against this set, both directions, and that every
# site literal tools/chaos_audit.py names is registered — a renamed site is
# a test failure, not a chaos audit that silently stops covering a failure
# mode.
FAULT_SITES = frozenset({
    "boot_chunk",     # bootstrap chunk dispatch (consensus/pipeline.py)
    "ckpt_write",     # checkpoint chunk save (utils/checkpoint.py; also the
                      # corrupt_bytes target — silent on-disk corruption)
    "ckpt_read",      # checkpoint chunk load at resume
    "null_chunk",     # null-simulation chunk dispatch (nulltest/null.py)
    "serve_batch",    # micro-batch device execution (serve/service.py)
    "serve_warmup",   # per-bucket warm-up compile dispatch
    "serve_worker",   # the serving worker loop itself (supervised restart)
})

# Deterministic work-ledger counters (ISSUE 12): the subset of METRIC_NAMES
# that measures *work dispatched*, not time — identical across reruns of the
# same seeded workload on any host, however contended. obs/ledger.py's
# WorkLedger harvests exactly these into RunRecord.work_ledger and the bench
# ``work_ledger`` block, and tools/bench_diff.py gates them exactly
# (``--gate work``: any counter regression fails regardless of wall noise).
# tools/check_obs_schema.py validates the ``*_WORK`` literals in
# obs/ledger.py against this set, both directions, that every name here is
# a registered metric (subset of METRIC_NAMES), and that bench.py's guarded
# fallback literals match obs/ledger.py — a renamed counter is a test
# failure, not a silently empty work gate.
WORK_LEDGER_COUNTERS = frozenset({
    "device_dispatches",        # top-level executable launches
    "executable_compiles",      # traces (one per shape bucket)
    "estimated_flops",          # summed XLA cost_analysis flops
    "estimated_bytes_accessed", # summed XLA cost_analysis bytes
    "donated_bytes",            # operand bytes donated in place
    "boots_completed",          # bootstraps actually computed
    "fault_injected",           # planted faults that fired (0 in production)
    "retry_attempts",           # fault-site attempts retried
    "retries_exhausted",        # fault-site calls that gave up
    "ckpt_quarantined",         # corrupt checkpoint chunks set aside
})

# Per-program cost-attribution vocabulary (ISSUE 16). PROGRAM_NAMES is the
# closed set of counting_jit-decorated entry points — the programs a
# ``program_profile`` block may name. tools/check_obs_schema.py
# (check_program_registry) scans the package for counting_jit decorators and
# validates both directions: an entry point not registered here fails lint
# (an unattributable program), and a registered name with no decorated
# definition fails lint (a ghost row the report would render forever).
PROGRAM_NAMES = frozenset({
    "_boot_batch",                       # consensus/pipeline.py boot hot path
    "_consensus_grid_from_knn",          # consensus/pipeline.py grid sweep
    "_accum_cocluster_counts",           # consensus/cocluster.py dense accum
    "_accum_sparse_cocluster_counts",    # consensus/cocluster.py sparse accum
    "_consensus_tail_sharded",           # parallel/step.py sharded tail
    "distributed_consensus_step",        # parallel/step.py distributed step
    "sharded_run_bootstraps",            # parallel/boots.py pmap boots
    "sharded_run_bootstraps_granular",   # parallel/boots.py granular boots
    "_null_stat_batch",                  # nulltest/null.py null statistics
    "_assign_batch",                     # serve/assign.py serving assignment
})

# Field names of one program_profile row (utils/compile_cache.py ``*_PROG``
# literals — validated there against this set, both directions).
PROGRAM_PROFILE_FIELDS = frozenset({
    "dispatches",       # executable launches attributed to the program
    "compiles",         # traces (one per fresh shape bucket)
    "est_flops",        # cost_analysis flops folded into the program's rows
    "est_bytes",        # cost_analysis bytes accessed, same fold
    "donated_bytes",    # operand bytes donated in place per dispatch
    "dispatch_wall_s",  # cumulative host-side wall around the dispatch call
})

# Span attrs stamped by consensus/pipeline.py on the candidates/cocluster
# spans (ISSUE 9 — the regime provenance tools/report.py's "== consensus =="
# table renders). tools/check_obs_schema.py validates the ``*_ATTR``
# literals there against this set, both directions.
CONSENSUS_SPAN_ATTRS = frozenset({
    "consensus_regime",   # which CONSENSUS_REGIMES entry assembled the consensus
    "candidate_m",        # sparse_knn: candidate-neighbour count per cell
    "accumulated_pairs",  # pairs the accumulator tracked (n*m sparse, n^2 dense)
    "pairs_ratio",        # accumulated_pairs / n^2 — the sub-quadratic ratio
    # ISSUE 13: SNN build provenance on the consensus_grid spans
    "snn_impl",              # which SNN_IMPLS entry built the rank weights
    "snn_rev_edges_dropped", # reverse-edge slot collisions summed over the run
    # ISSUE 20: Leiden local-move provenance on the consensus_grid spans
    "leiden_impl",           # which LEIDEN_IMPLS entry ran the k_ic sweep
})

# SNN rank-build implementations (ISSUE 13): the dispatch vocabulary of
# cluster/engine.resolve_snn_impl — "jax" is the lax.scan build (always
# available, the CPU/ledger baseline), "pallas" the fused VMEM kernel
# (ops/pallas_snn.py; TPU default, bit-identical by contract, probed once
# and degraded to "jax" on any lowering/runtime failure).
# tools/check_obs_schema.py validates the ``*_SNN_IMPL`` literals in
# ops/pallas_snn.py against this set, both directions — a renamed impl is a
# test failure, not a silently unreachable kernel.
SNN_IMPLS = frozenset({
    "jax",
    "pallas",
})

# Leiden local-move k_ic implementations (ISSUE 20): the dispatch vocabulary
# of cluster/engine.py::resolve_leiden_impl (explicit > CCTPU_LEIDEN_IMPL >
# backend default; CCTPU_NO_PALLAS honored, one-shot smoke probe degrades to
# "jax" on any lowering/runtime failure — the same contract as SNN_IMPLS).
# tools/check_obs_schema.py validates the ``*_LEIDEN_IMPL`` literals in
# ops/pallas_leiden.py against this set, both directions.
LEIDEN_IMPLS = frozenset({
    "jax",
    "pallas",
})

# Flight-recorder dump reasons (ISSUE 14): why obs/flight.py wrote a
# post-mortem. Stamped as the dump's ``reason`` field and on the
# ``postmortem_dump`` event, so tools/postmortem.py can render/diff dumps by
# failure class. tools/check_obs_schema.py validates the ``*_FLIGHT``
# literals in obs/flight.py against this set, both directions — a renamed
# reason is a test failure, not a dump a post-mortem tool can't classify.
FLIGHT_EVENT_KINDS = frozenset({
    "exception",           # unhandled exception (sys.excepthook chain)
    "signal",              # fatal signal (SIGTERM/SIGINT handler chain)
    "fail_all",            # serving gave up: AssignmentService._fail_all
    "retries_exhausted",   # a fault site surfaced its original exception
    "stall",               # the watchdog saw a deadline expire
    "manual",              # an explicit dump() call (tests, operators)
})

# Declarative SLO alert rules (ISSUE 14): the names obs/alerts.py evaluates
# over the metrics registries and fires as ``alert_raised``/``alert_cleared``
# events + the ``alerts_active`` gauge (surfaced in /healthz so a router can
# drain a sick replica). tools/check_obs_schema.py validates the ``*_ALERT``
# literals in obs/alerts.py against this set, both directions, and that
# every alert literal obs/flight.py, serve/service.py and the bench/audit
# tools name is registered — a renamed rule is a test failure, not a
# dashboard silently scraping a dead alert name.
ALERT_RULES = frozenset({
    "serve_p99_high",           # serve_latency_seconds p99 above its bound
    "serve_rejection_rate_high",  # windowed rejected/(rejected+served) rate
    "slo_burn_rate_high",       # error-budget burn multiple over the window
    "retries_exhausted_rising", # retries_exhausted moved within the window
    "aot_fallbacks_rising",     # aot_fallbacks moved within the window
})

# ---------------------------------------------------------------------------
# Environment-knob registry (ISSUE 15). Every ``CCTPU_*`` environment
# variable read anywhere in consensusclustr_tpu/, bench.py, or tools/ must
# have an entry here: (default-as-documented, one-line help). graftlint's
# GL002 rule enforces the contract both directions — a knob read in code but
# absent here fails lint, and a registered knob nothing reads fails lint —
# and the docs/quirks.md knob table is GENERATED from this dict
# (``python -m tools.graftlint --gen-env-docs``), so the 47-read-vs-19-
# documented drift this registry was built to close cannot reopen.
# Registering here is additive vocabulary, not a payload-shape change, so
# SCHEMA_VERSION stays 8 (the ISSUE 9/10/13 non-bump precedent).
ENV_KNOBS = {
    "CCTPU_ALERT_P99_S": (
        "30.0",
        "serve_p99_high alert threshold: p99 serve latency bound, seconds.",
    ),
    "CCTPU_ALERT_REJECT_RATE": (
        "0.05",
        "serve_rejection_rate_high alert threshold: windowed reject fraction.",
    ),
    "CCTPU_AOT_CACHE_DIR": (
        "~/.cache/consensusclustr_tpu/aot",
        "Directory for serialized AOT serving executables (warm starts).",
    ),
    "CCTPU_BENCH_CPU_RETRY": (
        "unset",
        "Internal bench.py flag marking the forced-CPU retry child process.",
    ),
    "CCTPU_BENCH_PROBE_BUDGET": (
        "240",
        "bench.py TPU-probe wall budget in seconds before falling back to CPU.",
    ),
    "CCTPU_BENCH_PROBE_S": (
        "0",
        "Internal bench.py handoff: parent probe seconds, re-read by the child.",
    ),
    "CCTPU_BENCH_PROBE_VERDICT": (
        "unset",
        "Internal bench.py handoff: parent probe verdict, re-read by the child.",
    ),
    "CCTPU_BOOTS_PER_PROGRAM": (
        "0",
        "Inner vmap width of _boot_batch: scan chunk/bpp groups per dispatch; 0 = one vmap.",
    ),
    "CCTPU_CHUNK_BYTES": (
        "6e9 on TPU, 2e9 on CPU",
        "Consensus chunk-planner memory budget in bytes.",
    ),
    "CCTPU_CKPT_CHUNK": (
        "32",
        "Bootstrap checkpoint chunk: replicates per checkpointed segment.",
    ),
    "CCTPU_COMPILE_CACHE_DIR": (
        "~/.cache/consensusclustr_tpu/xla",
        "Directory for the persistent XLA compilation cache.",
    ),
    "CCTPU_DENSE_CONSENSUS_LIMIT": (
        "16384",
        "Max n for the dense [n, n] consensus path; larger runs go blockwise.",
    ),
    "CCTPU_FAULT_INJECT": (
        "unset",
        "Fault-injection spec 'site:kind[:arg][,...]' planted at FAULT_SITES.",
    ),
    "CCTPU_FLEET_CONTROL": (
        "unset",
        "Truthy arms the fleet ControlPolicy (alert-driven adaptive batching/admission).",
    ),
    "CCTPU_FLEET_CONTROL_DEADLINE_MS": (
        "2.0",
        "Armed-control base batch-gather deadline in milliseconds.",
    ),
    "CCTPU_FLEET_REPLICAS": (
        "2",
        "Default FleetRouter replica count (build_fleet).",
    ),
    "CCTPU_FLEET_TRACE_CAP": (
        "100000",
        "Fleet hop-chain retention cap (trace_ids past it count fleet_traces_dropped).",
    ),
    "CCTPU_FLEET_TRACE_PATH": (
        "unset",
        "When set, fleet loadgen/chaos runs write the merged FleetRecord incident artifact here.",
    ),
    "CCTPU_FORCE_CPU": (
        "unset",
        "Truthy pins JAX to the CPU backend before first device touch.",
    ),
    "CCTPU_GRID_IMPL": (
        "fused",
        "Boot fan-out program: 'fused' (vmapped-k) or 'looped' (parity oracle).",
    ),
    "CCTPU_LEIDEN_IMPL": (
        "pallas on TPU, jax elsewhere",
        "Leiden local-move k_ic backend: 'pallas' (fused kernel) or 'jax' (slab scan).",
    ),
    "CCTPU_LOG_LEVEL": (
        "WARNING",
        "Package logger level (name or int) for the consensusclustr logger.",
    ),
    "CCTPU_MAX_CHUNK": (
        "8 on TPU, 64 elsewhere",
        "Consensus chunk-planner cap on replicates per chunk.",
    ),
    "CCTPU_NO_AOT_CACHE": (
        "unset",
        "Truthy disables the on-disk AOT serving-executable cache.",
    ),
    "CCTPU_NO_COMPILE_CACHE": (
        "unset",
        "Truthy disables the persistent XLA compilation cache.",
    ),
    "CCTPU_NO_COST_ANALYSIS": (
        "unset",
        "Truthy skips XLA cost analysis in counting_jit (flops/bytes attrs).",
    ),
    "CCTPU_NO_FLIGHT": (
        "unset",
        "Truthy disables the flight recorder (no post-mortem dumps).",
    ),
    "CCTPU_NO_PALLAS": (
        "unset",
        "Truthy kill switch: force XLA fallbacks over all Pallas kernels.",
    ),
    "CCTPU_NUMERICS": (
        "off",
        "Numerics-fingerprint level: off, light, or paranoid checkpoints.",
    ),
    "CCTPU_NUMERICS_INJECT": (
        "unset",
        "Numeric-drift injection spec 'bf16:<checkpoint>' for parity audits.",
    ),
    "CCTPU_PALLAS_INTERPRET": (
        "unset",
        "Truthy runs Pallas kernels in interpret mode (CPU-debuggable).",
    ),
    "CCTPU_PALLAS_VARIANT": (
        "mxu",
        "Cocluster Pallas kernel variant: 'mxu' (dot-general) or 'vpu'.",
    ),
    "CCTPU_PIPELINE_DEPTH": (
        "2",
        "Double-buffered bootstrap pipeline depth (in-flight chunk count).",
    ),
    "CCTPU_POSTMORTEM_DIR": (
        "unset",
        "Directory for timestamped flight-recorder post-mortem dumps.",
    ),
    "CCTPU_POSTMORTEM_PATH": (
        "unset",
        "Exact file path for the flight-recorder post-mortem dump.",
    ),
    "CCTPU_PROFILE_HZ": (
        "off",
        "Sampling-profiler rate in Hz; 0/off/none disables (the default).",
    ),
    "CCTPU_PROFILE_MAX_NODES": (
        "4096",
        "Cap on distinct folded stacks the profiler retains; extras drop.",
    ),
    "CCTPU_RESOURCE_MAX_SAMPLES": (
        "4096",
        "Ring-buffer cap on retained resource samples (trace stream).",
    ),
    "CCTPU_RESOURCE_SAMPLE_MS": (
        "off",
        "Resource-sampler period in ms; 0/off/none disables (the default).",
    ),
    "CCTPU_RETRY_ATTEMPTS": (
        "3",
        "Max attempts per fault site before retries_exhausted surfaces.",
    ),
    "CCTPU_RETRY_BASE_S": (
        "0.02",
        "Base backoff delay in seconds (exponential, jittered, capped).",
    ),
    "CCTPU_RETRY_DEADLINE_S": (
        "unset",
        "Optional wall deadline in seconds across all attempts at a site.",
    ),
    "CCTPU_RUN_RECORD": (
        "unset",
        "Path to write the per-run provenance record JSON (api.run_record).",
    ),
    "CCTPU_SERVE_BUCKETS": (
        "powers of two up to max batch",
        "Comma-separated compiled batch-bucket ladder for serving.",
    ),
    "CCTPU_SERVE_MAX_BATCH": (
        "256",
        "Largest serving micro-batch (top of the bucket ladder).",
    ),
    "CCTPU_SERVE_METRICS_PORT": (
        "off",
        "Serving /metrics + /healthz port; 0 = ephemeral, off/none = no socket.",
    ),
    "CCTPU_SERVE_QUEUE_DEPTH": (
        "64",
        "Serving admission-queue depth; beyond it requests are rejected.",
    ),
    "CCTPU_SERVE_WORKER_RESTARTS": (
        "16",
        "Worker-supervisor restart budget before the service fails all.",
    ),
    "CCTPU_SHARDED_PALLAS": (
        "unset",
        "'1' enables the per-shard Pallas cocluster path under pmap.",
    ),
    "CCTPU_SNN_IMPL": (
        "pallas on TPU, jax elsewhere",
        "SNN rank-scan backend: 'pallas' (fused kernel) or 'jax' (scan build).",
    ),
    "CCTPU_SPAN_ANNOTATE": (
        "unset",
        "Truthy mirrors obs spans into jax.profiler trace annotations.",
    ),
    "CCTPU_STALL_FACTOR": (
        "8.0",
        "Stall-watchdog deadline multiplier over the observed p99.",
    ),
    "CCTPU_STALL_FLOOR_S": (
        "120.0",
        "Stall-watchdog minimum deadline in seconds (cold-start floor).",
    ),
    "CCTPU_SWEEP_MAX": (
        "8",
        "tools/tpu_chunk_sweep.py ceiling on the swept chunk sizes.",
    ),
}
