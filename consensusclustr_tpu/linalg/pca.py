"""Truncated PCA via randomized SVD with implicit centring/scaling operators.

Equivalent of irlba::prcomp_irlba as used at reference R/consensusClust.R:339,
:369 and :790 (truncated PCA of the HVG-subset normalised matrix with per-gene
centring and scaling), and of the pcNum selection rules:

  * "find"/elbow path (:337-365): 50-PC decomposition, then
    pcNum = max(first k with cum-sdev fraction > pcVar, 5).
  * numeric pcNum > 30 silently re-enters the "find" path (:338) — replicated
    deliberately, see docs/quirks.md item 3.
  * "getDenoisedPCs" path (:321-335): Poisson technical-variance model, keep
    PCs covering the biological variance (scran::getDenoisedPCs capability).

TPU-first: the centred/scaled matrix A = (X - mu) / sigma is never
materialised; every product folds the centring into the matmul
(A @ M = X @ (M/sigma) - 1 (mu/sigma)^T M). Randomized SVD (Halko et al.)
with q power iterations is all large-matmul work for the MXU, unlike the
reference's Lanczos iteration which is a sequential chain of matvecs.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple, Union

import jax
import jax.numpy as jnp


class PCAResult(NamedTuple):
    scores: jax.Array    # [n_cells, k]  (U * S, == prcomp's $x)
    sdev: jax.Array      # [k]           (singular values / sqrt(n-1))
    loadings: jax.Array  # [n_genes, k]  (V, == prcomp's $rotation)


def _stats(x, center: bool, scale: bool):
    mu = jnp.mean(x, axis=0) if center else jnp.zeros((x.shape[1],), x.dtype)
    if scale:
        # ddof=1 to match R's sd()
        n = x.shape[0]
        var = jnp.sum((x - mu[None, :]) ** 2, axis=0) / jnp.maximum(n - 1, 1)
        sigma = jnp.sqrt(var)
        sigma = jnp.where(sigma > 1e-8, sigma, 1.0)
    else:
        sigma = jnp.ones((x.shape[1],), x.dtype)
    return mu, sigma


@functools.partial(jax.jit, static_argnames=("k", "center", "scale", "n_oversample", "n_power_iters"))  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
def truncated_pca(
    x: jax.Array,
    k: int,
    *,
    center: bool = True,
    scale: bool = True,
    key: jax.Array = None,
    n_oversample: int = 10,
    n_power_iters: int = 2,
) -> PCAResult:
    """Randomized truncated SVD of the implicitly centred/scaled [n, g] matrix.

    Note: unlike the reference, `scale` is gated on `scale` — the reference
    gates it on `center` (R/consensusClust.R:339/:369; quirk 5).
    """
    x = jnp.asarray(x, jnp.float32)
    n, g = x.shape
    k = min(k, min(n, g))
    r = min(k + n_oversample, min(n, g))
    if key is None:
        key = jax.random.key(0)

    mu, sigma = _stats(x, center, scale)
    mu_s = mu / sigma  # centring vector in the scaled space

    def a_mat(m):  # A @ m, m: [g, r]
        return x @ (m / sigma[:, None]) - jnp.ones((n, 1), x.dtype) * (mu_s @ m)[None, :]

    def at_mat(m):  # A^T @ m, m: [n, r]
        return (x.T @ m) / sigma[:, None] - mu_s[:, None] * jnp.sum(m, axis=0)[None, :]

    omega = jax.random.normal(key, (g, r), x.dtype)
    y = a_mat(omega)
    q, _ = jnp.linalg.qr(y)
    for _ in range(n_power_iters):
        z, _ = jnp.linalg.qr(at_mat(q))
        q, _ = jnp.linalg.qr(a_mat(z))

    b = at_mat(q).T  # [r, g] = Q^T A
    ub, s, vt = jnp.linalg.svd(b, full_matrices=False)
    u = q @ ub
    scores = u[:, :k] * s[None, :k]
    sdev = s[:k] / jnp.sqrt(jnp.maximum(n - 1, 1))
    return PCAResult(scores=scores, sdev=sdev, loadings=vt[:k].T)


@functools.partial(jax.jit, static_argnames=("center", "scale"))  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
def standardization_stats(
    x: jax.Array, center: bool = True, scale: bool = True
) -> Tuple[jax.Array, jax.Array]:
    """(mu, sigma) of the implicit standardization ``A = (x - mu) / sigma``
    that :func:`truncated_pca` applies — the frozen statistics a serving-time
    projection (serve/assign.py) needs to place NEW rows into the same PC
    space as the fitted loadings. Matches ``_stats`` exactly (ddof=1 sd,
    near-zero sigmas clamped to 1)."""
    x = jnp.asarray(x, jnp.float32)
    return _stats(x, center, scale)


def project_onto_loadings(
    x: jax.Array, loadings: jax.Array, mu: jax.Array, sigma: jax.Array
) -> jax.Array:
    """Scores of new rows under a fitted PCA: ``((x - mu) / sigma) @ V``.

    For the fitted matrix itself this reproduces ``PCAResult.scores``
    (U S = A V); for unseen rows it is the out-of-sample projection used by
    reference mapping."""
    x = jnp.asarray(x, jnp.float32)
    return ((x - mu[None, :]) / sigma[None, :]) @ loadings


def choose_pc_num(sdev50: jax.Array, pc_var: float = 0.2, floor: int = 5) -> int:
    """Elbow rule (reference :356): smallest k with
    cumsum(sdev[1:k]) / sum(sdev[1:50]) > pc_var, floored at 5."""
    sdev50 = jnp.asarray(sdev50)
    frac = jnp.cumsum(sdev50) / jnp.maximum(jnp.sum(sdev50), 1e-12)
    k = int(jnp.argmax(frac > pc_var)) + 1
    return max(k, floor)


def denoised_pc_num(
    x_norm: jax.Array,
    counts: jax.Array,
    size_factors: jax.Array,
    sdev50_unscaled: jax.Array,
    max_pcs: int = 50,
    design: jax.Array = None,
) -> int:
    """scran getDenoisedPCs capability (reference :321-335): keep the number
    of PCs whose variance sums to the estimated biological variance.

    `sdev50_unscaled` must come from a PCA of the *unscaled* centred
    log-expression (scran operates on unscaled variances), so PC variances and
    the per-gene variance decomposition share units.

    Technical per-gene variance of y = log1p(c/sf) with c ~ Poisson(mu_g sf_j)
    by the delta method at the mean: Var(y | g, j) ~ mu_g / (sf_j (1+mu_g)^2),
    where mu_g is the per-gene rate (mean of counts/sf), then averaged over
    cells.

    `design` ([n, p] covariate matrix, no intercept column): per-gene total
    variance becomes the RESIDUAL variance after projecting out intercept +
    design, with matching ddof — the reference passes its varsToRegress model
    matrix into modelGeneVarByPoisson the same way (:325-331), so covariate-
    driven variance does not masquerade as biology.
    """
    x_norm = jnp.asarray(x_norm, jnp.float32)
    counts = jnp.asarray(counts, jnp.float32)
    sf = jnp.asarray(size_factors, jnp.float32)[:, None]
    n = x_norm.shape[0]
    if design is not None:
        design = jnp.asarray(design, jnp.float32)
        x_full = jnp.concatenate([jnp.ones((n, 1), jnp.float32), design], axis=1)
        q, _ = jnp.linalg.qr(x_full)
        resid = x_norm - q @ (q.T @ x_norm)
        dof = max(n - x_full.shape[1], 1)
        total_var = jnp.sum(resid * resid, axis=0) / dof
    else:
        total_var = jnp.var(x_norm, axis=0, ddof=1)
    mu = jnp.mean(counts / sf, axis=0)[None, :]  # per-gene rate, [1, g]
    tech = jnp.mean((mu / sf) / jnp.square(1.0 + mu), axis=0)
    bio_total = jnp.sum(jnp.maximum(total_var - tech, 0.0))
    pc_var = sdev50_unscaled**2
    covered = jnp.cumsum(pc_var)
    k = int(jnp.argmax(covered >= bio_total)) + 1
    if float(covered[-1]) < float(bio_total):
        k = int(pc_var.shape[0])
    return max(min(k, max_pcs), 5)


def pca_for_config(
    x_norm: jax.Array,
    pc_num: Union[str, int],
    pc_var: float,
    *,
    center: bool = True,
    scale: bool = True,
    key: jax.Array = None,
    counts: jax.Array = None,
    size_factors: jax.Array = None,
    design: jax.Array = None,
) -> Tuple[jax.Array, int, PCAResult]:
    """Full pcNum-selection + PCA flow of reference :321-382.

    `design` reaches the getDenoisedPCs variance decomposition (reference
    :325-331 passes the varsToRegress model matrix). Returns
    (scores[:, :pc_num], pc_num, full PCAResult).
    """
    n = x_norm.shape[0]
    needs_find = (isinstance(pc_num, str)) or (int(pc_num) > 30)  # :338 override
    if needs_find:
        k50 = min(50, min(n, x_norm.shape[1]))
        res = truncated_pca(x_norm, k50, center=center, scale=scale, key=key)
        if (
            pc_num == "getDenoisedPCs"
            and counts is not None
            and size_factors is not None
            and n > 400
        ):
            # scran's variance decomposition lives in unscaled log-expression
            # units, so the PC spectrum for the denoised rule must too.
            if scale:
                res_u = truncated_pca(x_norm, k50, center=center, scale=False, key=key)
                sdev_u = res_u.sdev
            else:
                sdev_u = res.sdev
            chosen = denoised_pc_num(
                x_norm, counts, size_factors, sdev_u, design=design
            )
            if chosen > 30:
                # the reference's :338 numeric>30 override also swallows the
                # getDenoisedPCs result (quirks item 3) — replicate
                chosen = choose_pc_num(res.sdev, pc_var)
        else:
            chosen = choose_pc_num(res.sdev, pc_var)
        chosen = min(chosen, k50)
        return res.scores[:, :chosen], chosen, res
    chosen = int(pc_num)
    chosen = min(chosen, min(n, x_norm.shape[1]))
    res = truncated_pca(x_norm, chosen, center=center, scale=scale, key=key)
    return res.scores, chosen, res
