"""Pairwise distance helpers shared by the API and split-testing layers.

The reference computes Euclidean cell-cell distances with stats::dist
(reference R/consensusClust.R:510, :523, :987); here the host-side numpy
variant serves the tiny irregular paths while the big O(n^2) passes stay on
device (consensus.cocluster, cluster.knn).
"""

from __future__ import annotations

import numpy as np


def euclidean_distance_matrix(x: np.ndarray) -> np.ndarray:
    """[n, n] Euclidean distances from an [n, d] embedding."""
    x = np.asarray(x)
    sq = np.sum(x * x, axis=1)
    d2 = sq[:, None] - 2.0 * (x @ x.T) + sq[None, :]
    return np.sqrt(np.maximum(d2, 0.0))
