from consensusclustr_tpu.linalg.pca import truncated_pca, choose_pc_num, pca_for_config
