"""Top-level API (L8): ingestion, orchestration, iteration, output assembly.

Equivalent of the reference's exported ``consensusClust``
(reference R/consensusClust.R:122-632, SURVEY §3.1/§3.4): validate inputs,
adapt container objects, normalise + select HVGs + regress, PCA with pcNum
selection, bootstrap consensus clustering, statistical significance testing,
optional recursive subclustering, and result assembly (assignments +
dendrogram + clustree-style hierarchy table).

Division of labor (SURVEY §7.1): everything per-cell/per-gene/per-boot runs on
device inside the lower layers; this module is the irregular host control —
adapters, the recursion over clusters (:542-578), label composition
(parent_child strings, :575-577), and the final dendrogram/hierarchy outputs
(:580-632).

Input orientation: cells x genes (the AnnData/Python convention), transposed
from the reference's R genes x cells. Adapters accept dense numpy, scipy
sparse, or AnnData-like objects (duck-typed on .X/.obs/.var/.obsm/.layers so
the package has no hard anndata dependency).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from consensusclustr_tpu.config import ClusterConfig
from consensusclustr_tpu.obs import (
    RunRecord,
    Tracer,
    maybe_span,
    record_device_memory,
)
from consensusclustr_tpu.obs.fingerprint import (
    HVG_CKPT,
    LABELS_CKPT,
    NORM_CKPT,
    PCA_CKPT,
    attach_numerics,
    numeric_checkpoint,
)
from consensusclustr_tpu.consensus.pipeline import ConsensusResult, consensus_cluster
from consensusclustr_tpu.hierarchy.clustree import hierarchy_edges, hierarchy_table
from consensusclustr_tpu.hierarchy.dendro import Dendrogram, determine_hierarchy
from consensusclustr_tpu.linalg.distance import euclidean_distance_matrix as _euclidean
from consensusclustr_tpu.linalg.pca import pca_for_config
from consensusclustr_tpu.nulltest.splits import test_splits
from consensusclustr_tpu.prep.hvg import select_hvgs
from consensusclustr_tpu.prep.regress import regress_features
from consensusclustr_tpu.prep.sizefactors import compute_size_factors
from consensusclustr_tpu.prep.transform import shifted_log
from consensusclustr_tpu.utils.log import LevelLog
from consensusclustr_tpu.utils.rng import cluster_key, depth_key, root_key

# The significance gate's small-cluster threshold is hardcoded 50 in the
# reference (:521), independent of the minSize parameter.
_GATE_SMALL_CLUSTER = 50
# Above this, the gate's dendrogram streams cluster-pair distance sums
# instead of materialising the [n, n] Euclidean matrix (same threshold as
# consensus/pipeline.py's DENSE_CONSENSUS_LIMIT).
_DENSE_GATE_LIMIT = 16384


@dataclasses.dataclass
class ClusterResult:
    """Result contract mirroring the reference's return list (:628-632).

    assignments: per-cell lineage labels ("2", "2_1", "2_1_3", ...); all-"1"
    when no significant structure. cluster_dendrogram: tree over the final
    labels from co-clustering (bootstrapped) or PCA distances; None for
    single-cluster results. clustree: hierarchy table + edges (only when
    iterated with >1 lineage depth, :603-606).
    """

    assignments: np.ndarray
    cluster_dendrogram: Optional[Dendrogram] = None
    clustree: Optional[Dict[str, np.ndarray]] = None
    clustree_edges: Optional[List[tuple]] = None
    log: Optional[LevelLog] = None
    # Observability: span tree + events + metrics for this run (obs/).
    # Serialize with run_record.write(path); render with tools/report.py.
    run_record: Optional[RunRecord] = None
    # Serving state (serve/artifact.ReferenceFit): frozen normalization +
    # PCA components + serve-path embedding + per-cluster stability, captured
    # when the run was fitted from raw counts. export_reference(result, path)
    # turns it into a versioned on-disk bundle; None for pca=/norm_counts=
    # -only runs (nothing to freeze).
    fit: Optional[Any] = None

    @property
    def n_clusters(self) -> int:
        return len(set(self.assignments.tolist()))


@dataclasses.dataclass
class _Ingested:
    """Normalised view of any supported input container."""

    counts: Optional[np.ndarray]          # [n_cells, n_genes] raw counts
    norm_counts: Optional[np.ndarray]     # [n_cells, n_genes] if provided
    pca: Optional[np.ndarray]             # [n_cells, d] if provided
    variable_features: Optional[np.ndarray]  # bool mask [n_genes] or names
    covariates: Optional[np.ndarray]      # [n_cells, n_cov] float design
    gene_names: Optional[np.ndarray]
    scale_data: bool = False              # Seurat scale.data semantics (:223-228)


def _densify(x) -> np.ndarray:
    """Dense float32 array from dense/sparse/CountMatrix input."""
    if hasattr(x, "indptr") and hasattr(x, "dense"):  # io.CountMatrix
        return x.dense()
    if hasattr(x, "toarray"):  # scipy sparse
        x = x.toarray()
    return np.asarray(x, dtype=np.float32)


def _is_sparse(x) -> bool:
    from consensusclustr_tpu.prep.sparse import is_sparse

    return x is not None and is_sparse(x)


def _sparse_or_dense(x):
    """Keep sparse counts sparse (scipy CSR) through ingestion; the dense
    materialisation happens only after the HVG subset (prep/sparse.py — the
    reference's dgCMatrix-end-to-end memory profile, SURVEY §2.2)."""
    from consensusclustr_tpu.prep.sparse import is_sparse, to_csr

    if is_sparse(x) or (
        hasattr(x, "indptr") and hasattr(x, "col") and hasattr(x, "val")
    ):
        return to_csr(x)  # scipy sparse or io.CountMatrix
    return np.asarray(x, dtype=np.float32)


def _dense_cols(x, mask: Optional[np.ndarray]) -> np.ndarray:
    """Dense float32 [n, sum(mask)] column subset of dense or sparse counts."""
    if _is_sparse(x):
        sub = x[:, mask] if mask is not None else x
        return np.asarray(sub.todense(), np.float32)
    x = np.asarray(x, np.float32)
    return x[:, mask] if mask is not None else x


def _encode_covariates(cols: List[np.ndarray]) -> np.ndarray:
    """Stack covariate columns, one-hot (drop-first) for non-numeric ones.

    The reference passes metadata columns straight into model.matrix-style
    lm fits (:209-214, 827-835); numeric columns pass through, factors become
    dummy indicators.
    """
    out = []
    for col in cols:
        col = np.asarray(col)
        if np.issubdtype(col.dtype, np.number):
            out.append(col.astype(np.float32).reshape(len(col), -1))
        else:
            levels = np.unique(col)
            for lv in levels[1:]:  # drop first level; intercept is implicit
                out.append((col == lv).astype(np.float32).reshape(-1, 1))
    if not out:
        return None
    return np.concatenate(out, axis=1)


def _is_anndata_like(obj) -> bool:
    return hasattr(obj, "X") and hasattr(obj, "obs") and hasattr(obj, "var")


def _ingest_anndata(adata, cfg: ClusterConfig) -> _Ingested:
    """AnnData adapter, mirroring the Seurat/SCE extraction semantics
    (reference :198-271, SURVEY §3.2):

      * counts from layers['counts'] when present, else .raw.X, else .X;
      * norm_counts from layers['logcounts'|'data'] (logcounts == the SCE
        adapter's source, :265-267);
      * HVGs from var['highly_variable'] (:199-206, :242-249);
      * PCA embedding from obsm['X_pca'] (:217-220, :260-262);
      * vars_to_regress names resolve against obs columns (:209-214, :251-257).
    """
    layers = getattr(adata, "layers", {}) or {}
    # Assay-scoped lookup (reference :231 `obj[[assay]]$counts`): layers named
    # "<assay>_counts"/"<assay>_data"/"<assay>_scale_data" take precedence
    # over the generic names, so multi-assay AnnData objects (CITE-seq etc.)
    # can address one assay the way Seurat's `assay` argument does.
    a = cfg.assay
    counts = None
    for name in (f"{a}_counts", "counts"):
        if name in layers:
            counts = _sparse_or_dense(layers[name])
            break
    if counts is None and getattr(adata, "raw", None) is not None:
        counts = _sparse_or_dense(adata.raw.X)
    norm = None
    scale_data = False
    # assay-scoped names beat ALL generic names before the scale/norm branch
    # split, so another assay's generic scale_data cannot shadow the
    # requested assay's own normalised layer
    tiers = (
        (f"{a}_scale_data", (f"{a}_logcounts", f"{a}_data")),
        ("scale_data", ("logcounts", "data")),
    )
    for scale_name, norm_names in tiers:
        if scale_name in layers:
            # Seurat scale.data semantics (:223-228): already HVG-subset and
            # regressed, so _level skips both steps downstream
            norm = _densify(layers[scale_name])
            scale_data = True
            break
        hit = next((nm for nm in norm_names if nm in layers), None)
        if hit is not None:
            norm = _sparse_or_dense(layers[hit])
            break
    if counts is None:
        x = _densify(adata.X)
        # Heuristic mirrored from Seurat's data-vs-counts fallback (:223-231):
        # integral non-negative X is counts, otherwise treat as normalised.
        if np.all(x >= 0) and np.allclose(x, np.round(x)):
            counts = x
        else:
            norm = x if norm is None else norm

    hvg = None
    if cfg.variable_features is not None:
        hvg = np.asarray(cfg.variable_features)
    elif "highly_variable" in getattr(adata, "var", {}):
        mask = np.asarray(adata.var["highly_variable"], dtype=bool)
        if mask.any():
            hvg = mask

    cov = None
    if cfg.vars_to_regress is not None:
        if isinstance(cfg.vars_to_regress, (list, tuple)) and all(
            isinstance(v, str) for v in cfg.vars_to_regress
        ):
            cov = _encode_covariates(
                [np.asarray(adata.obs[v]) for v in cfg.vars_to_regress]
            )
        else:
            cov = np.asarray(cfg.vars_to_regress, dtype=np.float32)
            cov = cov.reshape(len(cov), -1)

    pca = None
    obsm = getattr(adata, "obsm", {}) or {}
    if "X_pca" in obsm:
        pca = np.asarray(obsm["X_pca"], dtype=np.float32)

    gene_names = None
    if hasattr(adata, "var_names"):
        gene_names = np.asarray(adata.var_names)
    return _Ingested(
        counts=counts, norm_counts=norm, pca=pca, variable_features=hvg,
        covariates=cov, gene_names=gene_names, scale_data=scale_data,
    )


def _ingest(data, cfg: ClusterConfig, norm_counts=None, pca=None) -> _Ingested:
    if _is_anndata_like(data):
        ing = _ingest_anndata(data, cfg)
        if norm_counts is not None:
            ing.norm_counts = _sparse_or_dense(norm_counts)
        if pca is not None:
            ing.pca = np.asarray(pca, np.float32)
        return ing

    counts = _sparse_or_dense(data) if data is not None else None
    cov = None
    if cfg.vars_to_regress is not None:
        cov = np.asarray(cfg.vars_to_regress, dtype=np.float32)
        cov = cov.reshape(len(cov), -1)
    hvg = np.asarray(cfg.variable_features) if cfg.variable_features is not None else None
    gene_names = getattr(data, "gene_names", None)  # io.CountMatrix carries names
    return _Ingested(
        counts=counts,
        norm_counts=_sparse_or_dense(norm_counts) if norm_counts is not None else None,
        pca=np.asarray(pca, np.float32) if pca is not None else None,
        variable_features=hvg,
        covariates=cov,
        gene_names=gene_names,
    )


def _resolve_hvg_mask(
    spec: Optional[np.ndarray], gene_names: Optional[np.ndarray], n_genes: int
) -> Optional[np.ndarray]:
    """Boolean HVG mask from a mask, an index list, or gene names."""
    if spec is None:
        return None
    spec = np.asarray(spec)
    if spec.dtype == bool:
        return spec
    if np.issubdtype(spec.dtype, np.integer):
        mask = np.zeros(n_genes, dtype=bool)
        mask[spec] = True
        return mask
    if gene_names is None:
        raise ValueError("named variable_features need gene names (AnnData input)")
    return np.isin(gene_names, spec)


def _single_cluster(n: int) -> np.ndarray:
    return np.full(n, "1", dtype=object)


def _skip_first_regression(cfg: ClusterConfig, ing: "_Ingested") -> bool:
    """First-level regression gating (reference :306-319): True, or a list of
    covariate names that must cover ALL of vars_to_regress for the skip to
    apply (the reference's `!all(colnames %in% skipFirstRegression)` test)."""
    skip = cfg.skip_first_regression
    if isinstance(skip, bool):
        return skip
    if isinstance(skip, str):  # a single covariate name, not a char sequence
        skip = [skip]
    names = (
        list(cfg.vars_to_regress)
        if isinstance(cfg.vars_to_regress, (list, tuple))
        and all(isinstance(v, str) for v in cfg.vars_to_regress)
        else None
    )
    if names is None:
        # covariates given as a raw design matrix: any non-empty skip list
        # can only mean "skip" (there are no names to match)
        return len(list(skip)) > 0
    return len(list(skip)) > 0 and all(v in list(skip) for v in names)


def _interactive_pc_num(norm, cfg: ClusterConfig, key, input_fn=input) -> Optional[int]:
    """Interactive pcNum selection (reference :342-346): render the elbow,
    prompt for a PC count; empty/invalid answer falls back to the elbow rule.

    Headless processes (no tty) skip the prompt entirely. The elbow is saved
    to ./pca_elbow.png (the reference shows a ggplot; a saved file works for
    remote TPU sessions).
    """
    import sys

    from consensusclustr_tpu.linalg.pca import truncated_pca

    if not sys.stdin.isatty() and input_fn is input:
        return None
    k50 = min(50, min(norm.shape))
    res = truncated_pca(
        jnp.asarray(norm, jnp.float32), k50, center=cfg.center, scale=cfg.scale,
        key=cluster_key(key, "elbow"),
    )
    try:
        from consensusclustr_tpu.viz import plot_elbow

        plot_elbow(np.asarray(res.sdev), path="pca_elbow.png")
        where = " (elbow saved to pca_elbow.png)"
    except Exception:  # graftlint: noqa[GL007] elbow plot is best-effort decoration of an interactive prompt
        where = ""
    answer = input_fn(f"Number of PCs to use{where} [enter = auto]: ").strip()
    try:
        chosen = int(answer)
    except ValueError:
        return None
    return chosen if 0 < chosen <= k50 else None


def _valid_k(k_num: Sequence[int], n: int) -> Tuple[int, ...]:
    """Drop neighbourhood sizes that exceed the cell count (the reference's
    tryCatch would absorb the resulting igraph error into a single-cluster
    fallback, :392-399; we degrade per-k instead)."""
    ks = tuple(int(k) for k in k_num if int(k) < n)
    return ks


def _level(
    key: jax.Array,
    ing: _Ingested,
    cfg: ClusterConfig,
    log: LevelLog,
    depth: int,
) -> Tuple[np.ndarray, Optional[ConsensusResult], Optional[np.ndarray], Optional[dict]]:
    """One level of the pipeline (reference :274-539): returns
    (labels [n] of str, consensus result or None, pca or None, serving
    capture dict or None — depth-1 frozen preprocessing state for
    serve/artifact.ReferenceFit).

    Span-wrapped: each level is one "level" span; recursion nests child
    levels under the parent's tree in the RunRecord."""
    with maybe_span(log, "level", depth=depth):
        return _level_impl(key, ing, cfg, log, depth)


def _level_impl(
    key: jax.Array,
    ing: _Ingested,
    cfg: ClusterConfig,
    log: LevelLog,
    depth: int,
) -> Tuple[np.ndarray, Optional[ConsensusResult], Optional[np.ndarray], Optional[dict]]:
    n = (
        ing.counts.shape[0]
        if ing.counts is not None
        else (ing.norm_counts.shape[0] if ing.norm_counts is not None else ing.pca.shape[0])
    )
    log.event("level_start", depth=depth, n_cells=n)

    k_list = _valid_k(cfg.k_num, n)
    if n < 4 or not k_list:
        log.event("too_small", n_cells=n)
        return _single_cluster(n), None, None, None
    cfg = cfg.replace(k_num=k_list)

    # Sparse counts stay scipy CSR through size factors + HVG selection
    # (prep/sparse.py); dense counts go straight to device.
    sparse_counts = _is_sparse(ing.counts)
    counts_dev = (
        jnp.asarray(ing.counts, jnp.float32)
        if ing.counts is not None and not sparse_counts
        else None
    )
    sf = None

    # Provided-PCA gate, decided up front: when honored, the whole
    # normalise/regress chain would only feed a PCA we never compute, so it
    # is skipped (its other consumer, the null test, needs raw HVG counts
    # only). Quirk 4: object/user PCA is honored iff pc_num is numeric <= 30.
    use_given_pca = (
        ing.pca is not None
        and not isinstance(cfg.pc_num, str)
        and int(cfg.pc_num) <= 30
    )

    with maybe_span(log, "prep"):
        # --- normalise (:274-288) ---------------------------------------------
        if use_given_pca:
            norm = None
        elif ing.norm_counts is not None:
            norm = (
                ing.norm_counts
                if _is_sparse(ing.norm_counts)
                else jnp.asarray(ing.norm_counts, jnp.float32)
            )
        else:
            if ing.counts is None:
                raise ValueError(
                    "need counts or norm_counts (or a precomputed pca with a "
                    "numeric pc_num <= 30)"
                )
            if sparse_counts:
                from consensusclustr_tpu.prep.sparse import (
                    compute_size_factors_sparse,
                    sparse_shifted_log,
                )

                sf_np = compute_size_factors_sparse(ing.counts, cfg.size_factors)
                sf = jnp.asarray(sf_np)
                norm = sparse_shifted_log(ing.counts, sf_np)  # stays CSR
            else:
                sf = compute_size_factors(counts_dev, cfg.size_factors)
                norm = shifted_log(counts_dev, sf)

        # numerics checkpoint: post-normalization, pre-HVG. Sparse norm stays
        # host CSR until after the HVG subset, so it is fingerprinted at the
        # hvg checkpoint instead (docs/perf.md "Auditing numerical parity").
        if norm is not None and not _is_sparse(norm):
            numeric_checkpoint(log, NORM_CKPT, norm)

        # --- HVG selection (:291-304) -----------------------------------------
        n_genes = ing.counts.shape[1] if ing.counts is not None else (
            norm.shape[1] if norm is not None else 0
        )
        hvg_mask = _resolve_hvg_mask(ing.variable_features, ing.gene_names, n_genes)
        if hvg_mask is None and not ing.scale_data and ing.counts is not None:
            n_hvg = min(cfg.n_var_features, n_genes)
            if sparse_counts:
                from consensusclustr_tpu.prep.sparse import sparse_select_hvgs

                hvg_mask = sparse_select_hvgs(ing.counts, n_hvg)
            else:
                hvg_mask = np.asarray(select_hvgs(counts_dev, n_hvg))
        if hvg_mask is not None:
            mask_np = np.asarray(hvg_mask)
            if norm is not None and not ing.scale_data:
                # scale.data input skips the norm HVG subset — Seurat already did
                # (:301); the null-test counts are HVG-subset either way (:526)
                norm = norm[:, mask_np]
            counts_hvg = _dense_cols(ing.counts, mask_np) if ing.counts is not None else None
        else:
            counts_hvg = _dense_cols(ing.counts, None) if ing.counts is not None else None
        # the dense device path starts here: post-HVG the matrix is
        # [n, n_var_features] and safely materialisable
        if _is_sparse(norm):
            norm = jnp.asarray(np.asarray(norm.todense(), np.float32))
        # numerics checkpoint: the HVG-subset matrix that feeds PCA (the
        # sparse path fingerprints here too — post-densify is the first
        # point its values live on device)
        if norm is not None:
            numeric_checkpoint(log, HVG_CKPT, norm)
        log.event("prep", n_genes_kept=int(norm.shape[1]) if norm is not None else 0)

    # --- covariate regression (:306-319) ----------------------------------
    skip_here = (
        depth == 1 and _skip_first_regression(cfg, ing)
    ) or ing.scale_data  # Seurat scale.data is already regressed (:314-319)
    if ing.covariates is not None and norm is not None and not skip_here:
        with maybe_span(log, "regress"):
            counts_for_glm = (
                jnp.asarray(counts_hvg, jnp.float32) if counts_hvg is not None else None
            )
            sf_glm = sf
            if (
                sf_glm is None
                and counts_for_glm is not None
                and cfg.regress_method in ("glmGamPoi", "poisson")
            ):
                # norm was supplied pre-normalised, so no size factors were
                # computed this level; the GLM paths still need a depth offset
                # (docs/quirks.md D9) — derive library-size factors.
                if sparse_counts:
                    from consensusclustr_tpu.prep.sparse import (
                        compute_size_factors_sparse,
                    )

                    sf_glm = jnp.asarray(
                        compute_size_factors_sparse(ing.counts, "libsize")
                    )
                else:
                    sf_glm = compute_size_factors(counts_dev, "libsize")
            norm = regress_features(
                norm, jnp.asarray(ing.covariates, jnp.float32),
                counts=counts_for_glm, method=cfg.regress_method,
                size_factors=sf_glm,
            )
            log.event("regressed", method=cfg.regress_method)

    # --- PCA + pcNum (:321-382) -------------------------------------------
    # The elbow prompt covers both "find" and the numeric pc_num > 30 case —
    # the latter silently re-enters the find path (reference :338, quirk 3),
    # so an interactive user should get the same say over the outcome.
    with maybe_span(log, "pca"):
        wants_find = cfg.pc_num == "find" or (
            not isinstance(cfg.pc_num, str) and int(cfg.pc_num) > 30
        )
        if (
            cfg.interactive
            and depth == 1
            and wants_find
            and norm is not None
            and not use_given_pca
        ):
            chosen = _interactive_pc_num(norm, cfg, key)
            if chosen is not None:
                cfg = cfg.replace(pc_num=chosen)
                log.event("interactive_pc_num", pc_num=chosen)
        pca_res = None
        if use_given_pca:
            pc_num = min(int(cfg.pc_num), ing.pca.shape[1])
            pca = np.asarray(ing.pca[:, :pc_num], np.float32)
        else:
            try:
                scores, pc_num, pca_res = pca_for_config(
                    norm, cfg.pc_num, cfg.pc_var,
                    center=cfg.center, scale=cfg.scale,
                    key=cluster_key(key, "pca"),
                    counts=(jnp.asarray(counts_hvg, jnp.float32) if counts_hvg is not None else None),
                    size_factors=sf,
                    design=(
                        jnp.asarray(ing.covariates, jnp.float32)
                        if ing.covariates is not None
                        else None
                    ),
                )
                pca = np.asarray(scores)
            except Exception as e:  # PCA failure => single cluster (:368-379)
                log.event("pca_failed", error=str(e))
                return _single_cluster(n), None, None, None
            if not np.all(np.isfinite(pca)):
                log.event("pca_failed", error="non-finite scores")
                return _single_cluster(n), None, None, None
        # Shape bucketing of the PC axis (SURVEY §7.3 item 2): pad to a multiple
        # of 4 with zero columns — inert for every distance/silhouette downstream
        # (exact), but subproblems with nearby elbow choices share jit caches.
        # pc_num itself stays UNpadded: the null sims extract pc_num genuine PCs
        # from simulated data, so feeding them the padded width would compare an
        # effectively lower-dimensional observed statistic against a higher-
        # dimensional null — anti-conservative. Only the boot grid (the hot
        # path) sees the bucketed width.
        if cfg.shape_buckets and depth > 1:
            d_pad = -(-int(pc_num) // 4) * 4
            pca = np.asarray(pca, np.float32)
            if d_pad != pca.shape[1]:
                pca = np.concatenate(
                    [pca, np.zeros((pca.shape[0], d_pad - pca.shape[1]), np.float32)],
                    axis=1,
                )
        # numerics checkpoint: the embedding every downstream boot sees (the
        # deliberate --inject bf16:pca target in tools/parity_audit.py's
        # self-test lands here)
        numeric_checkpoint(log, PCA_CKPT, pca)
        log.event("pca", pc_num=int(pc_num))

    # --- serving capture (serve/, ISSUE 3) --------------------------------
    # Depth-1 runs fitted from raw counts freeze the preprocessing a query
    # cell needs (HVG subset, normalization rule, PCA components) and the
    # reference embedding re-computed through that FROZEN path — the exact
    # arrays serve/assign.py applies at request time, so reference and
    # query geometry agree by construction. Cheap: two stats reductions and
    # one [n, g_hvg] @ [g_hvg, d] projection.
    fit_capture = None
    if (
        depth == 1
        and counts_hvg is not None
        and norm is not None
        and not use_given_pca
        and pca_res is not None
    ):
        from consensusclustr_tpu.linalg.pca import standardization_stats
        from consensusclustr_tpu.serve.assign import embed_reference_counts

        mu_fit, sigma_fit = standardization_stats(norm, cfg.center, cfg.scale)
        loadings_fit = np.asarray(pca_res.loadings[:, : int(pc_num)], np.float32)
        libsize_mean = float(np.mean(np.sum(counts_hvg, axis=1)))
        libsize_mean = libsize_mean if libsize_mean > 0 else 1.0
        fit_capture = {
            "embedding": embed_reference_counts(
                counts_hvg, np.asarray(mu_fit), np.asarray(sigma_fit),
                loadings_fit, libsize_mean,
            ),
            "mu": np.asarray(mu_fit, np.float32),
            "sigma": np.asarray(sigma_fit, np.float32),
            "loadings": loadings_fit,
            "libsize_mean": libsize_mean,
            "pc_num": int(pc_num),
            "n_genes_full": int(n_genes),
            "hvg_indices": (
                np.flatnonzero(np.asarray(hvg_mask)) if hvg_mask is not None else None
            ),
            "gene_names": (
                np.asarray(ing.gene_names)[np.asarray(hvg_mask)]
                if ing.gene_names is not None and hvg_mask is not None
                else None
            ),
        }

    # --- consensus clustering (L5, :388-511) ------------------------------
    with maybe_span(log, "consensus"):
        cons = consensus_cluster(cluster_key(key, "consensus"), pca, cfg, log=log)
    labels = np.asarray([str(l + 1) for l in cons.labels], dtype=object)

    # --- significance gate (:514-539) -------------------------------------
    # On bucket-padded subproblems the gate and null test see ONLY the real
    # cells: duplicate rows would inflate cluster sizes and silhouettes,
    # bypassing tests that the unpadded subproblem would run. The test's
    # outcome is a per-cluster label mapping, so it extends to duplicates.
    with maybe_span(log, "significance"):
        n_real = int(cfg.n_real_cells) if cfg.n_real_cells else n
        labels_real = labels[:n_real]
        sizes = np.unique(labels_real, return_counts=True)[1]
        any_small = bool((sizes < _GATE_SMALL_CLUSTER).any())  # quirk 7: "any"
        if n_real == n:
            sil_gate = cons.silhouette
        elif not cfg.test_significance:
            # the gate is disabled: don't pay a full silhouette pass over the
            # real cells just to decide whether to log the skip event — treat
            # the gate as firing (slightly over-logs on bucketed sub-levels)
            sil_gate = -np.inf
        else:
            from consensusclustr_tpu.nulltest.splits import labelled_silhouette

            sil_gate = labelled_silhouette(pca[:n_real], labels_real, cfg.max_clusters)
        gate_fires = len(sizes) > 1 and (
            sil_gate <= cfg.silhouette_thresh or any_small
        )
        if not cfg.test_significance and gate_fires:
            # only when a test was actually suppressed — a single cluster or a
            # high-silhouette result would not have been tested anyway
            log.event("null_test_skipped", reason="disabled by config")
        if cfg.test_significance and gate_fires:
            if counts_hvg is None:
                log.event("null_test_skipped", reason="no raw counts available")
            else:
                # gate on n_real, not the bucket-padded count: the dendrogram
                # below is built on pca[:n_real] (ADVICE r3)
                dense_gate = (
                    cfg.dense_consensus
                    if cfg.dense_consensus is not None
                    else n_real <= _DENSE_GATE_LIMIT
                )
                if dense_gate:
                    dend = determine_hierarchy(_euclidean(pca[:n_real]), labels_real)
                else:
                    # scale regime: the gate's PCA-distance dendrogram (:523)
                    # streams cluster-pair sums instead of the [n, n] matrix
                    from consensusclustr_tpu.consensus.blockwise import (
                        euclidean_cluster_distance,
                    )
                    from consensusclustr_tpu.hierarchy.dendro import (
                        _sorted_unique,
                        dendrogram_from_cluster_distance,
                    )

                    uniq = _sorted_unique(labels_real)
                    code_of = {u: i for i, u in enumerate(uniq)}
                    codes = np.asarray([code_of[l] for l in labels_real], np.int32)
                    cmat = euclidean_cluster_distance(pca[:n_real], codes)
                    dend = dendrogram_from_cluster_distance(cmat, uniq)
                tested = test_splits(
                    counts_hvg[:n_real], pca[:n_real], dend, labels_real,
                    pc_num=int(pc_num), k_num=cfg.k_num, alpha=cfg.alpha,
                    silhouette_thresh=cfg.silhouette_thresh,
                    covariates=(
                        ing.covariates[:n_real]
                        if ing.covariates is not None
                        else None
                    ),
                    n_sims=cfg.n_null_sims,
                    key=cluster_key(key, "nulltest"),
                    test_separately=cfg.test_splits_separately,
                    max_clusters=cfg.max_clusters, log=log,
                    cluster_fun=cfg.cluster_fun, compute_dtype=cfg.compute_dtype,
                )
                # merges act on whole clusters, so the outcome is a label map
                mapping = {}
                for old, new in zip(labels_real, tested):
                    mapping.setdefault(old, new)
                labels = np.asarray(
                    [mapping.get(l, l) for l in labels], dtype=object
                )
                labels = _relabel(labels)
    log.event("level_done", depth=depth, n_clusters=len(set(labels.tolist())))
    return labels, cons, pca, fit_capture


_BUCKET_BASE = 64
_BUCKET_RATIO = 1.3


def _bucket_size(n: int) -> int:
    """Smallest size in the geometric bucket series >= n (SURVEY §7.3 item 2:
    pad-to-bucket sizing bounds XLA recompilation across iterate levels)."""
    s = _BUCKET_BASE
    while s < n:
        s = int(np.ceil(s * _BUCKET_RATIO))
    return s


def _relabel(labels: np.ndarray) -> np.ndarray:
    """Compact surviving labels to "1".."C" in first-seen order (the reference
    re-factors assignments after merges)."""
    labels = np.asarray(labels, dtype=object)
    mapping: Dict[Any, str] = {}
    out = np.empty(len(labels), dtype=object)
    for i, l in enumerate(labels):
        if l not in mapping:
            mapping[l] = str(len(mapping) + 1)
        out[i] = mapping[l]
    return out


def _iterate(
    key: jax.Array,
    counts: np.ndarray,
    covariates: Optional[np.ndarray],
    labels: np.ndarray,
    cfg: ClusterConfig,
    log: LevelLog,
    depth: int,
) -> np.ndarray:
    """Recursive subclustering (reference :542-578): re-run the full pipeline
    inside each surviving cluster with > min_size cells, HVGs and PCs
    recomputed per cluster, labels composed parent_child."""
    out = labels.copy()
    uniq = sorted(set(labels.tolist()), key=str)
    for ci, parent in enumerate(uniq):
        mask = labels == parent
        n_c = int(mask.sum())
        if n_c <= cfg.min_size:
            continue
        # Shape bucketing (SURVEY §7.3 item 2): pad the subproblem's cell
        # count to the geometric bucket by cyclic duplication — the same
        # with-replacement duplication the bootstrap already performs, so
        # every downstream kernel handles it natively — and slice the child
        # labels back. Same-bucket subclusters then share every jit cache.
        # n_real_cells makes the sub-level's significance gate + null test
        # evaluate only the real rows.
        if cfg.shape_buckets:
            n_pad = _bucket_size(n_c)
            pad_idx = np.arange(n_pad) % n_c
        else:
            n_pad = n_c
            pad_idx = np.arange(n_c)
        sub_cfg = cfg.replace(
            variable_features=None, depth=depth + 1,
            n_real_cells=(n_c if n_pad != n_c else None),
        )
        sub_counts = counts[mask][pad_idx]
        sub_cov = (
            covariates[mask][pad_idx] if covariates is not None else None
        )
        sub_ing = _Ingested(
            counts=sub_counts,
            norm_counts=None, pca=None, variable_features=None,
            covariates=sub_cov,
            gene_names=None,
        )
        sub_key = depth_key(key, depth + 1, ci)
        sub_log = log.child()
        try:
            child, _, _, _ = _level(sub_key, sub_ing, sub_cfg, sub_log, depth + 1)
            child = child[:n_c]
            if len(set(child.tolist())) > 1:
                child = _iterate(
                    sub_key, counts[mask],
                    covariates[mask] if covariates is not None else None,
                    child, sub_cfg, sub_log, depth + 1,
                )
        except Exception as e:
            # failed child => parent keeps its label (reference sentinel :572,
            # rebuilt as an explicit status per quirks item 12)
            log.event("subcluster_failed", parent=str(parent), error=str(e))
            continue
        if len(set(child.tolist())) > 1:
            out[mask] = np.asarray(
                [f"{parent}_{c}" for c in child], dtype=object
            )
    return out


def consensus_clust(
    counts=None,
    *,
    norm_counts=None,
    pca=None,
    config: Optional[ClusterConfig] = None,
    **params,
) -> ClusterResult:
    """Bootstrapped consensus clustering with statistical significance testing.

    Public API mirroring the reference export (NAMESPACE:3; :122). `counts`
    may be a dense [n_cells, n_genes] array, scipy sparse matrix, or an
    AnnData-like object; keyword `params` mirror the reference's arguments
    snake_cased (see ClusterConfig).

    Note on ``iterate=True``: by default (``shape_buckets=True``) recursive
    subproblems are padded to ~1.3x geometric size buckets by cyclically
    duplicating cells, so sub-level size factors/HVGs/PCA see up to ~30%
    duplicated rows — a deliberate deviation from the reference's exact
    per-subcluster statistics that bounds XLA recompilation (docs/quirks.md
    D7). The significance gate and null test always evaluate real cells
    only. Pass ``shape_buckets=False`` for exact per-subcluster statistics
    at the cost of one compile per distinct subproblem shape (cheap on CPU,
    expensive on TPU).

    Returns ClusterResult(assignments, cluster_dendrogram, clustree) per the
    reference's result contract (SURVEY §8.3).
    """
    from consensusclustr_tpu.utils.backend import default_backend
    from consensusclustr_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()
    cfg = (config or ClusterConfig()).replace(**params) if params else (config or ClusterConfig())
    # CCTPU_SPAN_ANNOTATE=1 mirrors every span into a
    # jax.profiler.TraceAnnotation so the phase names appear inside device
    # traces captured with utils.profiling.device_trace.
    tracer = Tracer(
        progress=cfg.progress,
        annotate=bool(os.environ.get("CCTPU_SPAN_ANNOTATE")),
    )
    # Numerics observability (obs/fingerprint.py): off unless cfg.numerics /
    # CCTPU_NUMERICS asks — with no monitor attached every
    # numeric_checkpoint call in the pipeline returns before touching (or
    # even materialising) its array, so the default path stays
    # dispatch-identical to a build without the layer.
    attach_numerics(tracer, cfg.numerics)
    # Work ledger (obs/ledger.py, ISSUE 12): always on — one dict
    # subtraction per root span buys the deterministic counter block every
    # RunRecord.work_ledger and bench rung gates on.
    from consensusclustr_tpu.obs.ledger import attach_ledger

    attach_ledger(tracer)
    # Flight recorder + SLO alert engine (obs/flight.py / obs/alerts.py,
    # ISSUE 14): the recorder is on by default — bounded rings that only
    # ever WRITE on failure (unhandled exception, SIGTERM/SIGINT, retry
    # exhaustion, stall) — and the alert engine evaluates its rules at
    # record time below. CCTPU_NO_FLIGHT=1 disarms recorder + watchdog.
    from consensusclustr_tpu.obs.alerts import attach_alerts
    from consensusclustr_tpu.obs.flight import attach_flight

    attach_flight(tracer)
    attach_alerts(tracer)
    log = LevelLog(enabled=cfg.progress, tracer=tracer)
    key = root_key(cfg.seed)

    # Resource profiling (obs/resource.py): off unless cfg.resource_sample_ms
    # / CCTPU_RESOURCE_SAMPLE_MS turns it on. The sampler covers the WHOLE
    # run (every top-level span gets watermark attrs) and its series lands in
    # the RunRecord below; the finally guarantees the daemon thread never
    # outlives a failed run.
    from consensusclustr_tpu.obs.resource import start_for as _start_sampler

    sampler = _start_sampler(tracer, cfg.resource_sample_ms)
    # Sampling profiler (obs/profiler.py, ISSUE 16): off unless
    # cfg.profile_hz / CCTPU_PROFILE_HZ arms it. Samples are tagged with
    # each thread's open-span path and the folded hot stacks land in the
    # RunRecord (schema v9); an armed profiler also rides any flight-
    # recorder post-mortem written while the run is live.
    from consensusclustr_tpu.obs.profiler import start_profiler_for

    profiler = start_profiler_for(tracer, cfg.profile_hz)
    # Fault injection (resilience/inject.py, ISSUE 10): cfg.fault_inject
    # plants a deterministic fault spec for exactly this run's duration;
    # None is inert (env-planted CCTPU_FAULT_INJECT faults still apply).
    from consensusclustr_tpu.resilience.inject import fault_scope

    try:
        with fault_scope(cfg.fault_inject):
            return _consensus_clust_run(
                counts, norm_counts, pca, cfg, tracer, log, key, sampler
            )
    finally:
        if sampler is not None:
            sampler.stop()
        if profiler is not None:
            profiler.stop()


def _consensus_clust_run(
    counts, norm_counts, pca, cfg, tracer, log, key, sampler
) -> ClusterResult:
    """Body of :func:`consensus_clust` (split out so the resource sampler's
    start/stop brackets the whole run without re-indenting the pipeline)."""
    from consensusclustr_tpu.utils.backend import default_backend

    # Per-phase stall watchdog (obs/flight.py, ISSUE 14): deadlines derive
    # from the live phase_seconds histogram (p99 x CCTPU_STALL_FACTOR) with
    # the cfg/env floor; expiry dumps all-thread stacks + a stall_detected
    # event but never kills the phase — detection, not enforcement. Inert
    # under CCTPU_NO_FLIGHT=1.
    from consensusclustr_tpu.obs.flight import stall_watch

    _phase_hist = lambda: tracer.metrics.histograms.get("phase_seconds")  # noqa: E731

    with tracer.span("ingest"), stall_watch(
        log, "ingest", hist=_phase_hist(), floor_s=cfg.stall_floor_s
    ):
        ing = _ingest(counts, cfg, norm_counts=norm_counts, pca=pca)
    labels, cons, pca_used, fit_capture = _level(key, ing, cfg, log, depth=cfg.depth)
    n = len(labels)

    if cfg.iterate and len(set(labels.tolist())) > 1 and ing.counts is not None:
        with tracer.span("iterate"), stall_watch(
            log, "iterate", hist=_phase_hist(), floor_s=cfg.stall_floor_s
        ):
            labels = _iterate(
                key, ing.counts, ing.covariates, labels, cfg, log, cfg.depth
            )

    # --- output assembly at depth 1 (:580-632) ----------------------------
    with tracer.span("assemble"), stall_watch(
        log, "assemble", hist=_phase_hist(), floor_s=cfg.stall_floor_s
    ):
        dend = None
        if len(set(labels.tolist())) > 1 and cons is not None and pca_used is not None:
            if cons.jaccard_dist is not None:
                dend = determine_hierarchy(cons.jaccard_dist, labels)
            elif getattr(cons, "sparse", None) is not None:
                # sparse_knn regime (ISSUE 9): the restricted counts are in
                # hand, so the cluster-pair dendrogram distances cost one
                # O(n·m) segment-sum — no [n, n] pass, no tile re-stream
                from consensusclustr_tpu.consensus.merge import (
                    restricted_cluster_distance,
                )
                from consensusclustr_tpu.hierarchy.dendro import (
                    _sorted_unique,
                    dendrogram_from_cluster_distance,
                )

                uniq = _sorted_unique(np.asarray(labels))
                code_of = {u: i for i, u in enumerate(uniq)}
                codes = np.asarray([code_of[l] for l in labels], np.int32)
                cmat = restricted_cluster_distance(
                    cons.sparse.agree, cons.sparse.union,
                    cons.sparse.cand_idx, codes, len(uniq),
                )
                dend = dendrogram_from_cluster_distance(cmat, uniq)
            elif cons.boot_labels is not None:
                # blockwise regime: the cell-cell matrix never existed; stream
                # the cluster-pair mean co-clustering distances instead (:621)
                from consensusclustr_tpu.consensus.blockwise import (
                    cocluster_cluster_distance,
                )
                from consensusclustr_tpu.hierarchy.dendro import (
                    _sorted_unique,
                    dendrogram_from_cluster_distance,
                )

                uniq = _sorted_unique(np.asarray(labels))
                code_of = {u: i for i, u in enumerate(uniq)}
                codes = np.asarray([code_of[l] for l in labels], np.int32)
                cmat = cocluster_cluster_distance(
                    cons.boot_labels, codes, cfg.max_clusters,
                    use_pallas=cfg.use_pallas,
                )
                dend = dendrogram_from_cluster_distance(cmat, uniq)
            else:
                dend = determine_hierarchy(_euclidean(pca_used), labels)
        elif len(set(labels.tolist())) <= 1:
            log.event("failed_test")  # the reference's message("Failed Test") :613

        tree = edges = None
        if cfg.iterate and any("_" in str(l) for l in labels):
            tree = hierarchy_table(labels)
            edges = hierarchy_edges(labels)

        # serving state: attach per-cluster bootstrap stability (the mean
        # pairwise-Rand self-agreement across boots, the diagonal of the
        # merge layer's stability matrix) to the frozen preprocessing
        # capture — assign_cells reports it as per-neighbour confidence.
        fit = None
        if fit_capture is not None:
            from consensusclustr_tpu.serve.artifact import (
                ReferenceFit,
                leaf_label_table,
            )

            leaf = leaf_label_table(labels)
            stability = np.ones(len(leaf), np.float32)
            stability_source = None
            if (
                cons is not None
                and getattr(cons, "sparse", None) is not None
                and len(leaf) > 1
            ):
                # sparse_knn regime: the stability diagonal comes straight
                # from the restricted counts (mean within-cluster candidate
                # -pair co-clustering rate) — O(n·m), no per-boot Rand pass
                from consensusclustr_tpu.consensus.merge import (
                    stability_from_restricted_counts,
                )

                code_of = {s: i for i, s in enumerate(leaf)}
                codes = np.asarray(
                    [code_of[str(l)] for l in labels], np.int32
                )
                stability = np.clip(
                    stability_from_restricted_counts(
                        cons.sparse.agree, cons.sparse.union,
                        cons.sparse.cand_idx, codes, len(leaf),
                    ),
                    0.0, 1.0,
                ).astype(np.float32)
                stability_source = "cocluster_restricted"
            elif cons is not None and cons.boot_labels is not None and len(leaf) > 1:
                from consensusclustr_tpu.consensus.merge import stability_matrix

                code_of = {s: i for i, s in enumerate(leaf)}
                codes = np.asarray(
                    [code_of[str(l)] for l in labels], np.int32
                )
                c_pad = max(cfg.max_clusters, 1 << (len(leaf) - 1).bit_length())
                sm = np.asarray(
                    stability_matrix(
                        codes, np.asarray(cons.boot_labels, np.int32),
                        c_pad, cfg.max_clusters,
                    )
                )
                stability = np.clip(
                    np.diagonal(sm)[: len(leaf)], 0.0, 1.0
                ).astype(np.float32)
                stability_source = "boot_rand"
            fit = ReferenceFit(
                stability=stability, stability_source=stability_source,
                **fit_capture,
            )

    # numerics checkpoint: the run's final assignments (string lineage
    # labels fingerprinted through their sorted-unique integer codes — two
    # regimes agreeing here agree on every cell's cluster)
    numeric_checkpoint(
        log, LABELS_CKPT,
        lambda: np.unique(labels, return_inverse=True)[1].astype(np.int32),
    )

    # --- run record (obs/): span tree + events + metrics snapshot ---------
    if sampler is not None:
        sampler.stop()  # closing watermark lands in the record's series
    profiler = getattr(tracer, "profiler", None)
    if profiler is not None:
        profiler.stop()  # folded stacks stay readable for the record
    record_device_memory(tracer.metrics)
    run_record = RunRecord.from_tracer(
        tracer, config=cfg, backend=default_backend()
    )
    record_path = cfg.run_record_path or os.environ.get("CCTPU_RUN_RECORD")
    if record_path:
        try:
            run_record.write(record_path)
        except OSError as e:
            log.event("run_record_write_failed", path=record_path, error=str(e))

    return ClusterResult(
        assignments=labels,
        cluster_dendrogram=dend,
        clustree=tree,
        clustree_edges=edges,
        log=log,
        run_record=run_record,
        fit=fit,
    )


def export_reference(result: ClusterResult, path: str, *, config=None):
    """Persist a fitted run as a servable reference bundle (serve/artifact).

    ``result`` must come from a ``consensus_clust(counts=...)`` run (raw
    counts are what the frozen serving normalization is derived from).
    Returns the in-memory ReferenceArtifact; the bundle at ``path`` is a
    directory of ``arrays.npz`` + ``manifest.json``, schema-versioned and
    checksummed — ``load_reference``/``assign_cells`` refuse corrupted or
    unknown-schema bundles loudly.
    """
    from consensusclustr_tpu.serve.artifact import export_reference as _export

    return _export(result, path, config=config)


def assign_cells(reference, counts, *, mode: str = "robust", **kwargs):
    """Map query cells onto an exported reference (serve/assign).

    ``reference``: a ReferenceArtifact or bundle path; ``counts``: raw query
    counts [q, genes] over the full reference gene space or its HVG subset.
    ``mode="granular"`` additionally returns labels at every hierarchy
    level. One-shot path — for sustained traffic use
    serve.service.AssignmentService (micro-batching, warm-up, backpressure).
    """
    from consensusclustr_tpu.serve.assign import assign_cells as _assign

    return _assign(reference, counts, mode=mode, **kwargs)


def build_fleet(reference, n_replicas=None, *, config=None, control=None,
                **svc_kwargs):
    """Serve a reference from N replicas behind a FleetRouter (serve/fleet).

    Health-keyed least-loaded admission, failover re-routing, zero-downtime
    ``swap_reference`` version swaps, and (opt-in via ``control=True`` /
    ``ClusterConfig.fleet_control`` / ``CCTPU_FLEET_CONTROL``) alert-driven
    adaptive batching. The router duck-types the single-service surface:
    ``submit`` / ``assign`` / ``health`` / ``close``. See docs/perf.md
    "Running a fleet".
    """
    from consensusclustr_tpu.serve.fleet import build_fleet as _build

    return _build(
        reference, n_replicas, config=config, control=control, **svc_kwargs
    )
