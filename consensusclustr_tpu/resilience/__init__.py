"""Resilience layer (ISSUE 10): fault injection, retry policy, recovery.

PR 8 built the *values* axis of trust (numeric fingerprints, regime-parity
audits); this package builds the *failures* axis — the same "auditable, not
eyeballed" contract applied to crashes. Named fault sites
(``obs/schema.py::FAULT_SITES``) can plant deterministic, seeded failures
under the opt-in ``CCTPU_FAULT_INJECT`` hook (off by default, zero-cost when
off, exactly like numerics), a bounded retry policy with deterministic
backoff wraps every site, and ``tools/chaos_audit.py`` proves that a run
which survived injected faults produces bit-identical labels to a clean run.
"""

from consensusclustr_tpu.resilience.inject import (  # noqa: F401
    FaultInjector,
    InjectedFault,
    active_injector,
    clear_fault,
    fault_scope,
    install_fault,
    maybe_corrupt_file,
    maybe_fail,
    parse_fault_spec,
)
from consensusclustr_tpu.resilience.retry import (  # noqa: F401
    RetryPolicy,
    resolve_retry_policy,
    retry_call,
)
