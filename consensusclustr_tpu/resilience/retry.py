"""Bounded retry with deterministic backoff (ISSUE 10 tentpole).

One policy object, one entry point: :func:`retry_call` wraps the package's
fault sites — bootstrap/null chunk dispatch (``ChunkPipeline.dispatch``),
checkpoint read/write (consensus/pipeline.py around utils/checkpoint.py),
and serving warm-up / micro-batch execution (serve/service.py). Contract:

  * bounded attempts (``attempts`` total, so ``attempts - 1`` retries);
  * exponential backoff ``base_s * 2**(attempt-1)`` capped at
    ``max_backoff_s``, with *deterministic seeded jitter* — the jitter
    fraction for (seed, site, attempt) is a pure function, so two runs of
    the same workload sleep identically and a chaos audit is reproducible
    to the wall clock;
  * an optional overall ``deadline_s`` — a site that keeps failing slowly
    stops retrying when the budget is spent even if attempts remain;
  * a call that exhausts retries surfaces the ORIGINAL (last) exception —
    never a wrapper — preserving the drain semantics every call site
    already has;
  * observability: ``retry_attempts`` / ``retries_exhausted`` counters, the
    ``retry_backoff_seconds`` histogram, and ``retry`` /
    ``retries_exhausted`` span events naming the site (obs/schema.py).

Injection integration: each attempt runs ``inject.maybe_fail(site)`` before
the wrapped work, so raise-kind plants fire exactly once per attempt and a
transient plant (raise_once) is consumed by attempt 1 with attempt 2
recovering. With nothing planted that check is one dict lookup — the
zero-overhead-when-off contract is pinned alongside numerics'.

Only ``Exception`` is retried: ``KeyboardInterrupt`` / ``SystemExit`` (and
any other ``BaseException``) propagate immediately — a retry loop must never
swallow an operator's ^C.
"""

from __future__ import annotations

import dataclasses
import os
import random
import time
from typing import Any, Callable, Optional

from consensusclustr_tpu.obs.metrics import MetricsRegistry
from consensusclustr_tpu.obs.tracer import metrics_of, tracer_of
from consensusclustr_tpu.resilience.inject import maybe_fail

DEFAULT_RETRY_ATTEMPTS = 3
DEFAULT_RETRY_BASE_S = 0.02
DEFAULT_RETRY_MAX_BACKOFF_S = 2.0
DEFAULT_RETRY_JITTER = 0.5


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Immutable retry knobs; build through :func:`resolve_retry_policy`."""

    attempts: int = DEFAULT_RETRY_ATTEMPTS
    base_s: float = DEFAULT_RETRY_BASE_S
    max_backoff_s: float = DEFAULT_RETRY_MAX_BACKOFF_S
    deadline_s: Optional[float] = None
    jitter: float = DEFAULT_RETRY_JITTER
    seed: int = 0

    def backoff_s(self, site: str, attempt: int) -> float:
        """Sleep before retry #``attempt`` (1-based): capped exponential with
        deterministic jitter — a pure function of (seed, site, attempt), so
        identical runs back off identically (no thundering-herd sync either:
        different sites jitter differently)."""
        raw = min(self.base_s * (2.0 ** (attempt - 1)), self.max_backoff_s)
        u = random.Random(f"{self.seed}:{site}:{attempt}").random()
        return raw * (1.0 + self.jitter * u)


def resolve_retry_policy(
    attempts: Optional[int] = None,
    base_s: Optional[float] = None,
    deadline_s: Optional[float] = None,
    seed: int = 0,
) -> RetryPolicy:
    """Explicit args > ``CCTPU_RETRY_ATTEMPTS`` / ``CCTPU_RETRY_BASE_S`` /
    ``CCTPU_RETRY_DEADLINE_S`` env > defaults (3 attempts, 20 ms base).
    ``attempts=1`` is the fail-fast policy — the wrapper degenerates to a
    plain call (plus the injection check)."""
    if attempts is None:
        attempts = int(
            os.environ.get("CCTPU_RETRY_ATTEMPTS", DEFAULT_RETRY_ATTEMPTS)
        )
    attempts = int(attempts)
    if attempts < 1:
        raise ValueError(f"retry attempts must be >= 1; got {attempts}")
    if base_s is None:
        base_s = float(
            os.environ.get("CCTPU_RETRY_BASE_S", DEFAULT_RETRY_BASE_S)
        )
    if deadline_s is None:
        env = os.environ.get("CCTPU_RETRY_DEADLINE_S", "").strip()
        deadline_s = float(env) if env else None
    return RetryPolicy(
        attempts=attempts, base_s=float(base_s), deadline_s=deadline_s,
        seed=seed,
    )


def retry_call(
    fn: Callable[[], Any],
    *,
    site: str,
    policy: Optional[RetryPolicy] = None,
    metrics: Optional[MetricsRegistry] = None,
    log: Any = None,
) -> Any:
    """Run ``fn()`` under the retry policy for fault site ``site``.

    Success on any attempt returns ``fn``'s value; exhaustion re-raises the
    last exception unchanged. Counters/events go to ``metrics`` (or the
    log's registry) and the log's tracer — both optional, and nothing is
    touched on the no-failure path beyond the injection check.
    """
    pol = policy if policy is not None else resolve_retry_policy()
    deadline = (
        time.monotonic() + pol.deadline_s if pol.deadline_s is not None else None
    )
    last: Optional[Exception] = None
    attempt = 0
    for attempt in range(1, pol.attempts + 1):
        try:
            maybe_fail(site, metrics)
            return fn()
        except Exception as e:
            last = e
            if attempt >= pol.attempts:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            backoff = pol.backoff_s(site, attempt)
            mets = metrics if metrics is not None else metrics_of(log)
            mets.counter("retry_attempts").inc()
            mets.histogram("retry_backoff_seconds").observe(backoff)
            tr = tracer_of(log)
            if tr is not None:
                tr.event(
                    "retry", site=site, attempt=attempt,
                    error=type(e).__name__, backoff_s=round(backoff, 4),
                )
            time.sleep(backoff)
    mets = metrics if metrics is not None else metrics_of(log)
    mets.counter("retries_exhausted").inc()
    tr = tracer_of(log)
    if tr is not None:
        tr.event(
            "retries_exhausted", site=site, attempts=attempt,
            error=type(last).__name__,
        )
    assert last is not None
    # Black-box dump before the raise escapes: retry exhaustion is one of
    # the four flight-recorder triggers (obs/flight.py). Lazy import and
    # never-raise — a broken recorder must not mask the real error.
    try:
        from consensusclustr_tpu.obs.flight import (
            RETRIES_FLIGHT,
            dump_on_failure,
        )

        dump_on_failure(
            RETRIES_FLIGHT, log=log, site=site, attempts=attempt,
            error=type(last).__name__,
        )
    except Exception:  # graftlint: noqa[GL007] flight-recorder dump is best-effort in the crash path; the original error re-raises on the next line
        pass
    raise last
