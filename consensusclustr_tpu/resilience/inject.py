"""Fault-site registry + deterministic fault injection (ISSUE 10 tentpole).

Mirrors ``obs/fingerprint.py``'s inject pattern: named sites are registered
in ``obs/schema.py::FAULT_SITES`` (tools/check_obs_schema.py validates every
``*_SITE`` literal here against the registry, both directions), and faults
are planted through an opt-in hook that is OFF by default and costs one dict
lookup when off — the default path stays dispatch- and wall-identical to a
build without the layer (pinned in tests/test_resilience.py, the same
off-is-free contract numerics established).

The hook: ``CCTPU_FAULT_INJECT=<site>:<kind>[:<arg>]`` (env) or
``ClusterConfig.fault_inject`` / :func:`install_fault` (explicit, beats the
env). Multiple plants separate with ``;``. Kinds (hyphens and underscores
both accepted):

  * ``raise_once``        — raise :class:`InjectedFault` on the first hit of
    the site, succeed forever after (the canonical *transient* fault).
  * ``raise_first_n:N``   — raise on the first N hits.
  * ``raise_always``      — raise on every hit (the *permanent* fault: the
    retry policy must exhaust and surface it).
  * ``flaky_p:P[@SEED]``  — raise with probability P per hit, drawn from a
    seeded ``random.Random`` stream (deterministic sequence per injector).
  * ``corrupt_bytes[:N]`` — for checkpoint-file sites only: after the first
    atomic write completes (sidecar checksum included), overwrite N bytes
    (default 64) of the final file with seeded garbage — simulating silent
    on-disk corruption that the sha256 sidecar must catch at resume
    (quarantine + recompute, utils/checkpoint.py). Never raises at the site.

Raise kinds fire inside ``resilience/retry.py::retry_call`` — exactly once
per attempt, before the wrapped work — so a planted transient fault consumes
attempt 1 and the retry recovers; ``corrupt_bytes`` fires through
:func:`maybe_corrupt_file` at the write site's implementation. Every firing
increments the ``fault_injected`` counter.

Import-light: no jax, no numpy — config validation and the checkpoint layer
import this module without touching a backend.
"""

from __future__ import annotations

import contextlib
import os
import random
import threading
from typing import Dict, Iterator, Optional

from consensusclustr_tpu.obs.metrics import MetricsRegistry, global_metrics
from consensusclustr_tpu.obs.schema import FAULT_SITES

# Site-name constants (tools/check_obs_schema.py validates every ``*_SITE``
# literal here against obs.schema.FAULT_SITES, both directions — call sites
# import these, so a rename cannot silently orphan a fault site).
BOOT_CHUNK_SITE = "boot_chunk"        # bootstrap chunk dispatch (consensus/pipeline.py)
CKPT_WRITE_SITE = "ckpt_write"        # checkpoint chunk save (utils/checkpoint.py)
CKPT_READ_SITE = "ckpt_read"          # checkpoint chunk load / resume
NULL_CHUNK_SITE = "null_chunk"        # null-simulation chunk dispatch (nulltest/null.py)
SERVE_BATCH_SITE = "serve_batch"      # micro-batch device execution (serve/service.py)
SERVE_WARMUP_SITE = "serve_warmup"    # per-bucket warm-up compile dispatch
SERVE_WORKER_SITE = "serve_worker"    # the serving worker loop itself (supervised restart)

FAULT_KINDS = (
    "raise_once", "raise_first_n", "raise_always", "flaky_p", "corrupt_bytes",
)

DEFAULT_CORRUPT_BYTES = 64


class InjectedFault(RuntimeError):
    """A deliberately planted failure (never raised unless a fault was
    installed). Carries the site so retry events and tests can localize."""

    def __init__(self, message: str, site: str) -> None:
        super().__init__(message)
        self.site = site


class _Plant:
    """One planted fault's mutable state (hits / fires / RNG stream)."""

    __slots__ = ("site", "kind", "n", "p", "rng", "calls", "fires")

    def __init__(self, site: str, kind: str, n: int, p: float, seed: int) -> None:
        self.site = site
        self.kind = kind
        self.n = n
        self.p = p
        self.rng = random.Random(seed)
        self.calls = 0
        self.fires = 0


def parse_fault_spec(spec: Optional[str]) -> Dict[str, tuple]:
    """Parse ``site:kind[:arg][;site:kind...]`` -> {site: (kind, n, p, seed)}.

    Unknown sites or kinds raise loudly — a typo'd plant would otherwise
    "prove" resilience by never firing (the same discipline as
    obs/fingerprint.parse_inject)."""
    out: Dict[str, tuple] = {}
    if not spec:
        return out
    for part in str(spec).split(";"):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        if len(bits) < 2 or len(bits) > 3:
            raise ValueError(
                f"fault spec must be 'site:kind[:arg]'; got {part!r}"
            )
        site = bits[0].strip()
        kind = bits[1].strip().lower().replace("-", "_")
        arg = bits[2].strip() if len(bits) == 3 else ""
        if site not in FAULT_SITES:
            raise ValueError(
                f"fault spec names unknown site {site!r} "
                f"(known: {', '.join(sorted(FAULT_SITES))})"
            )
        if kind not in FAULT_KINDS:
            raise ValueError(
                f"fault spec names unknown kind {kind!r} "
                f"(known: {', '.join(FAULT_KINDS)})"
            )
        n, p, seed = 1, 0.0, 0
        if kind == "raise_first_n":
            if not arg:
                raise ValueError(f"raise_first_n needs a count; got {part!r}")
            n = int(arg)
            if n < 1:
                raise ValueError(f"raise_first_n count must be >= 1; got {n}")
        elif kind == "flaky_p":
            if not arg:
                raise ValueError(f"flaky_p needs a probability; got {part!r}")
            p_str, _, seed_str = arg.partition("@")
            p = float(p_str)
            if not (0.0 < p <= 1.0):
                raise ValueError(f"flaky_p probability must be in (0, 1]; got {p}")
            seed = int(seed_str) if seed_str else 0
        elif kind == "corrupt_bytes":
            n = int(arg) if arg else DEFAULT_CORRUPT_BYTES
            if n < 1:
                raise ValueError(f"corrupt_bytes count must be >= 1; got {n}")
        elif arg:
            raise ValueError(f"kind {kind!r} takes no argument; got {part!r}")
        if site in out:
            raise ValueError(f"fault spec plants site {site!r} twice")
        out[site] = (kind, n, p, seed)
    return out


class FaultInjector:
    """Process-scoped planted-fault state for one spec.

    Thread-safe (the serving worker and the async checkpoint writer hit
    sites off the main thread); the per-plant RNG streams make every firing
    decision deterministic for a fixed spec, so a chaos run is exactly
    reproducible."""

    def __init__(self, spec: str) -> None:
        self.spec = str(spec)
        self._plants = {
            site: _Plant(site, kind, n, p, seed)
            for site, (kind, n, p, seed) in parse_fault_spec(spec).items()
        }
        if not self._plants:
            raise ValueError(f"fault spec {spec!r} plants nothing")
        self._lock = threading.Lock()

    @property
    def total_fires(self) -> int:
        return sum(pl.fires for pl in self._plants.values())

    @property
    def total_calls(self) -> int:
        return sum(pl.calls for pl in self._plants.values())

    def plant(self, site: str) -> Optional[_Plant]:
        return self._plants.get(site)

    def fire(self, site: str, metrics: Optional[MetricsRegistry] = None) -> None:
        """Raise :class:`InjectedFault` when a raise-kind plant at ``site``
        is due. corrupt_bytes plants never raise here."""
        pl = self._plants.get(site)
        if pl is None or pl.kind == "corrupt_bytes":
            return
        with self._lock:
            pl.calls += 1
            if pl.kind == "raise_once":
                due = pl.fires < 1
            elif pl.kind == "raise_first_n":
                due = pl.fires < pl.n
            elif pl.kind == "raise_always":
                due = True
            else:  # flaky_p
                due = pl.rng.random() < pl.p
            if due:
                pl.fires += 1
                calls = pl.calls
        if due:
            (metrics if metrics is not None else global_metrics()).counter(
                "fault_injected"
            ).inc()
            raise InjectedFault(
                f"injected fault at site {site!r} ({pl.kind}, hit {calls})",
                site,
            )

    def corrupt_file(
        self, site: str, path: str, metrics: Optional[MetricsRegistry] = None
    ) -> bool:
        """corrupt_bytes plant: overwrite bytes of ``path`` in place (first
        hit only — one silently corrupted chunk is the scenario; corrupting
        every write would just be a slower spelling of the same recovery).
        Returns True when the file was corrupted."""
        pl = self._plants.get(site)
        if pl is None or pl.kind != "corrupt_bytes":
            return False
        with self._lock:
            pl.calls += 1
            if pl.fires >= 1:
                return False
            pl.fires += 1
            garbage = bytes(pl.rng.randrange(256) for _ in range(pl.n))
        size = os.path.getsize(path)
        if size == 0:
            return False
        with open(path, "r+b") as f:
            f.seek(min(size // 3, size - 1))
            f.write(garbage)
        (metrics if metrics is not None else global_metrics()).counter(
            "fault_injected"
        ).inc()
        return True


# -- process-global resolution ------------------------------------------------

_LOCK = threading.Lock()
_EXPLICIT: Optional[FaultInjector] = None
_ENV_CACHE: tuple = (None, None)  # (spec string, FaultInjector)


def install_fault(spec: str) -> FaultInjector:
    """Install an explicit injector (beats the env var) and return it —
    callers (tools/chaos_audit.py) inspect its ``total_fires`` afterwards to
    prove the planted fault actually fired."""
    global _EXPLICIT
    inj = FaultInjector(spec)
    with _LOCK:
        _EXPLICIT = inj
    return inj


def clear_fault() -> None:
    """Remove the explicit injector and drop the env-spec cache (a re-read
    of an unchanged env spec then starts from fresh plant state)."""
    global _EXPLICIT, _ENV_CACHE
    with _LOCK:
        _EXPLICIT = None
        _ENV_CACHE = (None, None)


@contextlib.contextmanager
def fault_scope(spec: Optional[str]) -> Iterator[Optional[FaultInjector]]:
    """Install ``spec`` for the duration of a block (``ClusterConfig.
    fault_inject`` rides this through api.consensus_clust); None is inert —
    env-planted faults still apply. The previous explicit injector is
    restored on exit."""
    if not spec:
        yield None
        return
    global _EXPLICIT
    inj = FaultInjector(spec)
    with _LOCK:
        prev, _EXPLICIT = _EXPLICIT, inj
    try:
        yield inj
    finally:
        with _LOCK:
            _EXPLICIT = prev


def active_injector() -> Optional[FaultInjector]:
    """The installed injector, else one resolved from ``CCTPU_FAULT_INJECT``
    (cached while the spec string is unchanged, so plant state — raise_once
    already fired — survives across calls). None when nothing is planted:
    the fast path is one dict lookup."""
    global _ENV_CACHE
    if _EXPLICIT is not None:
        return _EXPLICIT
    spec = os.environ.get("CCTPU_FAULT_INJECT") or None
    if spec is None:
        return None
    with _LOCK:
        if _ENV_CACHE[0] != spec:
            _ENV_CACHE = (spec, FaultInjector(spec))
        return _ENV_CACHE[1]


def maybe_fail(site: str, metrics: Optional[MetricsRegistry] = None) -> None:
    """Raise the planted fault for ``site`` when one is installed and due.
    The off path (no injector) is one env-dict lookup — zero device work,
    zero allocation."""
    inj = active_injector()
    if inj is not None:
        inj.fire(site, metrics)


def maybe_corrupt_file(
    site: str, path: str, metrics: Optional[MetricsRegistry] = None
) -> bool:
    """Apply a planted corrupt_bytes fault to ``path`` (write sites call
    this after their atomic rename lands). No-op / False when off."""
    inj = active_injector()
    if inj is None:
        return False
    return inj.corrupt_file(site, path, metrics)
