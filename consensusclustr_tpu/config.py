"""Configuration schema mirroring the reference's 34 function parameters.

The reference has no config files — its de-facto config schema is the default
argument list of ``consensusClust`` (reference R/consensusClust.R:122-128) and
``testSplits`` (:892), validated by ~20 stopifnot contracts (:130-191).
``ClusterConfig`` mirrors those names/defaults 1:1 (snake_cased), plus a small
set of TPU-specific static-shape knobs that have no reference counterpart.

Deliberate deviations from reference bugs (see docs/quirks.md):
  * ``seed`` is honored everywhere (reference hardcodes set.seed(123) at :194).
  * ``scale`` gates scaling of the PCA input (reference gates it on ``center``
    at :339/:369).
  * "any cluster < 50 cells" triggers the significance gate (reference's :521
    expression is only truthy when *all* clusters are small).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Union

import numpy as np


def _default_res_range() -> tuple:
    # reference R/consensusClust.R:126: c(seq(0.01, 0.3, length.out = 10),
    #                                     seq(0.25, 1.5, length.out = 10))
    lo = np.linspace(0.01, 0.3, 10)
    hi = np.linspace(0.25, 1.5, 10)
    return tuple(float(r) for r in np.concatenate([lo, hi]))


DEFAULT_RES_RANGE = _default_res_range()

# reference R/consensusClust.R:892 — testSplits' own default sweep.
TEST_SPLITS_RES_RANGE = tuple(float(r) for r in np.arange(0.1, 3.4 + 1e-9, 0.15))

# reference R/consensusClust.R:803-804 — the null-simulation sweep is hardcoded.
NULL_SIM_RES_RANGE = tuple(
    float(r) for r in np.concatenate([np.arange(0.01, 0.3, 0.03), np.arange(0.3, 2.0 + 1e-9, 0.2)])
)
NULL_SIM_MIN_SIZE = 5


@dataclasses.dataclass(frozen=True)
class ClusterConfig:
    """All knobs of the pipeline; defaults match the reference signature.

    Reference lines given per field (R/consensusClust.R unless noted).
    """

    # --- preprocessing (L2) -------------------------------------------------
    size_factors: Union[str, np.ndarray] = "deconvolution"  # :123; "deconvolution" | "libsize" | vector
    n_var_features: int = 2000            # :124 nVarFeatures
    variable_features: Optional[Sequence] = None  # :123 (None => deviance selection)
    vars_to_regress: Optional[object] = None      # :124 (None | array [n_cells, n_cov] | names)
    regress_method: str = "lm"            # :125 ("lm" | "glmGamPoi" | "poisson")
    skip_first_regression: Union[bool, Sequence[str]] = False  # :125

    # --- dimensionality reduction (L3) --------------------------------------
    pc_num: Union[str, int] = "find"      # :123 ("find" | "getDenoisedPCs" | int)
    pc_var: float = 0.2                   # :122 pcVar — cum-sdev fraction for the elbow rule
    pca_method: str = "irlba"             # :124 — validated but never used by the reference
    scale: bool = True                    # :124
    center: bool = True                   # :124
    interactive: bool = False             # :122

    # --- clustering engine (L4) ---------------------------------------------
    cluster_fun: str = "leiden"           # :126 ("leiden" | "louvain")
    res_range: Sequence[float] = DEFAULT_RES_RANGE  # :126
    k_num: Sequence[int] = (10, 15, 20)   # :127
    mode: str = "robust"                  # :127 ("robust" | "granular")

    # --- consensus layer (L5) -----------------------------------------------
    nboots: int = 100                     # :124
    boot_size: float = 0.9                # :127 bootSize — resample fraction
    min_stability: float = 0.175          # :125

    # --- statistical testing (L6) -------------------------------------------
    alpha: float = 0.05                   # :122
    silhouette_thresh: float = 0.45       # :126
    test_splits_separately: bool = False  # :125 (sic: reference spells it "seperately")
    n_null_sims: int = 20                 # :933 — per adaptive round
    # No reference counterpart: skip the null-simulation gate entirely (the
    # reference always tests when its :521 gate fires). For benchmark runs of
    # the clustering core and for platforms where the vmapped null sims are
    # impractical (a single 50k-cell sim measured ~40 min on 1 CPU core).
    test_significance: bool = True

    # --- hierarchy / iteration (L7) -----------------------------------------
    iterate: bool = False                 # :122
    min_size: int = 50                    # :127
    depth: int = 1                        # :128 (internal)

    # --- runtime ------------------------------------------------------------
    seed: int = 123                       # :128
    assay: str = "RNA"                    # :127 (Seurat adapter only)

    # --- TPU-specific static-shape knobs (no reference counterpart) ---------
    max_clusters: int = 64      # padded one-hot width for labels everywhere
    boot_batch: int = 0         # boots jitted per device batch; 0 => auto
    compute_dtype: str = "float32"
    use_pallas: bool = True     # Pallas co-clustering kernel on TPU; einsum fallback
    progress: bool = False      # structured per-level logging
    # Observability sink (obs/): append this run's RunRecord (span tree +
    # events + metrics, schema-versioned JSON) as one JSONL line to this
    # path. None still attaches the record to the returned ClusterResult;
    # the CCTPU_RUN_RECORD env var supplies a default path when unset.
    # Render with `python tools/report.py <path>`.
    run_record_path: Optional[str] = None
    # Persist boot chunks; a rerun with identical (data, config, seed)
    # resumes at the first missing chunk. Covers single-chip AND mesh runs,
    # robust AND granular (granular checkpoints the flattened |k|*|res|
    # candidate axis). On a mesh the boot fan-out runs chunked (multiple of
    # the device count, CCTPU_CKPT_CHUNK) instead of fused; results are
    # bit-identical either way.
    checkpoint_dir: Optional[str] = None
    # Pad iterate-subproblem shapes to geometric ~1.3x buckets so deep
    # iterate=True runs reuse jit caches instead of recompiling per subcluster
    # size (SURVEY §7.3 item 2). Cells pad by cyclic duplication — the same
    # with-replacement duplication the bootstrap itself performs — and PC dims
    # pad with inert zero columns; child labels are sliced back. Disable for
    # exact unpadded per-subcluster statistics.
    shape_buckets: bool = True
    # Internal: set by the iterate driver on bucketed subproblems — the
    # first n_real_cells rows are real, the rest cyclic duplicates. The
    # significance gate and null test evaluate ONLY the real rows (padded
    # duplicates would inflate cluster sizes past the 50-cell trigger and
    # silhouettes past the threshold) and the outcome maps back by label.
    n_real_cells: Optional[int] = None
    # Async chunk pipelining (parallel/pipelined.py): how many boot / null-sim
    # chunks may be in flight on the device at once. None = $CCTPU_PIPELINE_DEPTH
    # (default 2). Depth 1 reproduces strictly serial dispatch (and synchronous
    # checkpoint writes); results are bit-identical at any depth — the window
    # only changes when chunks are fetched, never what was dispatched.
    pipeline_depth: Optional[int] = None
    # Inner vmap width of the _boot_batch program (ISSUE 20 byte diet):
    # 0 < bpp < chunk (and chunk % bpp == 0) runs each chunk as a lax.scan
    # over chunk/bpp groups of a width-bpp vmap inside ONE dispatch — the
    # program's working set and est_bytes scale with bpp instead of chunk,
    # per-boot labels stay bit-identical (vmap is an exact map), and chunk /
    # checkpoint / dispatch accounting are untouched. None = the
    # CCTPU_BOOTS_PER_PROGRAM env var; 0 (the resolved default) keeps the
    # historical single-vmap HLO exactly.
    boots_per_program: Optional[int] = None
    # Consensus-accumulator regime (consensus/pipeline.py, ISSUE 9):
    # None = auto — dense up to DENSE_CONSENSUS_LIMIT cells (16384;
    # CCTPU_DENSE_CONSENSUS_LIMIT overrides), the kNN-restricted
    # ``sparse_knn`` accumulator above it (O(n·m) memory/FLOPs instead of
    # O(n²)). Explicit values: "dense" (the [n, n] einsum oracle), "pallas"
    # (the [n, n] Mosaic tile kernel forced), "blockwise" ([block, n]
    # streaming tiles), "sparse_knn". An explicit dense regime above the
    # limit raises loudly instead of OOMing. Takes precedence over the
    # legacy ``dense_consensus`` bool below.
    consensus_regime: Optional[str] = None
    # Per-cell candidate-set width m for the sparse_knn regime: the top-m
    # PC-space neighbours whose pairs the restricted accumulator counts.
    # None = auto (max(64, 2*max(k_num)), clipped to n-1). On candidate
    # pairs the restricted counts are integer-exactly the dense counts
    # (tools/parity_audit.py --pair dense:sparse_knn).
    sparse_knn_candidates: Optional[int] = None
    # Legacy dense/blockwise switch (pre-ISSUE-9): None = auto, or force
    # dense [n, n] assembly with True / blockwise streaming with False.
    # The blockwise path computes the consensus kNN graph and merge
    # statistics from [block, n] tiles and never holds the full matrix;
    # its ConsensusResult carries jaccard_dist=None.
    dense_consensus: Optional[bool] = None
    # Distributed execution: None = single chip; "auto" = shard over all
    # visible devices when >1; or an explicit jax.sharding.Mesh built by
    # parallel.mesh.consensus_mesh. Robust AND granular modes shard; the
    # pipeline falls back to single-chip (with a log event) when a level's
    # shape can't (nboots<=1, or n not divisible by the mesh's cell axis).
    mesh: Optional[object] = None
    # --- serving knobs (serve/, no reference counterpart) -------------------
    # Resolution order everywhere: explicit AssignmentService argument >
    # these fields > CCTPU_SERVE_QUEUE_DEPTH / CCTPU_SERVE_MAX_BATCH /
    # CCTPU_SERVE_BUCKETS env vars > defaults (64 / 256 / powers of two).
    # Defaults and rationale: docs/quirks.md "Serving defaults".
    serve_queue_depth: Optional[int] = None   # bounded request-queue slots
    serve_max_batch: Optional[int] = None     # max rows per micro-batch
    serve_buckets: Optional[Sequence[int]] = None  # compiled pad-to sizes
    # Prometheus /metrics + /healthz HTTP exporter on AssignmentService.
    # None (and no CCTPU_SERVE_METRICS_PORT env) = off — serving never opens
    # a socket unless asked (docs/quirks.md). 0 = bind an ephemeral port
    # (the bound port is svc.metrics_port).
    serve_metrics_port: Optional[int] = None
    # Numerics observability (obs/fingerprint.py): "off" | "watch" | "audit".
    # None resolves CCTPU_NUMERICS; unset = OFF — checkpoints cost nothing
    # and dispatch nothing unless asked (docs/quirks.md "Observability
    # schema v5 → v6"). "watch" runs only the NaN/Inf watchdog
    # (numerics_nonfinite counter + span tag); "audit" records a device-side
    # fingerprint (order-independent 64-bit checksum + shape/dtype/min/max/
    # mean/nan/inf) at every registered pipeline checkpoint — the stream
    # tools/parity_audit.py diffs across compute regimes.
    numerics: Optional[str] = None
    # Resource profiling (obs/resource.py): background host-RSS +
    # device-memory sampling interval in milliseconds. None resolves
    # CCTPU_RESOURCE_SAMPLE_MS; unset/0 = OFF — the sampler thread never
    # starts unless asked, so tests and library users pay zero overhead
    # (docs/quirks.md "Observability schema v3 → v4"). When on, spans gain
    # rss_peak_bytes/device_peak_bytes watermark attrs and the RunRecord
    # carries the sample series (rendered as Perfetto counter tracks).
    resource_sample_ms: Optional[int] = None
    # Sampling profiler (obs/profiler.py, ISSUE 16): host stack-sampling
    # rate in Hz. None resolves CCTPU_PROFILE_HZ; unset/0 = OFF — the
    # profiler thread never starts and span() pays one attribute check
    # (the off-is-free pin). When on, samples are tagged with each
    # thread's open-span path, the RunRecord carries the folded hot
    # stacks (schema v9), and tools/flamegraph.py exports them as
    # collapsed text or speedscope JSON. Per-program cost attribution is
    # independent of this knob and always on.
    profile_hz: Optional[float] = None
    # Resilience (resilience/, ISSUE 10): total attempts per fault site —
    # chunk dispatch, checkpoint read/write, serving warm-up/batch. None
    # resolves CCTPU_RETRY_ATTEMPTS (default 3); 1 = fail-fast (no retries).
    # Retried work is a pure function of its inputs, so results are
    # bit-identical whether or not a retry fired (tools/chaos_audit.py).
    retry_attempts: Optional[int] = None
    # Deterministic fault injection (resilience/inject.py): a
    # "<site>:<kind>[:<arg>]" spec planted for this run's duration — e.g.
    # "boot_chunk:raise_once" or "ckpt_write:corrupt_bytes:64". None
    # resolves CCTPU_FAULT_INJECT; unset = OFF, and the off path costs one
    # dict lookup per site hit (docs/quirks.md). Sites are registered in
    # obs/schema.py::FAULT_SITES; tools/chaos_audit.py drives the presets.
    fault_inject: Optional[str] = None
    # Stall watchdog (obs/flight.py, ISSUE 14): minimum per-phase deadline
    # in seconds before the watchdog calls a phase wedged. None resolves
    # CCTPU_STALL_FLOOR_S (default 120 s). Deadlines self-tune upward from
    # the live phase_seconds / serve_latency_seconds histograms (p99 x
    # CCTPU_STALL_FACTOR once they hold enough samples), so the floor only
    # matters cold. The watchdog itself rides the flight-recorder kill
    # switch: CCTPU_NO_FLIGHT=1 disarms both.
    stall_floor_s: Optional[float] = None
    # Fleet layer (serve/fleet.py + serve/router.py, ISSUE 18): replica
    # count behind the FleetRouter. None resolves CCTPU_FLEET_REPLICAS
    # (default 2); must be >= 1.
    fleet_replicas: Optional[int] = None
    # Alert-driven adaptive control (serve/control.py, ISSUE 18): True arms
    # the ControlPolicy (alerts + queue-wait modulate batching and
    # admission). None resolves CCTPU_FLEET_CONTROL; unset = OFF, and off
    # is pinned bit-identical to a routerless service (tests/test_fleet.py)
    # — see docs/quirks.md "Observability schema v9 -> v10" for why a
    # reproducible benchmark keeps this opt-in.
    fleet_control: Optional[bool] = None

    def __post_init__(self):
        if isinstance(self.pc_num, str) and self.pc_num not in ("find", "getDenoisedPCs"):
            raise ValueError(f"pc_num must be an int, 'find' or 'getDenoisedPCs'; got {self.pc_num!r}")
        if self.mode not in ("robust", "granular"):
            raise ValueError(f"mode must be 'robust' or 'granular'; got {self.mode!r}")
        if self.cluster_fun not in ("leiden", "louvain"):
            raise ValueError(f"cluster_fun must be 'leiden' or 'louvain'; got {self.cluster_fun!r}")
        if self.regress_method not in ("lm", "glmGamPoi", "poisson"):
            raise ValueError(
                f"regress_method must be 'lm', 'glmGamPoi' or 'poisson'; got {self.regress_method!r}"
            )
        if not (0.0 < self.boot_size <= 1.0):
            raise ValueError("boot_size must be in (0, 1]")
        if isinstance(self.size_factors, str) and self.size_factors not in (
            "deconvolution",
            "libsize",
        ):
            raise ValueError("size_factors must be 'deconvolution', 'libsize' or a vector")
        if not (0.0 < self.pc_var <= 1.0):
            raise ValueError("pc_var must be in (0, 1]")
        if self.compute_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"compute_dtype must be 'float32' or 'bfloat16'; got {self.compute_dtype!r}"
            )
        if self.nboots < 0 or self.min_size < 0 or self.n_var_features <= 0:
            raise ValueError("nboots/min_size must be >= 0, n_var_features > 0")
        if self.mesh is not None and not (
            self.mesh == "auto" or hasattr(self.mesh, "devices")
        ):
            raise ValueError("mesh must be None, 'auto', or a jax.sharding.Mesh")
        if self.pipeline_depth is not None and self.pipeline_depth < 1:
            raise ValueError(
                f"pipeline_depth must be >= 1 (1 = serial); got {self.pipeline_depth}"
            )
        if self.boots_per_program is not None and int(self.boots_per_program) < 0:
            raise ValueError(
                f"boots_per_program must be >= 0 (0 = one vmap per chunk); "
                f"got {self.boots_per_program}"
            )
        for knob in ("serve_queue_depth", "serve_max_batch"):
            v = getattr(self, knob)
            if v is not None and int(v) < 1:
                raise ValueError(f"{knob} must be >= 1; got {v}")
        if self.numerics is not None and self.numerics not in (
            "off", "watch", "audit"
        ):
            raise ValueError(
                f"numerics must be None, 'off', 'watch' or 'audit'; got "
                f"{self.numerics!r}"
            )
        if self.consensus_regime is not None and self.consensus_regime not in (
            "dense", "pallas", "blockwise", "sparse_knn"
        ):
            raise ValueError(
                f"consensus_regime must be None, 'dense', 'pallas', "
                f"'blockwise' or 'sparse_knn'; got {self.consensus_regime!r}"
            )
        if self.sparse_knn_candidates is not None and int(
            self.sparse_knn_candidates
        ) < 2:
            raise ValueError(
                f"sparse_knn_candidates must be >= 2; got "
                f"{self.sparse_knn_candidates}"
            )
        if self.retry_attempts is not None and int(self.retry_attempts) < 1:
            raise ValueError(
                f"retry_attempts must be >= 1 (1 = fail-fast); got "
                f"{self.retry_attempts}"
            )
        if self.fault_inject is not None:
            # validate eagerly: a typo'd plant would otherwise "prove"
            # resilience by never firing (resilience/inject.py raises on
            # unknown sites/kinds; import is lazy + jax-free)
            from consensusclustr_tpu.resilience.inject import parse_fault_spec

            parse_fault_spec(self.fault_inject)
        if self.stall_floor_s is not None and float(self.stall_floor_s) <= 0:
            raise ValueError(
                f"stall_floor_s must be > 0; got {self.stall_floor_s}"
            )
        if self.fleet_replicas is not None and int(self.fleet_replicas) < 1:
            raise ValueError(
                f"fleet_replicas must be >= 1; got {self.fleet_replicas}"
            )
        if self.resource_sample_ms is not None and int(self.resource_sample_ms) < 0:
            raise ValueError(
                f"resource_sample_ms must be >= 0 (0 = off); got "
                f"{self.resource_sample_ms}"
            )
        if self.profile_hz is not None and float(self.profile_hz) < 0:
            raise ValueError(
                f"profile_hz must be >= 0 (0 = off); got {self.profile_hz}"
            )
        if self.serve_metrics_port is not None and not (
            0 <= int(self.serve_metrics_port) <= 65535
        ):
            raise ValueError(
                f"serve_metrics_port must be in [0, 65535] (0 = ephemeral) or "
                f"None (off); got {self.serve_metrics_port}"
            )
        if self.serve_buckets is not None:
            sb = [int(b) for b in self.serve_buckets]
            if not sb or any(b < 1 for b in sb):
                raise ValueError(
                    f"serve_buckets must be non-empty positive sizes; got "
                    f"{self.serve_buckets!r}"
                )

    def replace(self, **kw) -> "ClusterConfig":
        return dataclasses.replace(self, **kw)
