"""Pallas TPU kernel for the SNN rank-weight scan.

The bandwidth-lean variant of cluster/snn.py's ``_rank_halfweights`` family
(the bluster rank rule w(i, j) = k - r/2, r = min over shared members of the
rank sum). The XLA lax.scan build streams a [n, k+1, k] compare transient
through HBM per q step — k+1 round trips of the biggest tensor in the SNN
build. The kernel here tiles the row axis and runs the whole q loop against
VMEM-resident tiles: per grid step it holds one [T, k+1] self+neighbour list
tile and one [T, k, k+1] gathered-neighbour-list tile, and every compare-min
intermediate lives and dies in VMEM — the transient never touches HBM (the
same no-HBM-intermediate trick as ops/pallas_cocluster.py), and the output
is the int16 half-weight lane directly.

The one gather the rank scan needs — neighbour q of neighbour a of row i —
cannot run inside a row-tiled kernel (it reads arbitrary OTHER rows), so the
wrapper precomputes ``nlists[i, a, q] = lists[idx[i, a], q]`` as k+1 composed
cheap gathers (`lists[:, q][idx]`, the same 1-D-indexed form the scan build
uses; see docs/perf.md on the ~30x row-gather cliff) and hands the kernel a
gather-free problem.

Two entries mirror the jax lane exactly:

* ``pallas_rank_halfweights(idx)`` — the plain build (every column an edge);
* ``pallas_rank_halfweights_masked(idx, kv)`` — the padded-k build with a
  *traced* kv in SMEM, so the fused ``cluster_grid`` vmap over the k axis
  keeps working (the batching rule broadcasts the row tiles and batches the
  scalar).

Both are integer-exact: rank sums are small ints, every compare/min/clamp is
integer arithmetic, so the output is bit-identical to the jax lane (pinned
by tools/parity_audit.py --pair snn_jax:snn_pallas and the forced-regime
tests in tests/test_fused_grid.py). Off TPU the kernel runs under
``interpret=True`` (tier-1 CPU coverage); runtime lowering/execution failure
degrades to the jax build via cluster/engine.resolve_snn_impl's probe — the
same warn-and-fall-back contract as the cocluster kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

ROW_TILE = 256      # rows per grid step; [T, k+1, k] int32 compare transient
#                     at k=20 is ~430 KB VMEM — comfortably resident

# The snn_impl names cluster/engine.py dispatches on (obs.schema.SNN_IMPLS;
# tools/check_obs_schema.py pins these constants <-> the registry both ways)
JAX_SNN_IMPL = "jax"
PALLAS_SNN_IMPL = "pallas"


def _sentinel(k: int) -> int:
    # any rank sum >= 2k clamps the half-weight to 0; matches the jax lane's
    # cluster/snn._rank_sentinel so intermediate values agree exactly
    return 2 * k + 4


def _interpret() -> bool:
    """Interpret off-TPU (CPU tier-1 runs the kernel in interpret mode);
    resolved at trace time — the backend is fixed per process."""
    return jax.default_backend() != "tpu"


def _kernel_plain(lists_ref, nlists_ref, out_ref, *, k: int):
    lists = lists_ref[...].astype(jnp.int32)                  # [T, k+1]
    t = lists.shape[0]
    sent = jnp.int32(_sentinel(k))
    # 2-D+ iota only (Mosaic): p runs along axis 1 of the [T, k+1, k] cube
    p_iota = jax.lax.broadcasted_iota(jnp.int32, (t, k + 1, k), 1)
    r = jnp.full((t, k), sent, jnp.int32)
    for q in range(k + 1):                                    # static unroll
        nl_q = nlists_ref[:, :, q].astype(jnp.int32)          # [T, k]
        mask = lists[:, :, None] == nl_q[:, None, :]          # VMEM-only cube
        best_p = jnp.min(jnp.where(mask, p_iota, sent), axis=1)
        r = jnp.minimum(r, best_p + q)
    out_ref[...] = jnp.maximum(2 * k - r, 0).astype(jnp.int16)


def _kernel_masked(kv_ref, lists_ref, nlists_ref, out_ref, *, k: int):
    kv = kv_ref[0, 0]                                         # traced scalar
    lists = lists_ref[...].astype(jnp.int32)                  # [T, k+1]
    t = lists.shape[0]
    sent = jnp.int32(_sentinel(k))
    p_iota = jax.lax.broadcasted_iota(jnp.int32, (t, k + 1, k), 1)
    # list position p valid iff p == 0 (self) or column p-1 < kv, i.e. p <= kv
    pvalid = p_iota <= kv
    r = jnp.full((t, k), sent, jnp.int32)
    for q in range(k + 1):                                    # static unroll
        nl_q = nlists_ref[:, :, q].astype(jnp.int32)
        mask = (lists[:, :, None] == nl_q[:, None, :]) & pvalid
        best_p = jnp.min(jnp.where(mask, p_iota, sent), axis=1)
        r_new = jnp.minimum(r, best_p + q)
        r = jnp.where(q <= kv, r_new, r)                      # skip invalid q
    colv = jax.lax.broadcasted_iota(jnp.int32, (t, k), 1) < kv
    hw = jnp.maximum(2 * kv - r, 0)
    out_ref[...] = jnp.where(colv, hw, 0).astype(jnp.int16)


def _gathered_lists(idx: jax.Array):
    """lists [n, k+1] (self at rank 0) and nlists [n, k, k+1] with
    nlists[i, a, q] = lists[idx[i, a], q] — the cross-row reads hoisted out
    of the kernel as composed 1-D-indexed gathers."""
    n, k = idx.shape
    self_ids = jnp.arange(n, dtype=idx.dtype)[:, None]
    lists = jnp.concatenate([self_ids, idx], axis=1)          # [n, k+1]
    nlists = jnp.stack([lists[:, q][idx] for q in range(k + 1)], axis=-1)
    return lists, nlists


def _row_pad(n: int) -> int:
    tile = min(ROW_TILE, -(-n // 8) * 8)                      # sublane-aligned
    return tile, -(-n // tile) * tile


def _cost(n: int, k: int) -> pl.CostEstimate:
    return pl.CostEstimate(
        flops=2 * n * (k + 1) * (k + 1) * k,                  # compare + min
        bytes_accessed=4 * n * (k + 1) + 4 * n * k * (k + 1) + 2 * n * k,
        transcendentals=0,
    )


@functools.partial(jax.jit, static_argnames=("interpret",))  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
def _halfweights_call(idx: jax.Array, interpret: bool) -> jax.Array:
    n, k = idx.shape
    tile, n_pad = _row_pad(n)
    lists, nlists = _gathered_lists(idx)
    lists = jnp.pad(lists, ((0, n_pad - n), (0, 0)))
    nlists = jnp.pad(nlists, ((0, n_pad - n), (0, 0), (0, 0)))
    hw = pl.pallas_call(
        functools.partial(_kernel_plain, k=k),
        grid=(n_pad // tile,),
        in_specs=[
            pl.BlockSpec((tile, k + 1), lambda i: (i, 0)),
            pl.BlockSpec((tile, k, k + 1), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((tile, k), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, k), jnp.int16),
        cost_estimate=_cost(n, k),
        interpret=interpret,
    )(lists, nlists)
    return hw[:n]


@functools.partial(jax.jit, static_argnames=("interpret",))  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
def _halfweights_masked_call(
    idx: jax.Array, kv: jax.Array, interpret: bool
) -> jax.Array:
    n, k = idx.shape
    tile, n_pad = _row_pad(n)
    lists, nlists = _gathered_lists(idx)
    lists = jnp.pad(lists, ((0, n_pad - n), (0, 0)))
    nlists = jnp.pad(nlists, ((0, n_pad - n), (0, 0), (0, 0)))
    hw = pl.pallas_call(
        functools.partial(_kernel_masked, k=k),
        grid=(n_pad // tile,),
        in_specs=[
            pl.BlockSpec(
                (1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM
            ),
            pl.BlockSpec((tile, k + 1), lambda i: (i, 0)),
            pl.BlockSpec((tile, k, k + 1), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((tile, k), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, k), jnp.int16),
        cost_estimate=_cost(n, k),
        interpret=interpret,
    )(jnp.asarray(kv, jnp.int32).reshape(1, 1), lists, nlists)
    return hw[:n]


def pallas_rank_halfweights(idx: jax.Array) -> jax.Array:
    """int16 half-weights [n, k] — the fused-kernel twin of
    cluster/snn._rank_halfweights, bit-identical by construction."""
    return _halfweights_call(jnp.asarray(idx, jnp.int32), _interpret())


def pallas_rank_halfweights_masked(idx: jax.Array, kv: jax.Array) -> jax.Array:
    """int16 masked half-weights [n, k_max] with traced ``kv`` — the
    fused-kernel twin of cluster/snn._rank_halfweights_masked."""
    return _halfweights_masked_call(
        jnp.asarray(idx, jnp.int32), kv, _interpret()
    )
