"""Pallas TPU kernel for the Leiden local-move k_ic sweep.

The bandwidth-lean variant of cluster/leiden.py's ``_local_moves`` inner
contraction (ISSUE 20). The XLA slab scan streams a [n, slab, e] broadcast-
compare one-hot through HBM per slab step — the same HBM-transient class
ops/pallas_snn.py killed in the SNN rank build — and the edge weights
re-visit HBM on every slab of every sweep iteration. The kernel here tiles
the row axis and computes the whole candidate axis against VMEM-resident
tiles: per grid step it holds one [T, e] candidate-community tile and one
[T, e] int16 half-weight tile, and every [T, slab, e] compare cube lives and
dies in VMEM — the one-hot never touches HBM, and the edge weights are read
once per sweep iteration instead of once per slab.

Everything is integer arithmetic (ISSUE 20's narrow-lane contract): the
output is the int32 HALF-unit k_ic — k_ic_h[i, j] = sum_s hw[i, s] *
[cand[i, j] == cand[i, s]] for the e neighbour candidates, plus the own-
community and solo columns — so the caller's single ``astype(f32) * 0.5``
widening reproduces the f32 einsum-of-halves bit for bit (per-row sums are
< 2^24 half-units). Bit-identical to the jax slab scan by construction,
pinned by tools/parity_audit.py --pair leiden_jax:leiden_pallas.

The row-tiled kernel reads no other rows: the candidate-community gather
``labels[nbr]`` stays outside in ``_local_moves`` (a cheap composed 1-D
gather; see docs/perf.md on the ~30x row-gather cliff), so the kernel gets a
gather-free problem — the same hoisting contract as ops/pallas_snn.py.

Off TPU the kernel runs under ``interpret=True`` (tier-1 CPU coverage);
runtime lowering/execution failure degrades to the jax slab scan via
cluster/engine.resolve_leiden_impl's probe — the same warn-and-fall-back
contract as the SNN and cocluster kernels.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

ROW_TILE = 256      # rows per grid step; the [T, slab, e] int32 compare cube
#                     at e=40 is ~330 KB VMEM — comfortably resident

_SLAB = 8           # candidate columns per compare cube (VMEM/VPU balance,
#                     mirrors cluster/leiden._SLAB)

# The leiden_impl names cluster/engine.py dispatches on
# (obs.schema.LEIDEN_IMPLS; tools/check_obs_schema.py pins these constants
# <-> the registry both ways)
JAX_LEIDEN_IMPL = "jax"
PALLAS_LEIDEN_IMPL = "pallas"


def _interpret() -> bool:
    """Interpret off-TPU (CPU tier-1 runs the kernel in interpret mode);
    resolved at trace time — the backend is fixed per process."""
    return jax.default_backend() != "tpu"


def _kernel(cand_ref, hw_ref, lab_ref, ids_ref, out_ref, *, e: int):
    cand = cand_ref[...]                                      # [T, e] int32
    hw = hw_ref[...].astype(jnp.int32)                        # [T, e]
    lab = lab_ref[...]                                        # [T, 1]
    ids = ids_ref[...]                                        # [T, 1]
    cols = []
    for j0 in range(0, e, _SLAB):                             # static unroll
        cj = cand[:, j0:min(j0 + _SLAB, e)]                   # [T, s]
        eq = cj[:, :, None] == cand[:, None, :]               # VMEM-only cube
        cols.append(jnp.sum(jnp.where(eq, hw[:, None, :], 0), axis=2))
    own = jnp.sum(jnp.where(lab == cand, hw, 0), axis=1, keepdims=True)
    solo = jnp.sum(jnp.where(ids == cand, hw, 0), axis=1, keepdims=True)
    out_ref[...] = jnp.concatenate(cols + [own, solo], axis=1)


def _row_pad(n: int):
    tile = min(ROW_TILE, -(-n // 8) * 8)                      # sublane-aligned
    return tile, -(-n // tile) * tile


def _cost(n: int, e: int) -> pl.CostEstimate:
    return pl.CostEstimate(
        flops=2 * n * e * (e + 2),                            # compare + add
        bytes_accessed=4 * n * e + 2 * n * e + 2 * 4 * n + 4 * n * (e + 2),
        transcendentals=0,
    )


@functools.partial(jax.jit, static_argnames=("interpret",))  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
def _kic_call(
    cand_nbr: jax.Array, hw: jax.Array, labels: jax.Array, interpret: bool
) -> jax.Array:
    n, e = cand_nbr.shape
    tile, n_pad = _row_pad(n)
    pad = n_pad - n
    node_ids = jnp.arange(n, dtype=jnp.int32)
    # padded rows use distinct negative sentinels so no padded candidate can
    # alias a real community id (their outputs are sliced away regardless)
    cand_p = jnp.pad(cand_nbr, ((0, pad), (0, 0)), constant_values=-1)
    hw_p = jnp.pad(hw, ((0, pad), (0, 0)))
    lab_p = jnp.pad(labels, (0, pad), constant_values=-2)[:, None]
    ids_p = jnp.pad(node_ids, (0, pad), constant_values=-3)[:, None]
    out = pl.pallas_call(
        functools.partial(_kernel, e=e),
        grid=(n_pad // tile,),
        in_specs=[
            pl.BlockSpec((tile, e), lambda i: (i, 0)),
            pl.BlockSpec((tile, e), lambda i: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((tile, e + 2), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, e + 2), jnp.int32),
        cost_estimate=_cost(n, e),
        interpret=interpret,
    )(cand_p, hw_p, lab_p, ids_p)
    return out[:n]


def pallas_leiden_kic(
    cand_nbr: jax.Array, hw: jax.Array, labels: jax.Array
) -> jax.Array:
    """int32 half-unit k_ic [n, e+2] — the fused-kernel twin of the
    ``_local_moves`` slab scan (e neighbour-candidate columns, then the
    own-community and solo columns), bit-identical by construction."""
    return _kic_call(
        jnp.asarray(cand_nbr, jnp.int32),
        jnp.asarray(hw, jnp.int16),
        jnp.asarray(labels, jnp.int32),
        _interpret(),
    )
