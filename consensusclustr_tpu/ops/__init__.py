"""Hand-written TPU kernels (Pallas) for the hot ops.

Every kernel here has a portable XLA twin that serves as its correctness
oracle (SURVEY §2.2); dispatch happens at the call sites based on backend and
the use_pallas config flag.
"""

from consensusclustr_tpu.ops.pallas_cocluster import pallas_coclustering_distance

__all__ = ["pallas_coclustering_distance"]
