"""Pallas TPU kernel for the co-clustering (consensus Jaccard) distance.

The bandwidth-lean variant of consensus/cocluster.py — the reference's inline
Armadillo kernel + parDist/OpenMP pass (reference R/consensusClust.R:411-421):

    dist(i, j) = 1 - #(L_i == L_j, both sampled) / #(both sampled)

The XLA einsum path one-hot encodes labels to ride the MXU, which round-trips
a [chunk, n, max_clusters] bf16 tensor through HBM per scan step. This kernel
instead tiles the n x n output over an (i, j, boot-block) grid and streams the
raw int8 label matrix: each program step holds two [BOOT_BLOCK, TILE] label
tiles in VMEM (~128 KB each at BOOT_BLOCK=512, TILE=256) and accumulates
agreement/valid counts in int32 VMEM scratch with VPU compares. The boot axis
is the innermost grid dimension, so arbitrarily large B (granular mode:
nboots x |k| x |res|) streams through fixed VMEM instead of residing whole —
no one-hot ever exists, and each output tile is written exactly once, fused
with the final 1 - agree/union division.

Mosaic constraint honored here: minor-dim insertion (`x[:, :, None]`) is only
supported for 32-bit types, so labels are widened to int32 *before* any
broadcast reshape and all mask algebra is int32 arithmetic — no i1/i8 vector
ever gets a new minor dimension (this exact pattern failed to compile in
round 2: `tpu.reshape vector<8x256xi1> -> vector<8x256x1xi1>`).

Numerical contract matches coclustering_distance exactly: never-co-sampled
pairs get distance 1, diagonal forced to 0.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE = 256          # output tile edge; multiple of the (32, 128) int8 tile
BOOT_BLOCK = 512    # boots streamed per grid step (int8 tile: 128 KB in VMEM)
BOOT_CHUNK = 8      # boots per VPU accumulation step inside a block


def _cocluster_kernel(li_ref, lj_ref, out_ref, agree_ref, union_ref):
    """li_ref/lj_ref: [boot_block, TILE] int8 label tiles (one boot block);
    out_ref: [TILE, TILE] f32; agree/union: int32 VMEM scratch accumulators
    that persist across the boot grid dimension (innermost, so the (i, j)
    output block is fixed while boot blocks stream)."""
    boot_block = li_ref.shape[0]
    # grid queries hoisted out of the pl.when closures: program_id inside a
    # when-body fails to lower in interpret mode (cond-wrapped primitive)
    nb = pl.num_programs(2)
    b = pl.program_id(2)
    i, j = pl.program_id(0), pl.program_id(1)

    @pl.when(b == 0)
    def _init():
        agree_ref[:] = jnp.zeros((TILE, TILE), jnp.int32)
        union_ref[:] = jnp.zeros((TILE, TILE), jnp.int32)

    def body(c, carry):
        agree, union = carry
        li = li_ref[pl.ds(c * BOOT_CHUNK, BOOT_CHUNK), :].astype(jnp.int32)
        lj = lj_ref[pl.ds(c * BOOT_CHUNK, BOOT_CHUNK), :].astype(jnp.int32)
        # int32 throughout: valid masks as 0/1 ints, equality applied via
        # where() — no boolean vector is ever reshaped (Mosaic i1 limit).
        vi = (li >= 0).astype(jnp.int32)                      # [C, T] int32
        vj = (lj >= 0).astype(jnp.int32)
        both = vi[:, :, None] * vj[:, None, :]                # [C, T, T] int32
        eq = jnp.where(li[:, :, None] == lj[:, None, :], both, 0)
        agree = agree + jnp.sum(eq, axis=0)
        union = union + jnp.sum(both, axis=0)
        return agree, union

    acc = (agree_ref[:], union_ref[:])
    agree, union = jax.lax.fori_loop(0, boot_block // BOOT_CHUNK, body, acc)
    agree_ref[:] = agree
    union_ref[:] = union

    @pl.when(b == nb - 1)
    def _finalize():
        jac = jnp.where(
            union > 0,
            agree.astype(jnp.float32) / jnp.maximum(union, 1).astype(jnp.float32),
            0.0,
        )
        dist = 1.0 - jac
        # zero the diagonal of diagonal-grid tiles
        rows = jax.lax.broadcasted_iota(jnp.int32, (TILE, TILE), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (TILE, TILE), 1)
        on_diag = (i == j) & (rows == cols)
        out_ref[:] = jnp.where(on_diag, 0.0, dist)


@functools.partial(jax.jit, static_argnames=("interpret",))
def pallas_coclustering_distance(
    labels: jax.Array, interpret: bool = False
) -> jax.Array:
    """labels: [B, n] integer assignments, -1 = unsampled. Returns [n, n]
    float32 co-clustering distance (diagonal 0, never-co-sampled pairs 1).

    Cluster ids must fit int8 (the engine's compact labels are bounded by
    max_clusters <= 127; -1 is the mask). Pads B to BOOT_BLOCK and n to TILE
    with -1, which contribute nothing to either count.
    """
    labels = jnp.asarray(labels)
    b, n = labels.shape
    # block the boot axis to BOOT_CHUNK granularity, capped at BOOT_BLOCK —
    # small B (robust mode: nboots ~ 100) pads to the next chunk, not to 512
    boot_block = min(BOOT_BLOCK, -(-b // BOOT_CHUNK) * BOOT_CHUNK)
    b_pad = -(-b // boot_block) * boot_block
    n_pad = -(-n // TILE) * TILE
    lab8 = jnp.full((b_pad, n_pad), -1, jnp.int8)
    lab8 = jax.lax.dynamic_update_slice(lab8, labels.astype(jnp.int8), (0, 0))

    # boot axis innermost: the (i, j) output block stays fixed in VMEM while
    # boot blocks stream past the scratch accumulators.
    grid = (n_pad // TILE, n_pad // TILE, b_pad // boot_block)
    out = pl.pallas_call(
        _cocluster_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (boot_block, TILE), lambda i, j, b: (b, i), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (boot_block, TILE), lambda i, j, b: (b, j), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (TILE, TILE), lambda i, j, b: (i, j), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((n_pad, n_pad), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((TILE, TILE), jnp.int32),
            pltpu.VMEM((TILE, TILE), jnp.int32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * b_pad * n_pad * n_pad,
            bytes_accessed=2 * b_pad * n_pad * (n_pad // TILE) + 4 * n_pad * n_pad,
            transcendentals=0,
        ),
        interpret=interpret,
    )(lab8, lab8)
    return out[:n, :n]
