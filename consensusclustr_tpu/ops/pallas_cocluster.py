"""Pallas TPU kernels for the co-clustering (consensus Jaccard) distance.

The bandwidth-lean variant of consensus/cocluster.py — the reference's inline
Armadillo kernel + parDist/OpenMP pass (reference R/consensusClust.R:411-421):

    dist(i, j) = 1 - #(L_i == L_j, both sampled) / #(both sampled)

The XLA einsum path one-hot encodes labels to ride the MXU, which round-trips
a [chunk, n, max_clusters] bf16 tensor through HBM per scan step. Both
kernels here instead tile the n x n output over an (i, j, boot-block) grid
and stream the raw int8 label matrix: each program step holds two
[BOOT_BLOCK, TILE] label tiles in VMEM (~128 KB each at BOOT_BLOCK=512,
TILE=256) and accumulates agreement/valid counts in VMEM scratch. The boot
axis is the innermost grid dimension, so arbitrarily large B (granular mode:
nboots x |k| x |res|) streams through fixed VMEM instead of residing whole —
no one-hot ever touches HBM, and each output tile is written exactly once,
fused with the final 1 - agree/union division.

Two variants (CCTPU_PALLAS_VARIANT=mxu|vpu, default mxu):

* ``mxu`` — builds the boot-chunk one-hot [CHUNK * n_classes, TILE] in bf16
  *inside VMEM* and turns both counts into MXU matmuls with f32 accumulation
  (integer-exact: every product is 0/1 and counts stay < 2^24, so parity
  with the einsum oracle is still bit-exact). This is the einsum path's
  math with its HBM round-trip amputated.
* ``vpu`` — the round-2-era compare-and-sum body: int32 mask algebra over
  [CHUNK, TILE, TILE] broadcasts on the VPU. First hardware measurement
  (docs/tpu_evidence_raw/pallas_parity.log, TPU v5e) put it ~50x off VPU
  peak and losing to einsum on tall few-boot shapes — kept as the
  known-compiles fallback and for A/B timing on chip.

Mosaic constraints honored here: minor-dim insertion (`x[:, :, None]`) is
only supported for 32-bit types, so labels are widened to int32 *before* any
broadcast reshape, and no i1/i8 vector ever gets a new minor dimension (this
exact pattern failed to compile in round 2: `tpu.reshape vector<8x256xi1>`).
The mxu one-hot reshape [C, NCLS, T] -> [C * NCLS, T] collapses major dims
only (minor dim untouched) on bf16, with NCLS padded to a multiple of 32 so
the collapse stays sublane-aligned.

Numerical contract matches coclustering_distance exactly: never-co-sampled
pairs get distance 1, diagonal forced to 0.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE = 256          # output tile edge; multiple of the (32, 128) int8 tile
BOOT_BLOCK = 512    # boots streamed per grid step (int8 tile: 128 KB in VMEM)
BOOT_CHUNK = 8      # boots per accumulation step inside a block

# Which variant the last pallas_coclustering_distance call resolved to
# ("mxu" | "vpu") — the reporting source of truth for bench.py, set where
# the resolution happens so env/default changes can't desynchronize it.
LAST_VARIANT: str = "mxu"


def _aligned_ncls(n_classes: int) -> int:
    """Sublane-aligned class count (multiple of 32, covering 0..n_classes-1).

    Loud contract (matches the block % TILE check): labels must fit int8 and
    the one-hot class axis is bounded at 128 — a larger request used to clamp
    silently, undercounting agreement for labels >= 128 on the mxu variant.
    Engine paths are gated upstream (max_clusters <= 127); this protects
    direct callers.
    """
    if int(n_classes) > 128:
        raise ValueError(
            f"n_classes ({n_classes}) exceeds the Pallas kernels' int8 label "
            "bound of 128; use the einsum path for larger max_clusters"
        )
    return max(32, -(-int(n_classes) // 32) * 32)


def _kernel_mxu(
    li_ref, lj_ref, out_ref, agree_ref, union_ref, *, n_classes, zero_diag
):
    """li_ref/lj_ref: [boot_block, TILE] int8 label tiles (one boot block);
    out_ref: [TILE, TILE] f32; agree/union: f32 VMEM scratch accumulators
    that persist across the boot grid dimension (innermost, so the (i, j)
    output block is fixed while boot blocks stream).

    agree[x, y] = sum_{b, c} 1[li[b, x] == c] * 1[lj[b, y] == c] is a single
    [TILE, K] x [K, TILE] contraction per boot chunk with K = CHUNK * NCLS;
    union[x, y] = sum_b 1[li[b, x] >= 0] * 1[lj[b, y] >= 0] a second one with
    K = CHUNK. Masked entries (-1) one-hot to the zero vector, so no
    validity multiply is needed on the agree side.
    """
    boot_block = li_ref.shape[0]
    nb = pl.num_programs(2)
    b = pl.program_id(2)
    i, j = pl.program_id(0), pl.program_id(1)

    @pl.when(b == 0)
    def _init():
        agree_ref[:] = jnp.zeros((TILE, TILE), jnp.float32)
        union_ref[:] = jnp.zeros((TILE, TILE), jnp.float32)

    one = jnp.bfloat16(1.0)
    zero = jnp.bfloat16(0.0)
    contract0 = (((0,), (0,)), ((), ()))  # sum over rows of both operands

    def body(c, carry):
        agree, union = carry
        li = li_ref[pl.ds(c * BOOT_CHUNK, BOOT_CHUNK), :].astype(jnp.int32)
        lj = lj_ref[pl.ds(c * BOOT_CHUNK, BOOT_CHUNK), :].astype(jnp.int32)
        cls = jax.lax.broadcasted_iota(
            jnp.int32, (BOOT_CHUNK, n_classes, TILE), 1
        )
        # [C, NCLS, T] bf16 one-hot, built and consumed entirely in VMEM
        ai = jnp.where(li[:, None, :] == cls, one, zero)
        aj = jnp.where(lj[:, None, :] == cls, one, zero)
        ai = ai.reshape(BOOT_CHUNK * n_classes, TILE)
        aj = aj.reshape(BOOT_CHUNK * n_classes, TILE)
        agree = agree + jax.lax.dot_general(
            ai, aj, contract0, preferred_element_type=jnp.float32
        )
        vi = jnp.where(li >= 0, one, zero)                    # [C, T] bf16
        vj = jnp.where(lj >= 0, one, zero)
        union = union + jax.lax.dot_general(
            vi, vj, contract0, preferred_element_type=jnp.float32
        )
        return agree, union

    acc = (agree_ref[:], union_ref[:])
    agree, union = jax.lax.fori_loop(0, boot_block // BOOT_CHUNK, body, acc)
    agree_ref[:] = agree
    union_ref[:] = union

    @pl.when(b == nb - 1)
    def _finalize():
        # agree/union hold exact integers in f32; the division below sees
        # the same operand values as the vpu variant's int->f32 casts, so
        # the result is bit-identical across variants and vs the oracle.
        jac = jnp.where(union > 0, agree / jnp.maximum(union, 1.0), 0.0)
        dist = 1.0 - jac
        if zero_diag:
            rows = jax.lax.broadcasted_iota(jnp.int32, (TILE, TILE), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (TILE, TILE), 1)
            on_diag = (i == j) & (rows == cols)
            dist = jnp.where(on_diag, 0.0, dist)
        out_ref[:] = dist


def _kernel_vpu(li_ref, lj_ref, out_ref, agree_ref, union_ref, *, zero_diag):
    """Compare-and-sum body (int32 VPU algebra, int32 scratch). See module
    docstring; kept verbatim from the first hardware-proven build."""
    boot_block = li_ref.shape[0]
    # grid queries hoisted out of the pl.when closures: program_id inside a
    # when-body fails to lower in interpret mode (cond-wrapped primitive)
    nb = pl.num_programs(2)
    b = pl.program_id(2)
    i, j = pl.program_id(0), pl.program_id(1)

    @pl.when(b == 0)
    def _init():
        agree_ref[:] = jnp.zeros((TILE, TILE), jnp.int32)
        union_ref[:] = jnp.zeros((TILE, TILE), jnp.int32)

    def body(c, carry):
        agree, union = carry
        li = li_ref[pl.ds(c * BOOT_CHUNK, BOOT_CHUNK), :].astype(jnp.int32)
        lj = lj_ref[pl.ds(c * BOOT_CHUNK, BOOT_CHUNK), :].astype(jnp.int32)
        # int32 throughout: valid masks as 0/1 ints, equality applied via
        # where() — no boolean vector is ever reshaped (Mosaic i1 limit).
        vi = (li >= 0).astype(jnp.int32)                      # [C, T] int32
        vj = (lj >= 0).astype(jnp.int32)
        both = vi[:, :, None] * vj[:, None, :]                # [C, T, T] int32
        eq = jnp.where(li[:, :, None] == lj[:, None, :], both, 0)
        agree = agree + jnp.sum(eq, axis=0)
        union = union + jnp.sum(both, axis=0)
        return agree, union

    acc = (agree_ref[:], union_ref[:])
    agree, union = jax.lax.fori_loop(0, boot_block // BOOT_CHUNK, body, acc)
    agree_ref[:] = agree
    union_ref[:] = union

    @pl.when(b == nb - 1)
    def _finalize():
        jac = jnp.where(
            union > 0,
            agree.astype(jnp.float32) / jnp.maximum(union, 1).astype(jnp.float32),
            0.0,
        )
        dist = 1.0 - jac
        if zero_diag:
            # zero the diagonal of diagonal-grid tiles
            rows = jax.lax.broadcasted_iota(jnp.int32, (TILE, TILE), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (TILE, TILE), 1)
            on_diag = (i == j) & (rows == cols)
            dist = jnp.where(on_diag, 0.0, dist)
        out_ref[:] = dist


def _pad_labels8(labels: jax.Array, b_pad: int, m_pad: int) -> jax.Array:
    lab8 = jnp.full((b_pad, m_pad), -1, jnp.int8)
    return jax.lax.dynamic_update_slice(lab8, labels.astype(jnp.int8), (0, 0))


def _rect_call(
    lab_rows8: jax.Array,   # [b_pad, m_pad] int8, -1 padded
    lab_cols8: jax.Array,   # [b_pad, n_pad] int8, -1 padded
    n_classes: int,
    variant: str,
    interpret: bool,
    zero_diag: bool,
    vma: tuple = (),
) -> jax.Array:
    """[m_pad, n_pad] distance from padded int8 label tiles (shared core of
    the square and rectangular entries). ``vma`` names the mesh axes the
    output varies over when called inside shard_map (pallas_call requires
    the out_shape's varying axes to be declared explicitly)."""
    b_pad, m_pad = lab_rows8.shape
    _, n_pad = lab_cols8.shape
    boot_block = min(BOOT_BLOCK, b_pad)
    if vma:
        out_shape = jax.ShapeDtypeStruct(
            (m_pad, n_pad), jnp.float32, vma=frozenset(vma)
        )
    else:
        out_shape = jax.ShapeDtypeStruct((m_pad, n_pad), jnp.float32)

    if variant == "mxu":
        kernel = functools.partial(
            _kernel_mxu, n_classes=n_classes, zero_diag=zero_diag
        )
        scratch_dtype = jnp.float32
        flops = 2 * b_pad * (n_classes + 1) * m_pad * n_pad
    else:
        kernel = functools.partial(_kernel_vpu, zero_diag=zero_diag)
        scratch_dtype = jnp.int32
        flops = 2 * b_pad * m_pad * n_pad

    # boot axis innermost: the (i, j) output block stays fixed in VMEM while
    # boot blocks stream past the scratch accumulators.
    grid = (m_pad // TILE, n_pad // TILE, b_pad // boot_block)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (boot_block, TILE), lambda i, j, b: (b, i), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (boot_block, TILE), lambda i, j, b: (b, j), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (TILE, TILE), lambda i, j, b: (i, j), memory_space=pltpu.VMEM
        ),
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((TILE, TILE), scratch_dtype),
            pltpu.VMEM((TILE, TILE), scratch_dtype),
        ],
        cost_estimate=pl.CostEstimate(
            flops=flops,
            bytes_accessed=b_pad * (m_pad + n_pad) * max(
                m_pad // TILE, n_pad // TILE
            ) + 4 * m_pad * n_pad,
            transcendentals=0,
        ),
        interpret=interpret,
    )(lab_rows8, lab_cols8)


@functools.partial(
    jax.jit, static_argnames=("n_classes", "variant", "interpret")  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
)
def _pallas_cocluster(
    labels: jax.Array, n_classes: int, variant: str, interpret: bool
) -> jax.Array:
    b, n = labels.shape
    # block the boot axis to BOOT_CHUNK granularity, capped at BOOT_BLOCK —
    # small B (robust mode: nboots ~ 100) pads to the next chunk, not to 512
    boot_block = min(BOOT_BLOCK, -(-b // BOOT_CHUNK) * BOOT_CHUNK)
    b_pad = -(-b // boot_block) * boot_block
    n_pad = -(-n // TILE) * TILE
    lab8 = _pad_labels8(labels, b_pad, n_pad)
    out = _rect_call(lab8, lab8, n_classes, variant, interpret, zero_diag=True)
    return out[:n, :n]


def pad_labels_int8(labels: jax.Array, n_pad: int) -> jax.Array:
    """[b_pad, n_pad] int8 labels, -1 padded, ready for the rows kernel.

    Call ONCE outside any tile loop (the conversion is loop-invariant but
    XLA is not guaranteed to hoist it out of a lax.map body). ``n_pad``
    must be a multiple of TILE and >= labels.shape[1].
    """
    b = labels.shape[0]
    boot_block = min(BOOT_BLOCK, -(-b // BOOT_CHUNK) * BOOT_CHUNK)
    b_pad = -(-b // boot_block) * boot_block
    return _pad_labels8(labels, b_pad, n_pad)


@functools.partial(
    jax.jit,  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
    static_argnames=("block", "n_classes", "variant", "interpret", "vma"),
)
def pallas_cocluster_rows(
    lab8: jax.Array,
    start: jax.Array,
    block: int,
    n_classes: int = 128,
    variant: str = "mxu",
    interpret: bool = False,
    vma: tuple = (),
) -> jax.Array:
    """[block, n_pad] co-clustering distance rows ``start .. start+block``
    against all cells — the blockwise consensus streamer's tile
    (consensus/blockwise.py) without its [chunk, n, n_classes] HBM one-hot.

    ``lab8`` comes from :func:`pad_labels_int8`. No diagonal zeroing: the
    caller owns self-pair handling (blockwise sets self-distance to inf for
    kNN, 0 for pair sums). Rows past the true ``n`` are padding (-1 labels,
    distance 1) and must be sliced off by the caller. ``block`` and
    ``start`` must be multiples of TILE.
    """
    b_pad, n_pad = lab8.shape
    if block % TILE:
        # loud: a non-multiple would floor-divide the grid and leave the
        # tail rows of the output uninitialized (silent wrong kNN edges)
        raise ValueError(f"block ({block}) must be a multiple of TILE ({TILE})")
    # same sublane-aligned class-count normalization as the square entry
    ncls = _aligned_ncls(n_classes)
    rows8 = jax.lax.dynamic_slice(
        lab8, (jnp.int32(0), jnp.asarray(start, jnp.int32)), (b_pad, block)
    )
    return _rect_call(
        rows8, lab8, ncls, variant, interpret, zero_diag=False, vma=vma
    )


def pallas_coclustering_distance(
    labels: jax.Array,
    n_classes: int = 128,
    variant: str | None = None,
    interpret: bool = False,
) -> jax.Array:
    """labels: [B, n] integer assignments, -1 = unsampled. Returns [n, n]
    float32 co-clustering distance (diagonal 0, never-co-sampled pairs 1).

    Cluster ids must fit int8 (the engine's compact labels are bounded by
    max_clusters <= 127; -1 is the mask); ``n_classes`` is an upper bound on
    label values (callers pass ClusterConfig-derived max_clusters — same
    contract as the einsum oracle's arange(max_clusters)). Pads B to the
    boot block and n to TILE with -1, which contribute nothing to either
    count. ``variant`` defaults to $CCTPU_PALLAS_VARIANT or "mxu"; resolved
    here, outside jit, so the env knob is honored per call.
    """
    global LAST_VARIANT
    if variant is None:
        variant = os.environ.get("CCTPU_PALLAS_VARIANT", "mxu")
    if variant not in ("mxu", "vpu"):
        raise ValueError(f"unknown pallas variant {variant!r}")
    LAST_VARIANT = variant
    # NCLS: cover labels 0..n_classes-1, sublane-aligned (multiple of 32),
    # int8 bound 128 (ValueError above that — no silent clamp). Padding
    # classes one-hot to zero columns — harmless.
    ncls = _aligned_ncls(n_classes)
    labels = jnp.asarray(labels)
    return _pallas_cocluster(labels, ncls, variant, interpret)
