"""Pallas TPU kernel for the co-clustering (consensus Jaccard) distance.

The bandwidth-lean variant of consensus/cocluster.py — the reference's inline
Armadillo kernel + parDist/OpenMP pass (reference R/consensusClust.R:411-421):

    dist(i, j) = 1 - #(L_i == L_j, both sampled) / #(both sampled)

The XLA einsum path one-hot encodes labels to ride the MXU, which round-trips
a [chunk, n, max_clusters] bf16 tensor through HBM per scan step. This kernel
instead tiles the n x n output over a (i, j) grid and streams the raw int8
label matrix: each program holds two [B, T] label tiles in VMEM (~0.5 MB at
B=1024, T=256) and accumulates agreement/valid counts with VPU compares over
boot chunks — no one-hot ever exists, and each output tile is written exactly
once, fused with the final 1 - agree/union division.

Numerical contract matches coclustering_distance exactly: never-co-sampled
pairs get distance 1, diagonal forced to 0.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE = 256          # output tile edge; multiple of the (32, 128) int8 tile
BOOT_CHUNK = 8      # boots per VPU accumulation step


def _cocluster_kernel(li_ref, lj_ref, out_ref):
    """li_ref/lj_ref: [B_pad, TILE] int8 label tiles; out_ref: [TILE, TILE] f32."""
    b_pad = li_ref.shape[0]

    def body(c, carry):
        agree, union = carry
        li = li_ref[pl.ds(c * BOOT_CHUNK, BOOT_CHUNK), :]     # [C, T] int8
        lj = lj_ref[pl.ds(c * BOOT_CHUNK, BOOT_CHUNK), :]
        vi = (li >= 0)[:, :, None]                            # [C, T, 1]
        vj = (lj >= 0)[:, None, :]                            # [C, 1, T]
        both = vi & vj                                        # [C, T, T]
        eq = (li[:, :, None] == lj[:, None, :]) & both
        agree = agree + jnp.sum(eq.astype(jnp.int32), axis=0)
        union = union + jnp.sum(both.astype(jnp.int32), axis=0)
        return agree, union

    zero = jnp.zeros((TILE, TILE), jnp.int32)
    agree, union = jax.lax.fori_loop(0, b_pad // BOOT_CHUNK, body, (zero, zero))

    jac = jnp.where(
        union > 0,
        agree.astype(jnp.float32) / jnp.maximum(union, 1).astype(jnp.float32),
        0.0,
    )
    dist = 1.0 - jac
    # zero the diagonal of diagonal-grid tiles
    i, j = pl.program_id(0), pl.program_id(1)
    rows = jax.lax.broadcasted_iota(jnp.int32, (TILE, TILE), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (TILE, TILE), 1)
    on_diag = (i == j) & (rows == cols)
    out_ref[:] = jnp.where(on_diag, 0.0, dist)


@functools.partial(jax.jit, static_argnames=("interpret",))
def pallas_coclustering_distance(
    labels: jax.Array, interpret: bool = False
) -> jax.Array:
    """labels: [B, n] integer assignments, -1 = unsampled. Returns [n, n]
    float32 co-clustering distance (diagonal 0, never-co-sampled pairs 1).

    Cluster ids must fit int8 (the engine's compact labels are bounded by
    max_clusters <= 127; -1 is the mask). Pads B to BOOT_CHUNK and n to TILE
    with -1, which contribute nothing to either count.
    """
    labels = jnp.asarray(labels)
    b, n = labels.shape
    b_pad = -(-b // BOOT_CHUNK) * BOOT_CHUNK
    n_pad = -(-n // TILE) * TILE
    lab8 = jnp.full((b_pad, n_pad), -1, jnp.int8)
    lab8 = jax.lax.dynamic_update_slice(lab8, labels.astype(jnp.int8), (0, 0))

    grid = (n_pad // TILE, n_pad // TILE)
    out = pl.pallas_call(
        _cocluster_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((b_pad, TILE), lambda i, j: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((b_pad, TILE), lambda i, j: (0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (TILE, TILE), lambda i, j: (i, j), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((n_pad, n_pad), jnp.float32),
        cost_estimate=pl.CostEstimate(
            flops=2 * b_pad * n_pad * n_pad,
            bytes_accessed=2 * b_pad * n_pad * (n_pad // TILE) + 4 * n_pad * n_pad,
            transcendentals=0,
        ),
        interpret=interpret,
    )(lab8, lab8)
    return out[:n, :n]
