"""The consensus layer driver (L5): bootstrap fan-out, co-clustering distance,
consensus re-clustering, merges.

Mirrors reference R/consensusClust.R:388-511 (SURVEY §3.1):

  bootstrap fan-out (:391-400)      -> vmapped cluster_grid over [B, m] gathers
  assignment matrix + NA->-1 (:404) -> int32 [B, n] with -1 masks
  C++ Jaccard + parDist (:411-421)  -> one batched einsum/Pallas pass
  consensus clustering (:423-441)   -> knn_from_distance -> SNN -> Leiden grid
  silhouette ranking on PCA (:445)  -> consensus_candidate_score
  small-cluster merge (:461-467)    -> merge_small_clusters on Jaccard dists
  stability merge (:469-497)        -> merge_unstable_clusters
  no-bootstrap path (:498-511)      -> single grid + Euclidean small-merge

Per-bootstrap failure semantics (reference :392-399 tryCatch -> all-ones): the
batched kernels cannot raise per boot; degenerate resamples produce the
single-cluster labelling naturally (scored 0), which is the same statistical
fallback (SURVEY §5 failure-detection row).
"""

from __future__ import annotations

import os
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from consensusclustr_tpu.config import ClusterConfig
from consensusclustr_tpu.cluster.engine import (
    DEFAULT_COMMUNITY_ITERS,
    align_to_cells,
    cluster_grid,
    community_detect,
    grid_fn,
    resolve_grid_impl,
    resolve_leiden_impl,
    resolve_snn_impl,
    ties_last_argmax as _ties_last_argmax,
)
from consensusclustr_tpu.cluster.knn import knn_candidates, knn_from_distance
from consensusclustr_tpu.cluster.leiden import _auto_kc as _leiden_auto_kc
from consensusclustr_tpu.cluster.leiden import compact_labels
from consensusclustr_tpu.cluster.metrics import mean_silhouette_score
from consensusclustr_tpu.cluster.engine import consensus_candidate_score
from consensusclustr_tpu.cluster.snn import snn_graph
from consensusclustr_tpu.consensus.bootstrap import bootstrap_indices
from consensusclustr_tpu.consensus.cocluster import (
    CoclusterAccumulator,
    SparseCoclusterAccumulator,
    _pallas_wanted,
    coclustering_distance,
)
from consensusclustr_tpu.consensus.merge import (
    merge_small_clusters,
    merge_unstable_clusters,
)
from consensusclustr_tpu.obs import maybe_span, metrics_of, tracer_of
from consensusclustr_tpu.obs.fingerprint import (
    BOOT_LABELS_CKPT,
    COCLUSTER_CKPT,
    CONSENSUS_DIST_CKPT,
    LABELS_CKPT,
    numeric_checkpoint,
)
from consensusclustr_tpu.obs.resource import resource_sampling
from consensusclustr_tpu.parallel.pipelined import (
    AsyncChunkWriter,
    ChunkPipeline,
    pipeline_depth,
)
from consensusclustr_tpu.resilience.inject import (
    BOOT_CHUNK_SITE,
    CKPT_READ_SITE,
    CKPT_WRITE_SITE,
)
from consensusclustr_tpu.resilience.retry import (
    resolve_retry_policy,
    retry_call,
)
from consensusclustr_tpu.utils.backend import default_backend as _default_backend
from consensusclustr_tpu.utils.compile_cache import counting_jit
from consensusclustr_tpu.utils.log import LevelLog
from consensusclustr_tpu.utils.rng import cluster_key


# Cells above which the auto-selected regime stops materialising the dense
# [n, n] consensus matrix (sparse_knn above, ISSUE 9; CCTPU_DENSE_CONSENSUS_LIMIT
# overrides — also the escape hatch the explicit-dense guard names).
DENSE_CONSENSUS_LIMIT = 16384

# The single-chip bootstrapped-consensus regimes (ClusterConfig.consensus_regime):
#   dense      — the [n, n] einsum oracle (streamed donated carries)
#   pallas     — the [n, n] regime with the Mosaic tile kernel forced
#   blockwise  — [block, n] streaming tiles, consensus kNN only (PR pre-9 scale path)
#   sparse_knn — kNN-restricted [n, m] accumulator, O(n·m) end to end (ISSUE 9)
CONSENSUS_REGIMES = ("dense", "pallas", "blockwise", "sparse_knn")

# Span-attr literals stamped on the candidates/cocluster spans (registered in
# obs/schema.py::CONSENSUS_SPAN_ATTRS; tools/check_obs_schema.py validates
# both directions — a renamed attr is a test failure, not a silently empty
# "== consensus ==" table in tools/report.py).
REGIME_ATTR = "consensus_regime"        # which regime assembled the consensus
CANDIDATE_M_ATTR = "candidate_m"        # sparse regime's per-cell candidate count
PAIRS_ATTR = "accumulated_pairs"        # pairs the accumulator tracked
PAIRS_RATIO_ATTR = "pairs_ratio"        # accumulated pairs / n^2
SNN_IMPL_ATTR = "snn_impl"              # which rank-scan backend built the SNN
SNN_REV_DROPPED_ATTR = "snn_rev_edges_dropped"  # reverse-slot collisions dropped
LEIDEN_IMPL_ATTR = "leiden_impl"        # which k_ic backend ran the local moves


def dense_consensus_limit() -> int:
    """The dense [n, n] cell ceiling: CCTPU_DENSE_CONSENSUS_LIMIT env
    override, else DENSE_CONSENSUS_LIMIT."""
    raw = os.environ.get("CCTPU_DENSE_CONSENSUS_LIMIT")
    if raw:
        try:
            return int(raw)
        except ValueError:
            pass
    return DENSE_CONSENSUS_LIMIT


def resolve_consensus_regime(cfg: ClusterConfig, n: int) -> str:
    """One of CONSENSUS_REGIMES for a single-chip bootstrapped consensus over
    ``n`` cells.

    Resolution: explicit ``cfg.consensus_regime`` wins; the legacy
    ``cfg.dense_consensus`` bool maps True -> dense / False -> blockwise;
    auto picks dense up to :func:`dense_consensus_limit` and sparse_knn
    above it (the ISSUE 9 default-at-scale switch).

    Footgun guard: a dense regime (explicit field OR legacy
    dense_consensus=True) above the limit raises loudly instead of
    silently materialising the [n, n] matrices and dying in an OOM —
    the error names the CCTPU_DENSE_CONSENSUS_LIMIT override for callers
    who really mean it. Auto never trips the guard.
    """
    limit = dense_consensus_limit()
    regime = cfg.consensus_regime
    if regime is None:
        if cfg.dense_consensus is not None:
            regime = "dense" if cfg.dense_consensus else "blockwise"
        else:
            return "dense" if n <= limit else "sparse_knn"
    if regime in ("dense", "pallas") and n > limit:
        gb = 2 * n * n * 4 / 1e9
        raise ValueError(
            f"dense consensus at n={n} cells would materialise two [n, n] "
            f"count carries (~{gb:.1f} GB) — refusing above "
            f"DENSE_CONSENSUS_LIMIT={limit}. Use "
            f"consensus_regime='sparse_knn' (O(n*m), the at-scale default) "
            f"or 'blockwise', or raise the CCTPU_DENSE_CONSENSUS_LIMIT env "
            f"var to force the dense path anyway."
        )
    return regime


def resolve_candidate_m(cfg: ClusterConfig, n: int, k_list) -> int:
    """Per-cell candidate-set width for the sparse regime:
    ``cfg.sparse_knn_candidates`` or ``max(64, 2 * max(k))``, never below
    the largest consensus-graph k (the grid needs that many neighbours) and
    never above n - 1 (self excluded)."""
    m = cfg.sparse_knn_candidates
    if m is None:
        m = max(64, 2 * max(k_list))
    m = max(int(m), max(k_list))
    return max(2, min(m, n - 1))


def resolve_boots_per_program(cfg: ClusterConfig) -> int:
    """Inner vmap width for ``_boot_batch`` (ISSUE 20's multi-boot batched
    programs, inverted: the knob narrows the per-program working set by
    scanning groups of this many boots inside one dispatch).

    Resolution: explicit ``cfg.boots_per_program`` wins, then the
    CCTPU_BOOTS_PER_PROGRAM env var; 0 (the default) disables the scan
    wrapper and keeps the historical one-vmap-per-chunk HLO exactly.
    Bit-identical either way — vmap is an exact map — so this is a pure
    bytes/latency trade, not a semantics knob."""
    if cfg.boots_per_program is not None:
        return int(cfg.boots_per_program)
    raw = os.environ.get("CCTPU_BOOTS_PER_PROGRAM")
    if raw:
        try:
            return max(0, int(raw))
        except ValueError:
            pass
    return 0


class SparseConsensus(NamedTuple):
    """The sparse regime's restricted-count state, carried on ConsensusResult
    so downstream consumers (small-cluster merge, dendrogram, serving
    stability diagonal) stay O(n·m) instead of re-streaming O(n²) tiles."""

    cand_idx: np.ndarray   # [n, m] int32 candidate-neighbour sets
    agree: np.ndarray      # [n, m] f32 integer agree counts
    union: np.ndarray      # [n, m] f32 integer union counts
    m: int                 # candidate count per cell


class ConsensusResult(NamedTuple):
    labels: np.ndarray                 # [n] compact consensus labels
    silhouette: float                  # mean approx-silhouette of labels on PCA
    jaccard_dist: Optional[np.ndarray]  # [n, n] co-clustering distance (None if
    #                                     nboots<=1 OR a non-dense regime ran)
    boot_labels: Optional[np.ndarray]   # [B(,*K*R), n] aligned boot assignments
    n_clusters: int
    regime: str = "dense"               # CONSENSUS_REGIMES entry that ran
    sparse: Optional[SparseConsensus] = None  # sparse_knn regime state


@counting_jit(
    static_argnames=(
        "k_list", "n_res", "max_clusters", "n_iters", "robust", "n_cells",
        "cluster_fun", "compute_dtype", "grid_impl", "snn_impl",
        "leiden_impl", "boots_per_program",
    ),
)
def _boot_batch(
    keys: jax.Array,          # [chunk]
    idx: jax.Array,           # [chunk, m]
    pca: jax.Array,           # [n, d]
    res_list: jax.Array,      # [R]
    k_list,
    min_size: jax.Array,
    n_res: int,
    max_clusters: int,
    n_iters: int,
    robust: bool,
    n_cells: int,
    cluster_fun: str = "leiden",
    compute_dtype: str = "float32",
    grid_impl: str = "fused",
    snn_impl: str = "jax",
    leiden_impl: str = "jax",
    boots_per_program: int = 0,
):
    """One jitted chunk of bootstraps: gather -> grid -> select -> align.

    ``grid_impl`` routes through the fused vmapped-k grid (production) or
    the per-k looped parity oracle (cluster/engine.py) — bit-identical
    outputs by contract, so flipping it (CCTPU_GRID_IMPL, exercised by
    tools/parity_audit.py ``--pair fused:looped``) must not move a single
    numeric checkpoint. ``snn_impl`` routes the SNN rank scan the same way
    (jax lax.scan vs the fused pallas kernel, ``--pair snn_jax:snn_pallas``
    — also bit-identical by contract), and ``leiden_impl`` routes the Leiden
    local-move k_ic sweep (jax slab scan vs the VMEM-resident pallas kernel,
    ``--pair leiden_jax:leiden_pallas``).

    ``boots_per_program`` (ISSUE 20, CCTPU_BOOTS_PER_PROGRAM /
    ClusterConfig.boots_per_program) narrows the vmapped boot axis INSIDE the
    program: when 0 < bpp < chunk and chunk % bpp == 0, the chunk runs as a
    lax.scan over chunk/bpp groups of a width-bpp vmap instead of one
    width-chunk vmap. vmap is an exact map, so per-boot outputs are
    bit-identical either way; but the program's working set — and, because
    scan bodies are counted ONCE by the work ledger's pre-optimization byte
    harvest, its est_bytes — scales with bpp instead of chunk. Dispatch and
    chunk accounting are untouched: still one program per chunk, same
    ChunkPipeline, same checkpoint layout. Default 0 keeps today's HLO
    exactly (pure vmap, no scan wrapper)."""

    def one(key_b, idx_b):
        x = pca[idx_b]
        grid = grid_fn(grid_impl)(
            key_b, x, res_list, k_list, min_size,
            max_clusters=max_clusters, n_iters=n_iters, cluster_fun=cluster_fun,
            compute_dtype=compute_dtype, snn_impl=snn_impl,
            leiden_impl=leiden_impl,
        )
        if robust:
            best = _ties_last_argmax(grid.scores)
            labels = grid.labels[best]                       # [m]
            aligned = align_to_cells(labels, idx_b, n_cells)  # [n]
            return aligned, grid.scores[best]
        aligned = align_to_cells(grid.labels, idx_b, n_cells)  # [n_cand, n]
        return aligned, grid.scores

    rows = keys.shape[0]
    bpp = boots_per_program
    if bpp and 0 < bpp < rows and rows % bpp == 0:
        keys_g = keys.reshape(rows // bpp, bpp, *keys.shape[1:])
        idx_g = idx.reshape(rows // bpp, bpp, *idx.shape[1:])

        def group(_, kb):
            return _, jax.vmap(one)(*kb)

        _, outs = jax.lax.scan(group, None, (keys_g, idx_g))
        return jax.tree.map(
            lambda a: a.reshape((rows,) + a.shape[2:]), outs
        )
    return jax.vmap(one)(keys, idx)


def _auto_boot_chunk(
    n: int, m: int, nboots: int, requested: int, n_res: int, k_max: int,
    n_k: int = 1,
) -> int:
    if requested > 0:
        return max(1, min(requested, nboots))
    # Bound the per-chunk workspace: the blockwise kNN row tile plus the
    # Leiden local-move working set per grid candidate — the [m, slab, e]
    # equality-slab transient plus ~8 [m, e] gather/gain buffers (e = 2k_max
    # edge slots), vmapped over the FUSED [n_k, n_res] candidate grid (the
    # batched-k cluster_grid runs every k concurrently, so the k axis
    # multiplies the live working set where the old per-k loop paid it
    # sequentially). The TPU runtime hard-crashes (not OOMs gracefully) when
    # pushed, so track a conservative budget against the 16 GB HBM.
    from consensusclustr_tpu.cluster.knn import KNN_BLOCK
    from consensusclustr_tpu.cluster.leiden import _SLAB, _auto_kc

    e = 2 * k_max
    n_cand = n_res * max(1, n_k)
    knn_bytes = (m * m if m <= 2 * KNN_BLOCK else KNN_BLOCK * m) * 4.0
    # coarse community-merge phase: ~6 live [kc, kc] f32 matrices per
    # grid-candidate instance (big_w, its transpose-fold, gain, outer(k_deg))
    kc = min(_auto_kc(m), m)
    coarse_bytes = n_cand * kc * kc * 4.0 * 6.0
    per_boot = knn_bytes + coarse_bytes + n_cand * m * e * 4.0 * (8.0 + _SLAB)
    backend = _default_backend()
    on_cpu = backend == "cpu"
    budget = float(os.environ.get("CCTPU_CHUNK_BYTES", 2e9 if on_cpu else 6e9))
    # TPU cap: XLA compile time grows superlinearly with the vmapped boot
    # axis, and the serving tunnel kills calls that stall past ~2 min — a
    # chunk of 8 compiles in ~70 s and is also the warm-throughput sweet spot
    # (larger chunks LOWER boots/sec; measured on v5e). CCTPU_MAX_CHUNK
    # overrides for untunneled pods. The cap is TPU-specific — other
    # accelerators keep the budget-derived chunk.
    cap = int(os.environ.get("CCTPU_MAX_CHUNK", 8 if backend == "tpu" else 64))
    return int(max(1, min(nboots, budget // max(per_boot, 1.0), cap)))


def run_bootstraps(
    key, pca, cfg: ClusterConfig, log: Optional[LevelLog] = None,
    accumulator: Optional[CoclusterAccumulator] = None,
):
    """All bootstrap clusterings, chunked over the boot axis.

    Returns (boot_labels [B_eff, n] int32 with -1 for unsampled, scores).
    In granular mode B_eff = nboots * |k_num| * |res_range| (reference keeps
    every candidate, :688).

    With cfg.checkpoint_dir set, each completed chunk is persisted and a rerun
    with identical (pca, config, seed) resumes at the first missing chunk
    (SURVEY §5 checkpoint row). Granular mode checkpoints the flattened
    candidate axis — |k_num| * |res_range| rows per boot — so the grid shape
    is part of the fingerprint.

    ``accumulator`` (a CoclusterAccumulator or SparseCoclusterAccumulator —
    anything with ``update(labels [rows, n])``) streams each chunk's aligned
    labels into the donated co-clustering counts the moment the chunk is
    enqueued: computed chunks feed their DEVICE label batch (the accumulator
    update rides the async stream behind the chunk itself — no host round
    trip), resumed chunks feed their host rows. Totals are integer counts, so
    the result is bit-identical to a one-shot pass over all rows.
    """
    n, _ = pca.shape
    m = max(2, int(round(cfg.boot_size * n)))
    idx = bootstrap_indices(key, n, cfg.nboots, m)
    res_list = jnp.asarray(list(cfg.res_range), jnp.float32)
    k_list = tuple(int(k) for k in cfg.k_num)
    robust = cfg.mode == "robust"
    grid_impl = resolve_grid_impl()
    snn_impl = resolve_snn_impl()
    leiden_impl = resolve_leiden_impl()
    bpp = resolve_boots_per_program(cfg)
    chunk = _auto_boot_chunk(
        n, m, cfg.nboots, cfg.boot_batch, len(cfg.res_range), max(k_list),
        n_k=len(k_list),
    )

    mets = metrics_of(log)
    # Bounded retries around every fault site this driver owns (ISSUE 10):
    # chunk dispatch, checkpoint read, checkpoint write. Dispatch and load
    # are pure functions of their inputs, so a retried chunk is bit-identical
    # to a first-try one — the chaos audit (tools/chaos_audit.py) pins it.
    rpol = resolve_retry_policy(cfg.retry_attempts)
    ckpt = None
    rows_per_boot = 1 if robust else len(k_list) * len(cfg.res_range)
    if cfg.checkpoint_dir:
        from consensusclustr_tpu.utils.checkpoint import (
            BootCheckpoint,
            run_fingerprint,
        )

        fp = run_fingerprint(
            np.asarray(pca),
            {
                "mode": cfg.mode,
                "nboots": cfg.nboots, "boot_size": cfg.boot_size,
                "k_num": list(k_list), "res_range": list(cfg.res_range),
                # Chunk size is deliberately NOT hashed: per-boot labels are
                # chunk-size-invariant, and load_chunk validates each chunk's
                # row count, so a resume under a different CCTPU_MAX_CHUNK /
                # platform budget reuses whatever aligned chunks exist instead
                # of orphaning the whole run (ADVICE r4).
                "max_clusters": cfg.max_clusters,
                # anything _boot_batch's output depends on must be hashed, or
                # a resume silently reuses chunks from a different algorithm
                "cluster_fun": cfg.cluster_fun,
                "compute_dtype": cfg.compute_dtype,
                "n_iters": DEFAULT_COMMUNITY_ITERS,
                "k_coarse": _leiden_auto_kc(m),
                # the fused [K, R] grid runs Leiden on padded [m, 2*k_max]
                # slot graphs — per-boot labels differ from the pre-fusion
                # per-k loop's, so old chunks must not resume into a fused run
                "grid": "fused-kmask-v1",
            },
            np.asarray(jax.random.key_data(key)).tobytes(),
        )
        ckpt = BootCheckpoint(
            cfg.checkpoint_dir, fp, cfg.nboots, n,
            rows_per_boot=rows_per_boot, metrics=mets, log=log,
        )

    keys = jax.vmap(lambda b: cluster_key(key, 50_000 + b))(jnp.arange(cfg.nboots, dtype=jnp.int32))
    depth = pipeline_depth(cfg.pipeline_depth)
    # one-time upload: the per-chunk jnp.asarray this replaces re-staged the
    # [n, d] matrix on every iteration when a caller passed a host array
    pca_dev = jax.device_put(jnp.asarray(pca, jnp.float32))
    out_labels, out_scores = [], []
    # Checkpoint serialization rides a background writer so disk IO never
    # sits on the dispatch path; depth 1 keeps the synchronous write (serial
    # behavior reproduced exactly). save_chunk stays atomic (tmp + replace)
    # on the writer thread, so no torn files either way.
    writer = AsyncChunkWriter() if (ckpt is not None and depth > 1) else None

    def _feed_accumulator(ent):
        # Donated-carry co-clustering accumulation at enqueue time (ISSUE 5):
        # computed chunks hand their device label batch straight to the
        # accumulator update (async, behind the chunk's own execution);
        # resumed chunks hand their host rows. Chunk order == boot order, and
        # the counts are integers, so the totals are order-exact either way.
        labels_part = ent.peek()[0]
        accumulator.update(jnp.asarray(labels_part, jnp.int32).reshape(-1, n))

    pipe = ChunkPipeline(
        depth, metrics=mets,
        on_enqueue=_feed_accumulator if accumulator is not None else None,
        site=BOOT_CHUNK_SITE, retry=rpol, log=log,
    )

    def _save_chunk(s2: int, labels2, scores2) -> None:
        # checkpoint write under the retry policy (runs on the writer thread
        # at depth > 1); exhaustion latches into the writer and fails the run
        # within one chunk, exactly as an unretried write error did
        retry_call(
            lambda: ckpt.save_chunk(s2, labels2, scores2),
            site=CKPT_WRITE_SITE, policy=rpol, metrics=mets, log=log,
        )

    def _load_chunk(s2: int, size: int):
        # checkpoint read under the retry policy. A chunk that stays
        # unreadable after the last attempt is treated as MISSING (the
        # checkpoint is a cache — recomputing is always correct, dying on a
        # bad cache never is); retry_call already counted retries_exhausted
        # and emitted the event naming the site.
        try:
            return retry_call(
                lambda: ckpt.load_chunk(s2, size),
                site=CKPT_READ_SITE, policy=rpol, metrics=mets, log=log,
            )
        except Exception:  # graftlint: noqa[GL007] checkpoint read failure degrades to recompute; the retry layer already logged the attempts
            return None

    def _consume(ent):
        s, e = ent.meta
        if ent.ready:  # checkpoint-resume chunk, already host data
            cached = ent.fetch()
            if robust:
                out_labels.append(cached[0])
                out_scores.append(cached[1])
            else:  # chunks store the flattened candidate axis
                out_labels.append(cached[0].reshape(e - s, rows_per_boot, n))
                out_scores.append(cached[1].reshape(e - s, rows_per_boot))
            mets.counter("boots_resumed").inc(e - s)
            # same normalized [rows, n] view as the computed branch, so a
            # resumed run's checkpoint stream matches a fresh one exactly
            numeric_checkpoint(
                log, BOOT_LABELS_CKPT,
                lambda: np.asarray(cached[0]).reshape(-1, n).astype(np.int32),
            )
            if log:
                log.event("boots_resumed", done=e, total=cfg.nboots)
            return
        labels_np, scores_np = ent.fetch()
        out_labels.append(labels_np)
        out_scores.append(scores_np)
        numeric_checkpoint(
            log, BOOT_LABELS_CKPT,
            lambda: np.asarray(labels_np).reshape(-1, n).astype(np.int32),
        )
        mets.counter("boots_completed").inc(e - s)
        mets.counter("leiden_iters").inc(
            (e - s) * len(k_list) * len(cfg.res_range) * DEFAULT_COMMUNITY_ITERS
        )
        # dispatch -> fetch-complete latency: identical to the old serial
        # timing at depth 1; includes overlapped device time at depth > 1
        mets.histogram("boot_chunk_seconds").observe(ent.latency_seconds)
        if ckpt is not None:
            payload = (s, labels_np.reshape(-1, n), scores_np.reshape(-1))
            if writer is not None:
                writer.submit(_save_chunk, *payload)
            else:
                _save_chunk(*payload)
        if log:
            log.event("boots", done=e, total=cfg.nboots)

    # Stall watchdog over the boot loop (obs/flight.py, ISSUE 14): the
    # deadline self-tunes from the boot_chunk_seconds histogram once it has
    # samples (p99 x factor per chunk), the cfg/env floor covers the cold
    # first chunk, and tick() re-arms per iteration — a wedged dispatch
    # gets a stall_detected event + all-thread stack dump instead of a
    # silent hang. Inert (one env check) under CCTPU_NO_FLIGHT=1.
    from consensusclustr_tpu.obs.flight import stall_watch

    with maybe_span(
        log, "boots", nboots=cfg.nboots, chunk=chunk, pipeline_depth=depth
    ) as bsp, stall_watch(
        log, "boot_chunk",
        hist=mets.histograms.get("boot_chunk_seconds"),
        floor_s=cfg.stall_floor_s,
    ) as watch:
        try:
            for s in range(0, cfg.nboots, chunk):
                watch.tick()
                e = min(s + chunk, cfg.nboots)
                if ckpt is not None:
                    cached = _load_chunk(s, e - s)
                    if cached is not None:
                        pipe.put_ready(s, cached, meta=(s, e))
                        continue
                for ent in pipe.ready_for_dispatch():
                    _consume(ent)
                # min_size=0: the reference never passes its minSize into the
                # boot grids (:394-395 vs :650's minSize=0 default) — the 0.15
                # floor is inert here and only bites in the null sims
                # (minSize=5).
                # grid_impl is passed explicitly (it was resolved above but
                # dropped before ISSUE 10, so CCTPU_GRID_IMPL=looped silently
                # kept running the fused program — the fused:looped parity
                # pair now actually flips the implementation)
                pipe.dispatch(
                    s,
                    lambda s=s, e=e: _boot_batch(
                        keys[s:e], idx[s:e], pca_dev, res_list, k_list,
                        jnp.float32(0.0),
                        len(cfg.res_range), cfg.max_clusters,
                        DEFAULT_COMMUNITY_ITERS,
                        robust, n, cfg.cluster_fun, cfg.compute_dtype,
                        grid_impl, snn_impl, leiden_impl, bpp,
                    ),
                    meta=(s, e),
                )
            for ent in pipe.drain():
                _consume(ent)
        except BaseException:
            # drain in-flight work and the writer queue so the ORIGINAL
            # exception surfaces (not a later async leak / torn shutdown)
            pipe.abort()
            if writer is not None:
                writer.close(raise_errors=False)
            raise
        if writer is not None:
            writer.close()  # re-raises a latched checkpoint-write error
        bsp.set(
            overlap_seconds=round(pipe.overlap_seconds, 4),
            max_inflight=pipe.max_inflight,
        )
        labels = np.concatenate(out_labels, axis=0)
        scores = np.concatenate(out_scores, axis=0)
    if not robust:
        labels = labels.reshape(-1, n)                      # [B*K*R, n]
        scores = scores.reshape(-1)
    return labels, scores


@counting_jit(
    static_argnames=(
        "k_list", "max_clusters", "n_iters", "cluster_fun", "snn_impl",
        "leiden_impl",
    )
)
def _consensus_grid_from_knn(
    key: jax.Array,
    knn_idx: jax.Array,  # [n, max(k_list)] kNN of the consensus distance
    pca: jax.Array,      # [n, d] for silhouette ranking
    res_list: jax.Array,
    k_list,
    max_clusters: int,
    n_iters: int = DEFAULT_COMMUNITY_ITERS,
    cluster_fun: str = "leiden",
    snn_impl: str = "jax",
    leiden_impl: str = "jax",
):
    """Consensus re-clustering (reference :423-441) from a precomputed kNN
    graph: SNN + Leiden per (k, resolution); rank by PCA silhouette with the
    all-singletons -> -1 floor (:445-453). Smaller-k graphs are prefixes of
    the max-k one (top_k order is deterministic), so one kNN pass serves the
    whole k sweep — and the dense and blockwise paths share this function,
    which makes them select identical candidates.

    Also returns the summed reverse-edge collision count over the k sweep
    (SNNGraph.rev_dropped) so the host can surface the
    snn_rev_edges_dropped counter/span attr without re-running the build."""
    r = res_list.shape[0]
    all_labels, all_scores = [], []
    rev_dropped = jnp.int32(0)
    for ki, k in enumerate(k_list):
        graph = snn_graph(knn_idx[:, :k], snn_impl=snn_impl)
        rev_dropped = rev_dropped + graph.rev_dropped
        keys = jax.vmap(lambda t: cluster_key(key, 90_000 + ki * 1000 + t))(jnp.arange(r, dtype=jnp.int32))

        def one_res(kk, res):
            raw = community_detect(
                kk, graph, res, cluster_fun, n_iters=n_iters,
                leiden_impl=leiden_impl,
            )
            compact, n_c, overflow = compact_labels(raw, max_clusters)
            score = consensus_candidate_score(pca, compact, n_c, overflow, max_clusters)
            return compact, score

        labels_k, scores_k = jax.vmap(one_res)(keys, res_list)
        all_labels.append(labels_k)
        all_scores.append(scores_k)
    labels = jnp.concatenate(all_labels, axis=0)
    scores = jnp.concatenate(all_scores, axis=0)
    # ties to the FIRST tied candidate: the reference ranks with
    # ties.method="last" here (:453), under which the max rank lands on the
    # first occurrence — the opposite of the boot path's "first"/last pairing.
    best = jnp.argmax(scores)
    return labels[best], scores, rev_dropped


def _consensus_grid(
    key: jax.Array,
    dist: jax.Array,     # [n, n] jaccard distance
    pca: jax.Array,
    res_list: jax.Array,
    k_list,
    max_clusters: int,
    n_iters: int = DEFAULT_COMMUNITY_ITERS,
    cluster_fun: str = "leiden",
    snn_impl: str = "jax",
    leiden_impl: str = "jax",
):
    """Dense-matrix entry: one kNN pass at max k, then the shared grid."""
    idx, _ = knn_from_distance(dist, max(k_list))
    return _consensus_grid_from_knn(
        key, idx, pca, res_list, k_list, max_clusters, n_iters, cluster_fun,
        snn_impl=snn_impl, leiden_impl=leiden_impl,
    )


def _resolve_mesh(cfg: ClusterConfig, n: int, log: Optional[LevelLog] = None):
    """Resolve cfg.mesh to a usable Mesh or None (single-chip).

    Falls back (with a log event) when the level cannot shard: nboots<=1,
    a 1-device mesh, or n not divisible by the cell axis. Robust AND
    granular modes both shard.
    """
    m = cfg.mesh
    if m is None:
        return None
    auto = False
    if isinstance(m, str):
        if m != "auto":
            raise ValueError(f"mesh must be None, 'auto' or a Mesh; got {m!r}")
        if len(jax.devices()) <= 1:
            return None
        from consensusclustr_tpu.parallel.mesh import consensus_mesh

        auto = True
        m = consensus_mesh()
    reason = None
    if cfg.nboots <= 1:
        reason = "nboots<=1"
    else:
        from consensusclustr_tpu.parallel.mesh import CELL_AXIS, consensus_mesh

        if n % m.shape[CELL_AXIS]:
            if auto:
                # a boot-only mesh always satisfies divisibility; keep the
                # bootstrap fan-out sharded rather than idling every device
                m = consensus_mesh(boot=len(jax.devices()), cell=1)
                if log:
                    log.event("mesh_auto_boot_only", n=n)
            else:
                reason = (
                    f"n={n} not divisible by cell axis {m.shape[CELL_AXIS]}"
                )
    if reason is not None:
        metrics_of(log).counter("mesh_fallbacks").inc()
        if log:
            log.event("mesh_fallback", reason=reason)
        return None
    return m


def _finish_consensus(
    pca: jax.Array,
    labels: np.ndarray,
    dist_np: Optional[np.ndarray],
    boot_labels: np.ndarray,
    cfg: ClusterConfig,
    k_list,
    log: Optional[LevelLog],
    regime: str = "dense",
    sparse: Optional[SparseConsensus] = None,
) -> ConsensusResult:
    """Shared tail of the bootstrap paths: small-cluster merge (:461-467),
    stability merge (:469-497), final silhouette.

    dist_np=None is a streaming regime: the small-cluster merge runs on the
    sparse regime's restricted pair stats (O(n·m), the counts are already in
    hand) or on blockwise cluster-pair tile sums, instead of the dense
    matrix."""
    with maybe_span(log, "merge"):
        if dist_np is not None:
            # small-cluster merge on co-clustering distances (:461-467)
            labels = merge_small_clusters(
                dist_np, labels, max(k_list[0], 20), cfg.max_clusters
            )
        elif sparse is not None:
            from consensusclustr_tpu.consensus.merge import (
                merge_small_clusters_from_pair_stats,
                restricted_pair_stats,
            )

            sums, pair_counts = restricted_pair_stats(
                jnp.asarray(sparse.agree), jnp.asarray(sparse.union),
                jnp.asarray(sparse.cand_idx), jnp.asarray(labels, jnp.int32),
                cfg.max_clusters,
            )
            labels = merge_small_clusters_from_pair_stats(
                np.asarray(sums), np.asarray(pair_counts), labels,
                max(k_list[0], 20),
            )
        else:
            from consensusclustr_tpu.consensus.blockwise import (
                cocluster_pair_sums,
                merge_small_clusters_from_sums,
            )

            sums, counts = cocluster_pair_sums(
                jnp.asarray(boot_labels, jnp.int32), jnp.asarray(labels, jnp.int32),
                cfg.max_clusters, cfg.max_clusters, use_pallas=cfg.use_pallas,
            )
            labels = merge_small_clusters_from_sums(
                np.asarray(sums), np.asarray(counts), labels, max(k_list[0], 20)
            )
        # stability merge against the per-boot assignments (:469-497)
        labels = merge_unstable_clusters(
            labels, boot_labels, cfg.min_stability, cfg.max_clusters
        )
        numeric_checkpoint(
            log, LABELS_CKPT, lambda: np.asarray(labels, np.int32)
        )
        sil = float(mean_silhouette_score(pca, jnp.asarray(labels), cfg.max_clusters))
    metrics_of(log).gauge("silhouette_best").set(sil)
    if log:
        log.event(
            "merged", n_clusters=len(np.unique(labels)), silhouette=sil,
        )
    return ConsensusResult(
        labels=labels,
        silhouette=sil,
        jaccard_dist=dist_np,
        boot_labels=boot_labels,
        n_clusters=len(np.unique(labels)),
        regime=regime,
        sparse=sparse,
    )


def consensus_cluster(
    key, pca, cfg: ClusterConfig, log: Optional[LevelLog] = None
) -> ConsensusResult:
    """Full L5: reference :388-511. With cfg.mesh set, the bootstrap fan-out,
    co-clustering distance and consensus grid run sharded over the device mesh
    (parallel/step.py); the merge/stability tail is identical either way."""
    pca = jnp.asarray(pca, jnp.float32)
    n = pca.shape[0]
    res_list = jnp.asarray(list(cfg.res_range), jnp.float32)
    k_list = tuple(int(k) for k in cfg.k_num)

    # Direct callers (bench's granular rung, tests) get the numerics layer
    # without going through api.consensus_clust: attach to their tracer when
    # the level asks for it and nothing is attached yet (same courtesy the
    # resource bracket below extends). An api-attached monitor is reused.
    _tr = tracer_of(log)
    if _tr is not None and getattr(_tr, "numerics", None) is None:
        from consensusclustr_tpu.obs.fingerprint import attach_numerics

        attach_numerics(_tr, cfg.numerics)
    # Same courtesy for the work ledger (obs/ledger.py, ISSUE 12) —
    # attach_ledger is idempotent, so an api-attached ledger is reused.
    if _tr is not None:
        from consensusclustr_tpu.obs.ledger import attach_ledger

        attach_ledger(_tr)

    mesh = _resolve_mesh(cfg, n, log)
    if mesh is not None:
        from consensusclustr_tpu.parallel.mesh import BOOT_AXIS, CELL_AXIS
        from consensusclustr_tpu.parallel.step import (
            distributed_consensus_cluster,
        )

        # The mesh path has no sparse regime yet (ROADMAP O2): an explicit
        # sparse_knn/blockwise request maps to the sharded blockwise
        # streaming path, dense/pallas to the sharded dense assembly. The
        # explicit-dense footgun guard does not apply here — sharded dense
        # spreads the [n, n] rows across devices by design.
        if cfg.consensus_regime is not None:
            dense = cfg.consensus_regime in ("dense", "pallas")
        else:
            dense = cfg.dense_consensus
            if dense is None:
                dense = n <= dense_consensus_limit()
        with maybe_span(
            log, "consensus_distributed",
            mesh={k: v for k, v in mesh.shape.items()},
        ):
            labels_np, dist_np, boot_labels = distributed_consensus_cluster(
                key, pca, cfg, mesh, dense=dense, log=log
            )
        if log:
            log.event(
                "consensus_distributed",
                n_clusters=len(np.unique(labels_np)),
                mesh={k: v for k, v in mesh.shape.items()},
            )
        return _finish_consensus(
            pca, labels_np, dist_np, boot_labels, cfg, k_list, log,
            regime="dense" if dense else "blockwise",
        )

    if cfg.nboots <= 1:
        # no-bootstrap path (reference :498-511); min_size=0 as in the boot
        # path — the reference's :500 call leaves minSize at its 0 default
        with maybe_span(log, "consensus_grid") as sp:
            grid = cluster_grid(
                key, pca, res_list, k_list, jnp.float32(0.0),
                max_clusters=cfg.max_clusters, cluster_fun=cfg.cluster_fun,
                compute_dtype=cfg.compute_dtype,
            )
            sp.value = grid.labels
        best = int(_ties_last_argmax(grid.scores))
        labels = np.asarray(grid.labels[best])
        # Euclidean small-cluster merge (:504-510): dense matrix below the
        # scale threshold, streamed cluster-pair sums above it. There is no
        # co-clustering here, so sparse_knn/blockwise both mean "streamed";
        # the resolver also supplies the explicit-dense footgun guard (the
        # [n, n] Euclidean matrix is the same OOM).
        dense = resolve_consensus_regime(cfg, n) in ("dense", "pallas")
        if dense:
            d2 = np.asarray(
                jnp.sqrt(jnp.maximum(
                    jnp.sum(pca**2, 1)[:, None] - 2 * pca @ pca.T + jnp.sum(pca**2, 1)[None, :],
                    0.0,
                ))
            )
            labels = merge_small_clusters(d2, labels, max(k_list[0], 30), cfg.max_clusters)
        else:
            from consensusclustr_tpu.consensus.blockwise import (
                euclidean_pair_sums,
                merge_small_clusters_from_sums,
            )

            esums, ecounts = euclidean_pair_sums(
                pca, jnp.asarray(labels, jnp.int32), cfg.max_clusters
            )
            labels = merge_small_clusters_from_sums(
                np.asarray(esums), np.asarray(ecounts), labels,
                max(k_list[0], 30),
            )
        numeric_checkpoint(
            log, LABELS_CKPT, lambda: np.asarray(labels, np.int32)
        )
        sil = float(mean_silhouette_score(pca, jnp.asarray(labels), cfg.max_clusters))
        if log:
            log.event("no_boot_result", n_clusters=len(np.unique(labels)), silhouette=sil)
        return ConsensusResult(
            labels=labels, silhouette=sil, jaccard_dist=None, boot_labels=None,
            n_clusters=len(np.unique(labels)),
            regime="dense" if dense else "blockwise",
        )

    regime = resolve_consensus_regime(cfg, n)
    dense = regime in ("dense", "pallas")
    # Explicit regime names fold the kernel choice in: "pallas" forces the
    # tile kernel, "dense" names the einsum oracle. Auto / legacy
    # dense_consensus keep cfg.use_pallas's dispatch — the pre-ISSUE-9
    # behavior, bit-identical below the threshold.
    if regime == "pallas":
        use_pallas = True
    elif cfg.consensus_regime == "dense":
        use_pallas = False
    else:
        use_pallas = cfg.use_pallas
    # Dense einsum regime: stream the co-clustering counts into a donated
    # accumulator DURING the boot fan-out (each chunk's device labels feed an
    # in-place [n, n] count update on the async stream) instead of one
    # fused pass over all rows afterwards — bit-identical (integer counts),
    # but the consensus matrix is ready the moment the boots drain and the
    # accumulator never double-buffers. The Pallas regime keeps the one-shot
    # tiled kernel (it wants the full int8 label matrix at once). The
    # sparse_knn regime (ISSUE 9) restricts the pair universe to each cell's
    # top-m PC-space neighbours and streams [n, m] donated carries the same
    # way — O(n·m) end to end; its consensus distance is born in kNN-graph
    # form, so the grid below consumes it directly.
    snn_impl = resolve_snn_impl()
    leiden_impl = resolve_leiden_impl()
    accum = None
    cand_idx = None
    if dense and cfg.nboots > 1 and not _pallas_wanted(use_pallas, cfg.max_clusters):
        accum = CoclusterAccumulator(n, cfg.max_clusters)
    elif regime == "sparse_knn":
        m_cand = resolve_candidate_m(cfg, n, k_list)
        with maybe_span(
            log, "candidates", **{CANDIDATE_M_ATTR: m_cand}
        ) as sp:
            cand_idx = knn_candidates(
                pca, m_cand, compute_dtype=cfg.compute_dtype
            )
            sp.value = cand_idx
        accum = SparseCoclusterAccumulator(cand_idx)
    # Resource bracket (obs/resource.py): the boots + cocluster phases are
    # where the O(n²) consensus memory materializes (ROADMAP O1), so sampling
    # covers at least this region even for direct consensus_cluster callers
    # (bench's granular rung, tests). An api-level sampler already attached
    # to the tracer is reused and NOT stopped here — the bracket only stops
    # what it itself started.
    with resource_sampling(tracer_of(log), cfg.resource_sample_ms):
        boot_labels, boot_scores = run_bootstraps(
            key, pca, cfg, log, accumulator=accum
        )
        sparse_state = None
        if dense:
            with maybe_span(
                log, "cocluster", dense=True, streamed=accum is not None,
                **{REGIME_ATTR: regime},
            ) as sp:
                if accum is not None:
                    # the streamed count carries, fingerprinted before
                    # finalize — chunk-order invariant (integer counts)
                    numeric_checkpoint(
                        log, COCLUSTER_CKPT, lambda: accum.carries()
                    )
                    dist = accum.distance()
                else:
                    dist = coclustering_distance(
                        jnp.asarray(boot_labels, jnp.int32), cfg.max_clusters,
                        use_pallas=use_pallas,
                    )
                numeric_checkpoint(log, CONSENSUS_DIST_CKPT, dist)
                sp.value = dist
            with maybe_span(
                log, "consensus_grid",
                **{SNN_IMPL_ATTR: snn_impl, LEIDEN_IMPL_ATTR: leiden_impl},
            ) as sp:
                cons_labels, cons_scores, rev_dropped = _consensus_grid(
                    key, dist, pca, res_list, k_list, cfg.max_clusters,
                    cluster_fun=cfg.cluster_fun, snn_impl=snn_impl,
                    leiden_impl=leiden_impl,
                )
                sp.value = (cons_labels, cons_scores)
                sp.set(**{SNN_REV_DROPPED_ATTR: int(rev_dropped)})
                metrics_of(log).counter("snn_rev_edges_dropped").inc(int(rev_dropped))
            dist_np = np.asarray(dist)
        elif regime == "sparse_knn":
            with maybe_span(
                log, "cocluster", dense=False,
                **{
                    REGIME_ATTR: regime,
                    CANDIDATE_M_ATTR: accum.m,
                    PAIRS_ATTR: accum.accumulated_pairs,
                    PAIRS_RATIO_ATTR: round(
                        accum.accumulated_pairs / float(n * n), 6
                    ),
                },
            ) as sp:
                # the restricted count carries, fingerprinted before
                # finalize — chunk-order invariant (integer counts), and on
                # candidate pairs integer-exactly equal to the dense counts
                # (tools/parity_audit.py --pair dense:sparse_knn)
                numeric_checkpoint(log, COCLUSTER_CKPT, lambda: accum.carries())
                # the consensus distance is born in kNN-graph form: no dense
                # matrix, no dense-distance -> kNN re-extraction downstream
                knn_idx, _ = accum.consensus_knn(max(k_list))
                numeric_checkpoint(log, CONSENSUS_DIST_CKPT, knn_idx)
                sp.value = knn_idx
            with maybe_span(
                log, "consensus_grid",
                **{SNN_IMPL_ATTR: snn_impl, LEIDEN_IMPL_ATTR: leiden_impl},
            ) as sp:
                cons_labels, cons_scores, rev_dropped = _consensus_grid_from_knn(
                    key, knn_idx, pca, res_list, k_list, cfg.max_clusters,
                    cluster_fun=cfg.cluster_fun, snn_impl=snn_impl,
                    leiden_impl=leiden_impl,
                )
                sp.value = (cons_labels, cons_scores)
                sp.set(**{SNN_REV_DROPPED_ATTR: int(rev_dropped)})
                metrics_of(log).counter("snn_rev_edges_dropped").inc(int(rev_dropped))
            agree, union = accum.carries()
            sparse_state = SparseConsensus(
                cand_idx=np.asarray(accum.candidate_idx),
                agree=np.asarray(agree),
                union=np.asarray(union),
                m=accum.m,
            )
            dist_np = None
        else:
            from consensusclustr_tpu.consensus.blockwise import (
                blockwise_consensus_knn,
            )

            with maybe_span(
                log, "cocluster", dense=False, **{REGIME_ATTR: regime}
            ) as sp:
                knn_idx, _ = blockwise_consensus_knn(
                    jnp.asarray(boot_labels, jnp.int32), max(k_list),
                    cfg.max_clusters, use_pallas=use_pallas,
                )
                # blockwise regime: the [n, n] matrix never exists — the
                # consensus kNN graph is the comparable downstream artifact
                numeric_checkpoint(log, CONSENSUS_DIST_CKPT, knn_idx)
                sp.value = knn_idx
            with maybe_span(
                log, "consensus_grid",
                **{SNN_IMPL_ATTR: snn_impl, LEIDEN_IMPL_ATTR: leiden_impl},
            ) as sp:
                cons_labels, cons_scores, rev_dropped = _consensus_grid_from_knn(
                    key, knn_idx, pca, res_list, k_list, cfg.max_clusters,
                    cluster_fun=cfg.cluster_fun, snn_impl=snn_impl,
                    leiden_impl=leiden_impl,
                )
                sp.value = (cons_labels, cons_scores)
                sp.set(**{SNN_REV_DROPPED_ATTR: int(rev_dropped)})
                metrics_of(log).counter("snn_rev_edges_dropped").inc(int(rev_dropped))
            dist_np = None
    labels = np.asarray(cons_labels)
    if log:
        log.event(
            "consensus", n_clusters=len(np.unique(labels)),
            best_score=float(np.max(np.asarray(cons_scores))),
            dense=bool(dense), regime=regime,
        )
    return _finish_consensus(
        pca, labels, dist_np, boot_labels, cfg, k_list, log,
        regime=regime, sparse=sparse_state,
    )
