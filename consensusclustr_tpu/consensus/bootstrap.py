"""Bootstrap resample generation.

Equivalent of the reference's per-boot `sample(rownames, bootSize*n,
replace=TRUE)` (reference R/consensusClust.R:394). The R mechanism — indexing
by duplicated rownames with first-match lookup — becomes an explicit
`int32 idx[boot, m]` gather plus masks (SURVEY §7.1; quirk 14): duplicates of
a cell all map to the same PCA row by construction, and alignment back to
cells takes each cell's first sampled copy (cluster.engine.align_to_cells).

Keys fold in the boot id, so resamples are identical regardless of device
count or batch order (SURVEY §2.4 RNG row).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from consensusclustr_tpu.utils.rng import boot_key


@functools.partial(jax.jit, static_argnames=("n", "nboots", "m"))  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
def bootstrap_indices(key: jax.Array, n: int, nboots: int, m: int) -> jax.Array:
    """[nboots, m] int32 cell indices, sampled uniformly with replacement."""

    def one(b):
        return jax.random.randint(boot_key(key, b), (m,), 0, n, dtype=jnp.int32)

    return jax.vmap(one)(jnp.arange(nboots, dtype=jnp.int32))


@functools.partial(jax.jit, static_argnames=("n",))  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
def sampled_mask(idx: jax.Array, n: int) -> jax.Array:
    """[.., n] bool: cell appears at least once in the resample."""
    shape = idx.shape[:-1] + (n,)
    flat = idx.reshape(-1, idx.shape[-1])
    out = jnp.zeros((flat.shape[0], n), bool)
    rows = jnp.broadcast_to(jnp.arange(flat.shape[0], dtype=jnp.int32)[:, None], flat.shape)
    out = out.at[rows, flat].set(True)
    return out.reshape(shape)
