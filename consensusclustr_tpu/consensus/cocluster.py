"""Co-clustering (consensus Jaccard) distance.

Equivalent of the reference's only first-party native code — the inline
RcppArmadillo kernel applied over all O(n^2) pairs by parallelDist/OpenMP
(reference R/consensusClust.R:411-421):

    jaccard(i, j) = #(L_i == L_j  and both sampled) / #(both sampled)
    dist = 1 - jaccard

TPU recasting (SURVEY §2.2 row 1): labels are one-hot encoded per assignment
column, so the agreement count is a batched matmul —
agree = sum_b onehot_b @ onehot_b^T — which rides the MXU; the union count is
the same matmul on the validity masks. Accumulation is chunked over the boot
axis with lax.scan so the [B, n, C] one-hots never materialise at once.

The Pallas int8 tile kernel (ops/pallas_cocluster.py) is the bandwidth-lean
variant; this einsum path is the portable default and the correctness oracle.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp


def coclustering_distance(
    labels: jax.Array,
    max_clusters: int = 64,
    chunk: int = 32,
) -> jax.Array:
    """labels: [B, n] int32, -1 == not sampled in that column.

    Returns [n, n] float32 distance, diagonal forced to 0. Pairs never
    co-sampled (union 0) get distance 1 (the R kernel's 0/0 NaN would poison
    downstream kNN; the reference effectively never hits it at its default
    nboots — documented deviation).

    Dispatch: on TPU with compact labels the tiled Pallas kernel
    (ops/pallas_cocluster.py) streams raw int8 labels; elsewhere (or with
    CCTPU_NO_PALLAS=1) the einsum path below is the oracle.
    """
    if (
        jax.default_backend() == "tpu"
        and max_clusters <= 127
        and not os.environ.get("CCTPU_NO_PALLAS")
    ):
        from consensusclustr_tpu.ops.pallas_cocluster import (
            pallas_coclustering_distance,
        )

        return pallas_coclustering_distance(labels)
    return _einsum_coclustering_distance(labels, max_clusters, chunk)


@functools.partial(jax.jit, static_argnames=("max_clusters", "chunk"))
def _einsum_coclustering_distance(
    labels: jax.Array,
    max_clusters: int = 64,
    chunk: int = 32,
) -> jax.Array:
    labels = jnp.asarray(labels, jnp.int32)
    b, n = labels.shape
    pad = (-b) % chunk
    if pad:
        labels = jnp.concatenate([labels, jnp.full((pad, n), -1, jnp.int32)], axis=0)
    labels = labels.reshape(-1, chunk, n)

    cvals = jnp.arange(max_clusters, dtype=jnp.int32)

    def body(carry, chunk_labels):
        agree, union = carry
        valid = (chunk_labels >= 0).astype(jnp.bfloat16)              # [c, n]
        onehot = (chunk_labels[:, :, None] == cvals[None, None, :]).astype(jnp.bfloat16)
        onehot = onehot * valid[:, :, None]                            # [c, n, C]
        agree = agree + jnp.einsum(
            "cik,cjk->ij", onehot, onehot, preferred_element_type=jnp.float32
        )
        union = union + jnp.einsum(
            "ci,cj->ij", valid, valid, preferred_element_type=jnp.float32
        )
        return (agree, union), None

    zero = jnp.zeros((n, n), jnp.float32)
    (agree, union), _ = jax.lax.scan(body, (zero, zero), labels)
    jac = jnp.where(union > 0, agree / jnp.maximum(union, 1.0), 0.0)
    dist = 1.0 - jac
    return dist.at[jnp.arange(n), jnp.arange(n)].set(0.0)
