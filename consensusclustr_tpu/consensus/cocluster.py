"""Co-clustering (consensus Jaccard) distance.

Equivalent of the reference's only first-party native code — the inline
RcppArmadillo kernel applied over all O(n^2) pairs by parallelDist/OpenMP
(reference R/consensusClust.R:411-421):

    jaccard(i, j) = #(L_i == L_j  and both sampled) / #(both sampled)
    dist = 1 - jaccard

TPU recasting (SURVEY §2.2 row 1): labels are one-hot encoded per assignment
column, so the agreement count is a batched matmul —
agree = sum_b onehot_b @ onehot_b^T — which rides the MXU; the union count is
the same matmul on the validity masks. Accumulation is chunked over the boot
axis with lax.scan so the [B, n, C] one-hots never materialise at once.

The Pallas int8 tile kernel (ops/pallas_cocluster.py) is the bandwidth-lean
variant; this einsum path is the portable default and the correctness oracle.
"""

from __future__ import annotations

import functools
import os
import warnings
from typing import Optional

import jax
import jax.numpy as jnp

from consensusclustr_tpu.utils.backend import default_backend as _default_backend

# Which implementation the last coclustering_distance call used:
# "pallas" | "einsum". Read by bench.py to report the measured path.
LAST_PATH: str = "einsum"


def _pallas_wanted(use_pallas: Optional[bool], max_clusters: int) -> bool:
    """Resolve the dispatch: the CCTPU_NO_PALLAS env kill-switch beats the
    config flag beats the backend default — the env var must win even over an
    explicit use_pallas=True so a broken kernel can be disabled fleet-wide
    without touching configs. The kernel needs int8-compact labels."""
    if max_clusters > 127 or _default_backend() != "tpu":
        return False
    if os.environ.get("CCTPU_NO_PALLAS"):
        return False
    return True if use_pallas is None else bool(use_pallas)


def coclustering_distance(
    labels: jax.Array,
    max_clusters: int = 64,
    chunk: int = 32,
    use_pallas: Optional[bool] = None,
) -> jax.Array:
    """labels: [B, n] int32, -1 == not sampled in that column.

    Returns [n, n] float32 distance, diagonal forced to 0. Pairs never
    co-sampled (union 0) get distance 1 (the R kernel's 0/0 NaN would poison
    downstream kNN; the reference effectively never hits it at its default
    nboots — documented deviation).

    Dispatch: on TPU with compact labels the tiled Pallas kernel
    (ops/pallas_cocluster.py) streams raw int8 labels; elsewhere the einsum
    path below is the oracle. ``use_pallas`` (ClusterConfig.use_pallas) forces
    the choice; None = auto; CCTPU_NO_PALLAS=1 disables globally. A Pallas
    compile/runtime failure falls back to the einsum path with a warning —
    the pipeline never dies on a kernel regression.
    """
    global LAST_PATH
    if _pallas_wanted(use_pallas, max_clusters):
        from consensusclustr_tpu.ops.pallas_cocluster import (
            pallas_coclustering_distance,
        )

        try:
            out = pallas_coclustering_distance(labels, n_classes=max_clusters)
            # block inside the try so async runtime failures (HBM OOM at
            # execute time) also degrade instead of escaping at the caller's
            # fetch — same fix as blockwise._run_with_tile_fallback
            jax.block_until_ready(out)
            LAST_PATH = "pallas"
            return out
        except Exception as e:  # Mosaic compile or runtime OOM: degrade, don't die
            warnings.warn(
                f"Pallas co-clustering kernel failed ({type(e).__name__}: {e}); "
                "falling back to the einsum path",
                RuntimeWarning,
                stacklevel=2,
            )
    LAST_PATH = "einsum"
    return _einsum_coclustering_distance(labels, max_clusters, chunk)


@functools.partial(jax.jit, static_argnames=("max_clusters", "chunk"))  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
def _einsum_coclustering_distance(
    labels: jax.Array,
    max_clusters: int = 64,
    chunk: int = 32,
) -> jax.Array:
    labels = jnp.asarray(labels, jnp.int32)
    b, n = labels.shape
    pad = (-b) % chunk
    if pad:
        labels = jnp.concatenate([labels, jnp.full((pad, n), -1, jnp.int32)], axis=0)
    labels = labels.reshape(-1, chunk, n)

    zero = jnp.zeros((n, n), jnp.float32)
    (agree, union), _ = jax.lax.scan(
        functools.partial(_count_step, max_clusters=max_clusters),
        (zero, zero), labels,
    )
    return _finalize_cocluster_distance(agree, union)


def _count_step(carry, chunk_labels, max_clusters: int):
    """One boot-chunk of agreement/union count accumulation (the MXU matmul
    body shared by the one-shot scan above and the donated streaming
    accumulator below — counts are integers, so any chunking of the boot
    axis yields bit-identical totals). Carry-dtype-agnostic: the one-shot
    oracle scans f32 carries, the streaming accumulator uint16 (ISSUE 20
    byte diet) — the per-chunk delta is an integer <= chunk rows, so the
    cast into the carry dtype is exact either way."""
    agree, union = carry
    cvals = jnp.arange(max_clusters, dtype=jnp.int32)
    valid = (chunk_labels >= 0).astype(jnp.bfloat16)              # [c, n]
    onehot = (chunk_labels[:, :, None] == cvals[None, None, :]).astype(jnp.bfloat16)  # graftlint: noqa[GL008] [c, n, C] one-hot IS the MXU matmul operand here (agree = onehot @ onehot^T rides the MXU); the transient is the price of the einsum recasting, bounded by chunk=32 rows
    onehot = onehot * valid[:, :, None]                            # [c, n, C]
    agree = agree + jnp.einsum(
        "cik,cjk->ij", onehot, onehot, preferred_element_type=jnp.float32
    ).astype(agree.dtype)
    union = union + jnp.einsum(
        "ci,cj->ij", valid, valid, preferred_element_type=jnp.float32
    ).astype(union.dtype)
    return (agree, union), None


@jax.jit  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
def _finalize_cocluster_distance(agree: jax.Array, union: jax.Array) -> jax.Array:
    # widen once: integer counts < 2^24 are exact in f32, so finalize output
    # is bit-identical whether the carries arrived f32 or uint16
    agree = jnp.asarray(agree, jnp.float32)
    union = jnp.asarray(union, jnp.float32)
    n = agree.shape[0]
    jac = jnp.where(union > 0, agree / jnp.maximum(union, 1.0), 0.0)
    dist = 1.0 - jac
    return dist.at[jnp.arange(n, dtype=jnp.int32), jnp.arange(n, dtype=jnp.int32)].set(0.0)


@functools.lru_cache(maxsize=None)
def _make_accum_update(chunk: int):
    """The donated accumulator step, wrapped lazily so importing this module
    never touches utils/compile_cache (which imports obs) at import time.
    Memoized per chunk width so every accumulator instance shares one jit
    cache (one compile per label-batch shape bucket, not per instance)."""
    from consensusclustr_tpu.utils.compile_cache import counting_jit

    @counting_jit(donate_argnums=(0, 1), static_argnames=("max_clusters",))
    def _accum_cocluster_counts(agree, union, labels, max_clusters):
        b, n = labels.shape
        pad = (-b) % chunk
        if pad:
            labels = jnp.concatenate(
                [labels, jnp.full((pad, n), -1, jnp.int32)], axis=0
            )
        labels = labels.reshape(-1, chunk, n)
        (agree, union), _ = jax.lax.scan(
            functools.partial(_count_step, max_clusters=max_clusters),
            (agree, union), labels,
        )
        return agree, union

    return _accum_cocluster_counts


class CoclusterAccumulator:
    """Streaming co-clustering counts with donated carries (ISSUE 5).

    The serial dense path materialised every boot label row, then ran one
    [B, n] -> [n, n] pass at the end; each round of a chunked variant without
    donation would round-trip two fresh [n, n] buffers per chunk (old + new
    alive at once — the doubling called out in ISSUE 5). Here ``update`` is a
    ``counting_jit`` program with ``donate_argnums=(0, 1)``: the agree/union
    count matrices are donated back to the executable every chunk and updated
    in place, and the update dispatch rides the async stream (the chunk
    pipeline feeds device label batches straight in — no host round trip).

    Carries are **uint16** (ISSUE 20 byte diet): each count is at most the
    number of label rows folded in, so with ``rows <= 65535`` the narrow
    lane is exact — ``update`` enforces the headroom, and ``carries()``
    widens back to the historical f32 integer counts once at read time, so
    the ``cocluster`` numeric-checkpoint fingerprint and every downstream
    consumer see bit-identical values while the live footprint halves
    (2 x [n, n] at 2 bytes/cell instead of 4).

    ``distance()`` renders exactly ``coclustering_distance``'s einsum result:
    the counts are integers, so accumulation order cannot change them,
    and the finalize formula is shared — bit-identical by construction,
    pinned in tests/test_consensus.py.
    """

    # uint16 carry ceiling: counts <= rows folded in, so rows above this
    # would saturate. nboots (x grid candidates in granular mode) at any
    # sane setting sits orders of magnitude below it.
    CARRY_MAX_ROWS = 65535

    def __init__(self, n: int, max_clusters: int = 64, chunk: int = 32):
        self.n = int(n)
        self.max_clusters = int(max_clusters)
        self._update = _make_accum_update(int(chunk))
        self._agree = jnp.zeros((n, n), jnp.uint16)
        self._union = jnp.zeros((n, n), jnp.uint16)
        self.chunks = 0
        self.rows = 0

    def update(self, labels) -> None:
        """Fold a [rows, n] int32 label batch (device or host; -1 = unsampled)
        into the counts. Dispatches asynchronously; the previous agree/union
        buffers are donated to the update program."""
        labels = jnp.asarray(labels, jnp.int32)
        if labels.ndim != 2 or labels.shape[1] != self.n:
            raise ValueError(
                f"label batch shape {labels.shape} incompatible with n={self.n}"
            )
        if self.rows + int(labels.shape[0]) > self.CARRY_MAX_ROWS:
            raise ValueError(
                f"uint16 co-cluster carries saturate above "
                f"{self.CARRY_MAX_ROWS} accumulated label rows; got "
                f"{self.rows} + {int(labels.shape[0])}"
            )
        self._agree, self._union = self._update(
            self._agree, self._union, labels, max_clusters=self.max_clusters
        )
        self.chunks += 1
        self.rows += int(labels.shape[0])

    def carries(self) -> tuple:
        """The (agree, union) count carries, widened once to the historical
        f32 integer counts — the arrays the numerics layer fingerprints at
        the ``cocluster`` checkpoint (integer counts, so the fingerprint is
        chunk-order invariant by construction and unchanged by the uint16
        internal lane)."""
        return (
            self._agree.astype(jnp.float32),
            self._union.astype(jnp.float32),
        )

    def distance(self) -> jax.Array:
        """[n, n] co-clustering distance of everything folded in so far."""
        global LAST_PATH
        LAST_PATH = "einsum"
        return _finalize_cocluster_distance(self._agree, self._union)


# -- kNN-restricted sparse accumulator (ISSUE 9) ------------------------------


@functools.lru_cache(maxsize=None)
def _make_sparse_accum_update(chunk: int):
    """Donated sparse-count step, lazily wrapped like _make_accum_update (no
    compile_cache/obs import at module import time; one jit cache per chunk
    width shared across accumulator instances)."""
    from consensusclustr_tpu.utils.compile_cache import counting_jit

    @counting_jit(donate_argnums=(0, 1))
    def _accum_sparse_cocluster_counts(agree, union, labels, cand_idx):
        b, n = labels.shape
        pad = (-b) % chunk
        if pad:  # bucket the boot axis so ragged tails reuse the executable
            labels = jnp.concatenate(
                [labels, jnp.full((pad, n), -1, jnp.int32)], axis=0
            )

        def step(carry, row):
            # One boot row: gather each cell's candidate-neighbour labels and
            # count agree/union ONLY on those pairs — the [n, m] transient is
            # the whole working set (no [n, n], no one-hot). Padded all--1
            # rows contribute nothing (vv is false everywhere). The 0/1
            # increments land in the carry dtype (uint16 narrow lane,
            # ISSUE 20) — integer-exact by construction.
            agree, union = carry
            valid = row >= 0                                     # [n]
            nbr = row[cand_idx]                                  # [n, m]
            vv = valid[:, None] & (nbr >= 0)
            agree = agree + jnp.where(
                vv & (row[:, None] == nbr), 1, 0
            ).astype(agree.dtype)
            union = union + jnp.where(vv, 1, 0).astype(union.dtype)
            return (agree, union), None

        (agree, union), _ = jax.lax.scan(step, (agree, union), labels)
        return agree, union

    return _accum_sparse_cocluster_counts


@jax.jit  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
def _finalize_sparse_distance(agree: jax.Array, union: jax.Array) -> jax.Array:
    """[n, m] restricted co-clustering distance — the same finalize formula
    as the dense path (union 0 -> distance 1); the diagonal repair is moot
    because candidate sets exclude self. Widens the uint16 carries once
    (integer counts < 2^24 are exact in f32)."""
    agree = jnp.asarray(agree, jnp.float32)
    union = jnp.asarray(union, jnp.float32)
    jac = jnp.where(union > 0, agree / jnp.maximum(union, 1.0), 0.0)
    return 1.0 - jac


@functools.partial(jax.jit, static_argnames=("k",))  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
def _sparse_knn_extract(cand_idx: jax.Array, dist: jax.Array, k: int):
    """Top-k of the restricted distances per row -> (idx [n, k] int32 into
    cells, dist [n, k] f32), increasing distance. Ties break by candidate
    slot (= PC-distance rank), where the dense knn_from_distance breaks by
    cell index — a documented, deliberate difference (docs/perf.md)."""
    m = dist.shape[1]
    k_eff = min(k, m)
    neg, sel = jax.lax.top_k(-dist, k_eff)
    idx = jnp.take_along_axis(cand_idx, sel, axis=1)
    if k_eff < k:  # degenerate m < k: pad with the last neighbour
        pad = k - k_eff
        idx = jnp.concatenate([idx, jnp.repeat(idx[:, -1:], pad, axis=1)], axis=1)
        neg = jnp.concatenate([neg, jnp.repeat(neg[:, -1:], pad, axis=1)], axis=1)
    return idx.astype(jnp.int32), -neg


class SparseCoclusterAccumulator:
    """kNN-restricted streaming co-clustering counts (ISSUE 9 tentpole).

    The dense accumulator above carries two [n, n] count matrices — the
    O(n²) wall that caps every regime (6.9 GB RSS at the 50k north star,
    ~2.7 TB extrapolated to 1M cells). This accumulator restricts the pair
    universe to each cell's ``cand_idx`` [n, m] candidate-neighbour set
    (cluster/knn.py::knn_candidates, top-m in PC space) and carries [n, m]
    agree/union counts instead: O(n·m) memory and FLOPs end to end, donated
    in place per chunk exactly like the dense carries, fed from the same
    ChunkPipeline ``on_enqueue`` hook. Like the dense accumulator the
    carries are uint16 (ISSUE 20 byte diet) with the same
    ``CARRY_MAX_ROWS`` headroom guard, and ``carries()`` widens back to the
    historical f32 integer counts once at read time.

    Restriction contract (pinned by ``tools/parity_audit.py --pair
    dense:sparse_knn`` and tests/test_sparse_consensus.py): for every
    candidate pair ``(i, cand_idx[i, s])`` the agree/union counts equal the
    dense accumulator's ``[i, cand_idx[i, s]]`` entries *integer-exactly* —
    the restriction changes WHICH pairs are counted, never a single count.
    ``consensus_knn`` then yields the consensus graph directly in kNN form,
    so the downstream grid skips the dense-distance -> kNN re-extraction.
    """

    CARRY_MAX_ROWS = CoclusterAccumulator.CARRY_MAX_ROWS

    def __init__(self, cand_idx, chunk: int = 32):
        cand_idx = jnp.asarray(cand_idx, jnp.int32)
        if cand_idx.ndim != 2:
            raise ValueError(
                f"cand_idx must be [n, m]; got shape {cand_idx.shape}"
            )
        self.n, self.m = (int(s) for s in cand_idx.shape)
        self._cand = jax.device_put(cand_idx)
        self._update = _make_sparse_accum_update(int(chunk))
        self._agree = jnp.zeros((self.n, self.m), jnp.uint16)
        self._union = jnp.zeros((self.n, self.m), jnp.uint16)
        self.chunks = 0
        self.rows = 0

    @property
    def candidate_idx(self) -> jax.Array:
        """[n, m] int32 candidate sets (read-only view)."""
        return self._cand

    @property
    def accumulated_pairs(self) -> int:
        """Directed pairs the accumulator tracks (n * m) — vs the dense
        regime's n²; the ratio is the ``pairs_ratio`` span attr."""
        return self.n * self.m

    def update(self, labels) -> None:
        """Fold a [rows, n] int32 label batch (-1 = unsampled) into the
        restricted counts; donates the previous carries, dispatches async —
        the same contract as CoclusterAccumulator.update."""
        labels = jnp.asarray(labels, jnp.int32)
        if labels.ndim != 2 or labels.shape[1] != self.n:
            raise ValueError(
                f"label batch shape {labels.shape} incompatible with n={self.n}"
            )
        if self.rows + int(labels.shape[0]) > self.CARRY_MAX_ROWS:
            raise ValueError(
                f"uint16 co-cluster carries saturate above "
                f"{self.CARRY_MAX_ROWS} accumulated label rows; got "
                f"{self.rows} + {int(labels.shape[0])}"
            )
        self._agree, self._union = self._update(
            self._agree, self._union, labels, self._cand
        )
        self.chunks += 1
        self.rows += int(labels.shape[0])

    def carries(self) -> tuple:
        """The (agree, union) [n, m] carries, widened once to the historical
        f32 integer counts — fingerprinted at the ``cocluster`` checkpoint;
        chunk-order invariant exactly like the dense carries, and unchanged
        by the uint16 internal lane."""
        return (
            self._agree.astype(jnp.float32),
            self._union.astype(jnp.float32),
        )

    def distances(self) -> jax.Array:
        """[n, m] restricted co-clustering distance of everything so far."""
        return _finalize_sparse_distance(self._agree, self._union)

    def consensus_knn(self, k: int):
        """(idx [n, k], dist [n, k]) consensus kNN graph straight from the
        restricted counts — the sparse regime's ``consensus_dist`` artifact
        (already graph-form; no dense matrix ever exists)."""
        return _sparse_knn_extract(self._cand, self.distances(), k)
