"""Blockwise consensus graph: co-clustering kNN + merges without the [n, n].

The consensus distance matrix is the framework's seq^2 analog (SURVEY §5
long-context row): dense assembly is 10 GB at 50k cells and 160 GB at 200k —
the reference sidesteps nothing here (parDist materialises the full matrix,
R/consensusClust.R:421), so this module is where the TPU design goes beyond
it. Strategy is the same family as blockwise attention: stream row tiles of
the implicit distance matrix, reduce each tile immediately (running top-k for
the consensus kNN graph; segment-sums for the cluster-pair merge statistics),
never materialising more than one [block, n] tile.

Downstream consumers and their replacements:
  * consensus kNN -> SNN -> Leiden (reference :423-441): `blockwise_consensus_knn`
  * small-cluster merge mean distances (:461-467): `cocluster_pair_sums` +
    `merge_small_clusters_from_sums` (exact incremental updates — the mean
    distance between merged clusters is a ratio of summed pair distances, so
    the host loop updates sums/counts instead of recomputing tiles)
  * dendrogram over final labels (:580-588): `cocluster_cluster_distance`
"""

from __future__ import annotations

import functools
import os
import warnings
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Row-tile edge. [block, n] f32 at n=200k is 800 MB — the peak transient.
# Multiple of the Pallas kernel's TILE (ops/pallas_cocluster.py).
BW_BLOCK = 1024


def _pallas_tile_opts(use_pallas: Optional[bool], max_clusters: int):
    """Resolve the per-tile kernel choice for the streaming loops.

    Returns (use_pallas, variant, interpret). On TPU the Pallas rows kernel
    replaces the [chunk, n, C] HBM one-hot of the einsum tile — at north-star
    scale (50k cells x 1000 boots) that one-hot alone is ~300 GB of HBM
    traffic re-materialised per row block. CCTPU_PALLAS_INTERPRET=1 runs the
    same composition in interpret mode (CPU parity tests); it bypasses ONLY
    the backend gate — the CCTPU_NO_PALLAS kill-switch and the int8
    compactness bound (max_clusters <= 127) always win, same contract as
    cocluster._pallas_wanted.
    """
    from consensusclustr_tpu.consensus.cocluster import _pallas_wanted

    variant = os.environ.get("CCTPU_PALLAS_VARIANT", "mxu")
    if variant not in ("mxu", "vpu"):  # same loud contract as the square path
        raise ValueError(f"unknown pallas variant {variant!r}")
    interpret = bool(os.environ.get("CCTPU_PALLAS_INTERPRET"))
    if max_clusters > 127 or os.environ.get("CCTPU_NO_PALLAS"):
        wanted = False
    elif interpret:
        wanted = bool(use_pallas)  # explicit opt-in only, any backend
    else:
        wanted = _pallas_wanted(use_pallas, max_clusters)
    return bool(wanted), variant, interpret


def _run_with_tile_fallback(jit_fn, arrays, static_tail, use_pallas, max_clusters):
    """Shared dispatch: try the Pallas tile, degrade to einsum on failure —
    the same contract as coclustering_distance (never die on a kernel
    regression)."""
    pallas, variant, interpret = _pallas_tile_opts(use_pallas, max_clusters)
    if pallas:
        try:
            out = jit_fn(*arrays, *static_tail, "pallas", variant, interpret)
            # block inside the try: with async dispatch a runtime failure
            # (e.g. HBM OOM at execute time) only surfaces at the fetch —
            # outside this block it would escape the fallback (ADVICE r5 #2)
            jax.block_until_ready(out)
            from consensusclustr_tpu.ops import pallas_cocluster as _pc

            _pc.LAST_VARIANT = variant
            return out
        except Exception as e:  # Mosaic compile or runtime OOM: degrade, don't die
            warnings.warn(
                f"Pallas blockwise tile failed ({type(e).__name__}: {e}); "
                "falling back to the einsum tile",
                RuntimeWarning,
                stacklevel=3,
            )
    return jit_fn(*arrays, *static_tail, "einsum", "mxu", False)


def _make_tile(labels, n_pad, max_clusters, block, chunk, tile_impl, variant,
               interpret, vma=()):
    """tile(start) -> [block, n_pad] distance rows for the streaming loops.

    ``start`` is the ABSOLUTE first row (traced ok) — shared by the
    single-chip streamers (start = i * block) and the sharded kernel
    (start = device_row0 + i * block). ``vma`` is forwarded to the pallas
    rows kernel for shard_map callers that keep vma checking strict.
    """
    if tile_impl == "pallas":
        from consensusclustr_tpu.ops.pallas_cocluster import (
            pad_labels_int8, pallas_cocluster_rows,
        )

        lab8 = pad_labels_int8(labels, n_pad)
        return lambda start: pallas_cocluster_rows(
            lab8, start, block, max_clusters, variant, interpret, vma=vma
        )
    labels_s = _onehot_chunks(labels, chunk, max_clusters)
    return lambda start: _dist_tile(labels_s, start, block, max_clusters)


def _onehot_chunks(labels: jax.Array, chunk: int, max_clusters: int):
    """Pad the boot axis to `chunk` granularity and reshape to [S, chunk, n]."""
    b, n = labels.shape
    pad = (-b) % chunk
    if pad:
        labels = jnp.concatenate([labels, jnp.full((pad, n), -1, jnp.int32)], axis=0)
    return labels.reshape(-1, chunk, n)


def _dist_tile(
    labels_s: jax.Array,   # [S, chunk, n] int32
    start: jax.Array,      # scalar: first row of the tile
    block: int,
    max_clusters: int,
) -> jax.Array:
    """[block, n] co-clustering distance rows, accumulated over boot chunks."""
    n = labels_s.shape[2]
    cvals = jnp.arange(max_clusters, dtype=jnp.int32)

    def body(carry, chunk_labels):
        agree, union = carry
        valid = (chunk_labels >= 0).astype(jnp.bfloat16)                  # [c, n]
        onehot = (chunk_labels[:, :, None] == cvals[None, None, :]).astype(jnp.bfloat16)  # graftlint: noqa[GL008] the bf16 one-hot IS the MXU matmul operand (both einsums below contract it); bounded by chunk rows per step
        onehot = onehot * valid[:, :, None]                               # [c, n, C]
        rows = jax.lax.dynamic_slice_in_dim(onehot, start, block, axis=1)
        vrows = jax.lax.dynamic_slice_in_dim(valid, start, block, axis=1)
        agree = agree + jnp.einsum(
            "cik,cjk->ij", rows, onehot, preferred_element_type=jnp.float32
        )
        union = union + jnp.einsum(
            "ci,cj->ij", vrows, valid, preferred_element_type=jnp.float32
        )
        return (agree, union), None

    # `+ start * 0` inherits start's varying-manual-axes type, so the scan
    # carry typechecks when the tile start is a shard_map axis_index
    zero = jnp.zeros((block, n), jnp.float32) + (start * 0).astype(jnp.float32)
    (agree, union), _ = jax.lax.scan(body, (zero, zero), labels_s)
    jac = jnp.where(union > 0, agree / jnp.maximum(union, 1.0), 0.0)
    return 1.0 - jac


def blockwise_consensus_knn(
    labels: jax.Array,
    k: int,
    max_clusters: int = 64,
    block: int = BW_BLOCK,
    chunk: int = 8,
    use_pallas: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Exact co-clustering kNN graph without materialising the distance matrix.

    labels: [B, n] int32 (-1 = unsampled). Returns (idx [n, k] int32, dist
    [n, k] f32) sorted by increasing distance, self excluded. Matches
    knn_from_distance(coclustering_distance(labels), k) exactly (same top_k
    tie-breaking), so smaller-k graphs are prefixes of larger-k ones.

    On TPU the [block, n] tile comes from the Pallas rows kernel
    (ops/pallas_cocluster.py::pallas_cocluster_rows) instead of the einsum
    tile; a kernel failure degrades to the einsum path with a warning, same
    contract as coclustering_distance.
    """
    return _run_with_tile_fallback(
        _blockwise_knn_jit, (jnp.asarray(labels, jnp.int32),),
        (k, max_clusters, block, chunk), use_pallas, max_clusters,
    )


@functools.partial(
    jax.jit,  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
    static_argnames=("k", "max_clusters", "block", "chunk", "tile_impl",
                     "variant", "interpret"),
)
def _blockwise_knn_jit(
    labels: jax.Array,
    k: int,
    max_clusters: int,
    block: int,
    chunk: int,
    tile_impl: str,
    variant: str,
    interpret: bool,
) -> Tuple[jax.Array, jax.Array]:
    b, n = labels.shape
    k_eff = min(k, n - 1)
    n_blocks = -(-n // block)
    n_pad = n_blocks * block
    if n_pad != n:
        labels = jnp.concatenate(
            [labels, jnp.full((b, n_pad - n), -1, jnp.int32)], axis=1
        )
    tile = _make_tile(
        labels, n_pad, max_clusters, block, chunk, tile_impl, variant, interpret
    )
    rows_local = jnp.arange(block, dtype=jnp.int32)

    def one_block(i):
        d = tile(i * block)[:, :n]                                    # [block, n]
        r_global = i * block + rows_local
        self_col = jnp.clip(r_global, 0, n - 1)
        d = d.at[rows_local, self_col].set(jnp.inf)                   # exclude self
        # padding rows beyond n produce garbage; sliced off by the caller
        neg, idx = jax.lax.top_k(-d, k_eff)
        return neg, idx

    neg, idx = jax.lax.map(one_block, jnp.arange(n_blocks, dtype=jnp.int32))
    neg = neg.reshape(n_pad, k_eff)[:n]
    idx = idx.reshape(n_pad, k_eff)[:n]
    if k_eff < k:
        pad = k - k_eff
        idx = jnp.concatenate([idx, jnp.repeat(idx[:, -1:], pad, axis=1)], axis=1)
        neg = jnp.concatenate([neg, jnp.repeat(neg[:, -1:], pad, axis=1)], axis=1)
    return idx.astype(jnp.int32), -neg


def cocluster_pair_sums(
    labels: jax.Array,        # [B, n] int32 boot assignments
    codes: jax.Array,         # [n] int32 cluster ids in [0, n_clusters)
    n_clusters: int,
    max_clusters: int = 64,
    block: int = BW_BLOCK,
    chunk: int = 8,
    use_pallas: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """(sums [C, C], counts [C]): summed co-clustering distances between the
    members of each cluster pair, streamed in [block, n] tiles.

    sums / outer(counts) is cluster_mean_distance without the dense matrix
    (self-pairs contribute distance 0 on the diagonal, matching the dense
    path's zeroed diagonal). Tile dispatch as in blockwise_consensus_knn.
    """
    return _run_with_tile_fallback(
        _pair_sums_jit,
        (jnp.asarray(labels, jnp.int32), jnp.asarray(codes, jnp.int32)),
        (n_clusters, max_clusters, block, chunk), use_pallas, max_clusters,
    )


@functools.partial(
    jax.jit,  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
    static_argnames=("max_clusters", "n_clusters", "block", "chunk",
                     "tile_impl", "variant", "interpret"),
)
def _pair_sums_jit(
    labels: jax.Array,
    codes: jax.Array,
    n_clusters: int,
    max_clusters: int,
    block: int,
    chunk: int,
    tile_impl: str,
    variant: str,
    interpret: bool,
) -> Tuple[jax.Array, jax.Array]:
    b, n = labels.shape
    n_blocks = -(-n // block)
    n_pad = n_blocks * block
    if n_pad != n:
        labels = jnp.concatenate(
            [labels, jnp.full((b, n_pad - n), -1, jnp.int32)], axis=1
        )
    tile = _make_tile(
        labels, n_pad, max_clusters, block, chunk, tile_impl, variant, interpret
    )
    oh_all = (codes[:, None] == jnp.arange(n_clusters, dtype=jnp.int32)[None, :]).astype(jnp.float32)
    codes_pad = jnp.concatenate([codes, jnp.full((n_pad - n,), -1, jnp.int32)])
    oh_pad = (codes_pad[:, None] == jnp.arange(n_clusters, dtype=jnp.int32)[None, :]).astype(
        jnp.float32
    )
    rows_local = jnp.arange(block, dtype=jnp.int32)

    def one_block(acc, i):
        d = tile(i * block)[:, :n]                                   # [block, n]
        r_global = i * block + rows_local
        self_col = jnp.clip(r_global, 0, n - 1)
        d = d.at[rows_local, self_col].set(0.0)                      # diag 0
        ohr = jax.lax.dynamic_slice_in_dim(oh_pad, i * block, block, axis=0)
        acc = acc + ohr.T @ (d @ oh_all)                              # [C, C]
        return acc, None

    sums, _ = jax.lax.scan(
        one_block, jnp.zeros((n_clusters, n_clusters), jnp.float32),
        jnp.arange(n_blocks, dtype=jnp.int32),
    )
    counts = jnp.sum(oh_all, axis=0)
    return sums, counts


def merge_small_clusters_from_sums(
    sums: np.ndarray,
    counts: np.ndarray,
    labels: np.ndarray,
    min_size: int,
) -> np.ndarray:
    """Small-cluster merge (reference :462-467) from pair sums.

    Equivalent to merge_small_clusters up to f32 accumulation order at ties:
    the mean inter-member distance between merged clusters is additive in
    (sums, counts), so the host loop updates them in place (in float64)
    instead of re-streaming tiles, while the dense path recomputes cluster
    means in f32 on device each iteration — a near-tie argmin target can
    differ between the two (ADVICE r3; parity tests cover n <= 700).
    """
    labels = np.asarray(labels, np.int32).copy()
    sums = np.asarray(sums, np.float64).copy()
    counts = np.asarray(counts, np.float64).copy()
    while True:
        live = np.where(counts > 0)[0]
        if len(live) <= 1:
            return labels
        smallest = live[np.argmin(counts[live])]
        if counts[live].min() >= min_size:
            return labels
        with np.errstate(invalid="ignore", divide="ignore"):
            denom = counts[smallest] * counts
            row = np.where(denom > 0, sums[smallest] / np.maximum(denom, 1.0), np.inf)
        row[smallest] = np.inf
        row[counts <= 0] = np.inf
        target = int(np.argmin(row))
        labels[labels == smallest] = target
        # fold row then column: the diagonal picks up all four terms
        sums[target, :] += sums[smallest, :]
        sums[:, target] += sums[:, smallest]
        sums[smallest, :] = 0.0
        sums[:, smallest] = 0.0
        counts[target] += counts[smallest]
        counts[smallest] = 0.0


@functools.partial(jax.jit, static_argnames=("n_clusters", "block"))  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
def euclidean_pair_sums(
    x: jax.Array,          # [n, d] embedding
    codes: jax.Array,      # [n] int32 cluster ids in [0, n_clusters)
    n_clusters: int,
    block: int = BW_BLOCK,
) -> Tuple[jax.Array, jax.Array]:
    """(sums [C, C], counts [C]) of pairwise Euclidean distances between
    cluster members, streamed in [block, n] tiles — the significance gate's
    dendrogram input (reference :523 `dist(pca)`) without the [n, n]."""
    x = jnp.asarray(x, jnp.float32)
    codes = jnp.asarray(codes, jnp.int32)
    n, d = x.shape
    n_blocks = -(-n // block)
    n_pad = n_blocks * block
    x_pad = jnp.zeros((n_pad, d), jnp.float32).at[:n].set(x)
    sq = jnp.sum(x * x, axis=1)
    sq_pad = jnp.zeros((n_pad,), jnp.float32).at[:n].set(sq)
    oh = (codes[:, None] == jnp.arange(n_clusters, dtype=jnp.int32)[None, :]).astype(jnp.float32)
    codes_pad = jnp.concatenate([codes, jnp.full((n_pad - n,), -1, jnp.int32)])
    oh_pad = (codes_pad[:, None] == jnp.arange(n_clusters, dtype=jnp.int32)[None, :]).astype(
        jnp.float32
    )
    rows_local = jnp.arange(block, dtype=jnp.int32)

    def one_block(acc, i):
        xb = jax.lax.dynamic_slice(x_pad, (i * block, 0), (block, d))
        sqb = jax.lax.dynamic_slice(sq_pad, (i * block,), (block,))
        d2 = sqb[:, None] - 2.0 * (xb @ x.T) + sq[None, :]
        dist = jnp.sqrt(jnp.maximum(d2, 0.0))                    # [block, n]
        self_col = jnp.clip(i * block + rows_local, 0, n - 1)
        dist = dist.at[rows_local, self_col].set(0.0)
        ohr = jax.lax.dynamic_slice_in_dim(oh_pad, i * block, block, axis=0)
        return acc + ohr.T @ (dist @ oh), None

    sums, _ = jax.lax.scan(
        one_block, jnp.zeros((n_clusters, n_clusters), jnp.float32),
        jnp.arange(n_blocks, dtype=jnp.int32),
    )
    return sums, jnp.sum(oh, axis=0)


def euclidean_cluster_distance(
    x: np.ndarray, codes: np.ndarray, block: int = BW_BLOCK
) -> np.ndarray:
    """[C, C] mean pairwise Euclidean distance between cluster members,
    streamed — determineHierachy(return="distance") on `dist(pca)` without
    materialising it (reference :523, :699-735)."""
    codes = np.asarray(codes, np.int32)
    n_clusters = int(codes.max()) + 1
    sums, counts = euclidean_pair_sums(
        jnp.asarray(x, jnp.float32), jnp.asarray(codes), n_clusters, block
    )
    sums = np.asarray(sums, np.float64)
    counts = np.asarray(counts, np.float64)
    denom = np.outer(counts, counts)
    with np.errstate(invalid="ignore", divide="ignore"):
        return np.where(denom > 0, sums / np.maximum(denom, 1.0), np.inf)


def cocluster_cluster_distance(
    boot_labels: np.ndarray,
    codes: np.ndarray,
    max_clusters: int = 64,
    use_pallas: Optional[bool] = None,
) -> np.ndarray:
    """[C, C] mean co-clustering distance between final clusters, streamed —
    the determineHierachy(return="distance") input for the dendrogram when the
    dense matrix was never assembled (reference :621)."""
    codes = np.asarray(codes, np.int32)
    n_clusters = int(codes.max()) + 1
    sums, counts = cocluster_pair_sums(
        jnp.asarray(boot_labels, jnp.int32), jnp.asarray(codes), n_clusters,
        max_clusters, use_pallas=use_pallas,
    )
    sums = np.asarray(sums, np.float64)
    counts = np.asarray(counts, np.float64)
    denom = np.outer(counts, counts)
    with np.errstate(invalid="ignore", divide="ignore"):
        out = np.where(denom > 0, sums / np.maximum(denom, 1.0), np.inf)
    return out
