"""Cluster merging: small-cluster absorption and stability-based merging.

Equivalents of the reference's two merge loops:

  * small-cluster merge (reference R/consensusClust.R:461-467, 504-510):
    while the smallest cluster is below a threshold, fold it into the cluster
    with the nearest centroid under mean inter-member distance
    (determineHierachy(return="distance") semantics, :699-735);
  * stability merge (:469-497): per bootstrap, the pairwise adjusted-Rand
    ratio between the consensus clustering and the boot clustering on the
    boot's sampled cells; averaged over boots (NaN -> 1, diag -> 1); while the
    matrix minimum is below `min_stability`, merge the offending pair and
    recompute.

Merge loops run on host over cluster-count-sized matrices (SURVEY §7.1 —
irregular control is host-driven); the per-boot Rand passes and the mean
inter-member distances are device segment-sums. Stability rows are indexed by
compacted cluster id throughout, fixing the reference's dimnames mismatch
(docs/quirks.md item 8).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from consensusclustr_tpu.cluster.metrics import pairwise_rand


@functools.partial(jax.jit, static_argnames=("max_clusters",))
def cluster_mean_distance(
    dist: jax.Array, labels: jax.Array, max_clusters: int
) -> jax.Array:
    """[C, C] mean of cell-cell distances between members of each pair
    (the centroid-linkage matrix of determineHierachy, reference :699-735).
    Empty clusters get +inf rows/cols."""
    lab = jnp.asarray(labels, jnp.int32)
    n = lab.shape[0]
    onehot = (lab[:, None] == jnp.arange(max_clusters)[None, :]).astype(jnp.float32)
    counts = jnp.sum(onehot, axis=0)
    sums = onehot.T @ jnp.asarray(dist, jnp.float32) @ onehot          # [C, C]
    denom = jnp.outer(counts, counts)
    out = jnp.where(denom > 0, sums / jnp.maximum(denom, 1.0), jnp.inf)
    return out


def merge_small_clusters(
    dist: np.ndarray,
    labels: np.ndarray,
    min_size: int,
    max_clusters: int,
) -> np.ndarray:
    """Host-driven loop: fold the smallest under-threshold cluster into its
    nearest neighbour by mean inter-member distance (reference :462-467)."""
    labels = np.asarray(labels, np.int32).copy()
    while True:
        ids, counts = np.unique(labels, return_counts=True)
        if len(ids) <= 1:
            return labels
        smallest = ids[np.argmin(counts)]
        if counts.min() >= min_size:
            return labels
        cd = np.asarray(cluster_mean_distance(dist, labels, max_clusters))
        row = cd[smallest].copy()
        row[smallest] = np.inf
        row[[c for c in range(max_clusters) if c not in ids]] = np.inf
        target = int(np.argmin(row))
        labels[labels == smallest] = target


@functools.partial(jax.jit, static_argnames=("max_clusters", "max_boot_clusters"))
def stability_matrix(
    consensus: jax.Array,
    boot_labels: jax.Array,
    max_clusters: int,
    max_boot_clusters: int = 64,
) -> jax.Array:
    """Mean pairwise-Rand ratio across bootstraps (reference :470-481).

    consensus: [n] compact ids; boot_labels: [B, n] with -1 for unsampled.
    Per boot the comparison is restricted to sampled cells (:471). NaNs
    (empty pairs) -> 1 and diag -> 1 repairs (:485) are applied after the
    mean, as in the reference.
    """
    cons = jnp.asarray(consensus, jnp.int32)

    def per_boot(bl):
        valid = bl >= 0
        m = pairwise_rand(cons, jnp.maximum(bl, 0), max_clusters, max_boot_clusters, valid)
        return m

    mats = jax.vmap(per_boot)(jnp.asarray(boot_labels, jnp.int32))     # [B, C, C]
    mean = jnp.nanmean(mats, axis=0)
    mean = jnp.where(jnp.isnan(mean), 1.0, mean)
    c = mean.shape[0]
    return mean.at[jnp.arange(c), jnp.arange(c)].set(
        jnp.where(jnp.isnan(jnp.diagonal(mean)), 1.0, jnp.diagonal(mean))
    )


def merge_unstable_clusters(
    consensus: np.ndarray,
    boot_labels: np.ndarray,
    min_stability: float,
    max_clusters: int,
) -> np.ndarray:
    """Host loop over the tiny stability matrix (reference :489-495).

    The matrix is computed ONCE; the loop then patch-and-rescans it exactly
    as the reference does — merge the argmin pair's labels in the consensus,
    set the pair's two entries to 1, scan again — with NO recomputation, so
    stale entries of already-merged rows keep participating, as in the
    reference. (The reference also relabels its boot assignment matrix at
    :488; that has no observable effect — neither the stability matrix nor
    anything downstream reads boot labels afterwards — so it is skipped.)
    Diagonal minima (a cluster unstable against itself) merge nothing in the
    reference either: its clustersToMerge[1]==[2] relabelling is a no-op, and
    the diag patch to 1 gives progress — replicated here.
    """
    consensus = np.asarray(consensus, np.int32).copy()
    ids = np.unique(consensus)
    if len(ids) <= 1:
        return consensus
    occupied = np.zeros(max_clusters, bool)
    occupied[ids] = True
    sm = np.asarray(stability_matrix(consensus, boot_labels, max_clusters))
    sm = sm.copy()
    sm[~occupied, :] = np.inf
    sm[:, ~occupied] = np.inf
    while True:
        flat = int(np.argmin(sm))
        a, b = np.divmod(flat, sm.shape[1])
        if sm[a, b] >= min_stability:
            return consensus
        if a != b:
            # reference :487: cells of the col cluster move to the row
            # cluster. R's which(arr.ind=TRUE) is column-major, so its first
            # hit on the symmetric min pair (i<j) is row=j, col=i — the
            # SMALLER id is absorbed into the LARGER. Direction matters under
            # the stale-matrix rescan: later minima may reference the dead id.
            lo, hi = (a, b) if a < b else (b, a)
            consensus[consensus == lo] = hi
        sm[a, b] = 1.0
        sm[b, a] = 1.0
