"""Cluster merging: small-cluster absorption and stability-based merging.

Equivalents of the reference's two merge loops:

  * small-cluster merge (reference R/consensusClust.R:461-467, 504-510):
    while the smallest cluster is below a threshold, fold it into the cluster
    with the nearest centroid under mean inter-member distance
    (determineHierachy(return="distance") semantics, :699-735);
  * stability merge (:469-497): per bootstrap, the pairwise adjusted-Rand
    ratio between the consensus clustering and the boot clustering on the
    boot's sampled cells; averaged over boots (NaN -> 1, diag -> 1); while the
    matrix minimum is below `min_stability`, merge the offending pair and
    recompute.

Merge loops run on host over cluster-count-sized matrices (SURVEY §7.1 —
irregular control is host-driven); the per-boot Rand passes and the mean
inter-member distances are device segment-sums. Stability rows are indexed by
compacted cluster id throughout, fixing the reference's dimnames mismatch
(docs/quirks.md item 8).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from consensusclustr_tpu.cluster.metrics import pairwise_rand


@functools.partial(jax.jit, static_argnames=("max_clusters",))  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
def cluster_mean_distance(
    dist: jax.Array, labels: jax.Array, max_clusters: int
) -> jax.Array:
    """[C, C] mean of cell-cell distances between members of each pair
    (the centroid-linkage matrix of determineHierachy, reference :699-735).
    Empty clusters get +inf rows/cols."""
    lab = jnp.asarray(labels, jnp.int32)
    n = lab.shape[0]
    onehot = (lab[:, None] == jnp.arange(max_clusters, dtype=jnp.int32)[None, :]).astype(jnp.float32)
    counts = jnp.sum(onehot, axis=0)
    sums = onehot.T @ jnp.asarray(dist, jnp.float32) @ onehot          # [C, C]
    denom = jnp.outer(counts, counts)
    out = jnp.where(denom > 0, sums / jnp.maximum(denom, 1.0), jnp.inf)
    return out


def merge_small_clusters(
    dist: np.ndarray,
    labels: np.ndarray,
    min_size: int,
    max_clusters: int,
) -> np.ndarray:
    """Host-driven loop: fold the smallest under-threshold cluster into its
    nearest neighbour by mean inter-member distance (reference :462-467)."""
    labels = np.asarray(labels, np.int32).copy()
    while True:
        ids, counts = np.unique(labels, return_counts=True)
        if len(ids) <= 1:
            return labels
        smallest = ids[np.argmin(counts)]
        if counts.min() >= min_size:
            return labels
        cd = np.asarray(cluster_mean_distance(dist, labels, max_clusters))
        row = cd[smallest].copy()
        row[smallest] = np.inf
        row[[c for c in range(max_clusters) if c not in ids]] = np.inf
        target = int(np.argmin(row))
        labels[labels == smallest] = target


@functools.partial(jax.jit, static_argnames=("max_clusters", "max_boot_clusters"))  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
def stability_matrix(
    consensus: jax.Array,
    boot_labels: jax.Array,
    max_clusters: int,
    max_boot_clusters: int = 64,
) -> jax.Array:
    """Mean pairwise-Rand ratio across bootstraps (reference :470-481).

    consensus: [n] compact ids; boot_labels: [B, n] with -1 for unsampled.
    Per boot the comparison is restricted to sampled cells (:471). NaNs
    (empty pairs) -> 1 and diag -> 1 repairs (:485) are applied after the
    mean, as in the reference.
    """
    cons = jnp.asarray(consensus, jnp.int32)

    def per_boot(bl):
        valid = bl >= 0
        m = pairwise_rand(cons, jnp.maximum(bl, 0), max_clusters, max_boot_clusters, valid)
        return m

    mats = jax.vmap(per_boot)(jnp.asarray(boot_labels, jnp.int32))     # [B, C, C]
    mean = jnp.nanmean(mats, axis=0)
    mean = jnp.where(jnp.isnan(mean), 1.0, mean)
    c = mean.shape[0]
    return mean.at[jnp.arange(c, dtype=jnp.int32), jnp.arange(c, dtype=jnp.int32)].set(
        jnp.where(jnp.isnan(jnp.diagonal(mean)), 1.0, jnp.diagonal(mean))
    )


@functools.partial(jax.jit, static_argnames=("n_clusters",))  # graftlint: noqa[GL004] inner kernel traced inline from a counting_jit entry program; its own counter would double-count the work ledger
def restricted_pair_stats(
    agree: jax.Array,     # [n, m] restricted agree counts
    union: jax.Array,     # [n, m] restricted union counts
    cand_idx: jax.Array,  # [n, m] candidate-neighbour indices
    codes: jax.Array,     # [n] int32 cluster ids in [0, n_clusters)
    n_clusters: int,
) -> Tuple[jax.Array, jax.Array]:
    """(sums [C, C], pair_counts [C, C]) of co-clustering distances over the
    *restricted* (candidate) pairs, bucketed by (codes[i], codes[j]).

    The sparse regime's replacement for ``blockwise.cocluster_pair_sums``:
    the restricted counts are already in hand, so the cluster-pair merge
    statistics cost one O(n·m) segment-sum instead of streaming O(n²)
    distance tiles. Directed (j in cand[i] does not imply the reverse);
    consumers symmetrise. Pairs outside every candidate set contribute
    nothing — the mean is over candidate pairs, not member pairs
    (docs/perf.md "Choosing a consensus regime" discusses when that
    restriction is safe)."""
    dist = jnp.where(union > 0, 1.0 - agree / jnp.maximum(union, 1.0), 1.0)
    ci = jnp.asarray(codes, jnp.int32)[:, None]               # [n, 1]
    cj = jnp.asarray(codes, jnp.int32)[cand_idx]              # [n, m]
    flat = (ci * n_clusters + cj).reshape(-1)
    sums = jnp.zeros((n_clusters * n_clusters,), jnp.float32).at[flat].add(
        dist.reshape(-1)
    )
    counts = jnp.zeros((n_clusters * n_clusters,), jnp.float32).at[flat].add(1.0)
    return sums.reshape(n_clusters, n_clusters), counts.reshape(
        n_clusters, n_clusters
    )


def merge_small_clusters_from_pair_stats(
    sums: np.ndarray,
    pair_counts: np.ndarray,
    labels: np.ndarray,
    min_size: int,
) -> np.ndarray:
    """Small-cluster merge (reference :462-467) from restricted pair stats.

    The same host loop as ``blockwise.merge_small_clusters_from_sums`` but
    with an explicit per-pair count matrix (under the kNN restriction the
    pair count between clusters a and b is the number of candidate edges
    between them, not |a|·|b|). Directed inputs are symmetrised up front.
    A small cluster with no candidate edge into any live cluster (fully
    isolated in the restriction) folds into the largest live cluster — the
    deterministic stand-in for the dense path's always-finite argmin."""
    labels = np.asarray(labels, np.int32).copy()
    sums = np.asarray(sums, np.float64)
    sums = sums + sums.T
    pc = np.asarray(pair_counts, np.float64)
    pc = pc + pc.T
    member = np.bincount(labels, minlength=sums.shape[0]).astype(np.float64)
    while True:
        live = np.where(member > 0)[0]
        if len(live) <= 1:
            return labels
        smallest = live[np.argmin(member[live])]
        if member[live].min() >= min_size:
            return labels
        with np.errstate(invalid="ignore", divide="ignore"):
            row = np.where(
                pc[smallest] > 0, sums[smallest] / np.maximum(pc[smallest], 1.0),
                np.inf,
            )
        row[smallest] = np.inf
        row[member <= 0] = np.inf
        if np.isfinite(row).any():
            target = int(np.argmin(row))
        else:
            others = live[live != smallest]
            target = int(others[np.argmax(member[others])])
        labels[labels == smallest] = target
        # fold row then column: the diagonal picks up all four terms
        sums[target, :] += sums[smallest, :]
        sums[:, target] += sums[:, smallest]
        sums[smallest, :] = 0.0
        sums[:, smallest] = 0.0
        pc[target, :] += pc[smallest, :]
        pc[:, target] += pc[:, smallest]
        pc[smallest, :] = 0.0
        pc[:, smallest] = 0.0
        member[target] += member[smallest]
        member[smallest] = 0.0


def restricted_cluster_distance(
    agree: np.ndarray,
    union: np.ndarray,
    cand_idx: np.ndarray,
    codes: np.ndarray,
    n_clusters: int,
) -> np.ndarray:
    """[C, C] mean restricted co-clustering distance between final clusters —
    the sparse regime's dendrogram input (the determineHierachy
    return="distance" analog, reference :621) without any [n, n] pass.
    Cluster pairs with no candidate edge get +inf (joined last)."""
    sums, pc = restricted_pair_stats(
        jnp.asarray(agree, jnp.float32), jnp.asarray(union, jnp.float32),
        jnp.asarray(cand_idx, jnp.int32), jnp.asarray(codes, jnp.int32),
        int(n_clusters),
    )
    sums = np.asarray(sums, np.float64)
    pc = np.asarray(pc, np.float64)
    sums = sums + sums.T
    pc = pc + pc.T
    with np.errstate(invalid="ignore", divide="ignore"):
        out = np.where(pc > 0, sums / np.maximum(pc, 1.0), np.inf)
    # the dendrogram's diagonal is never read, but keep it sane (self
    # distance 0 on occupied clusters)
    occupied = np.asarray(np.bincount(
        np.asarray(codes, np.int64), minlength=int(n_clusters)
    ) > 0)
    out[np.diag_indices_from(out)] = np.where(occupied, 0.0, np.inf)
    return out


def stability_from_restricted_counts(
    agree: np.ndarray,
    union: np.ndarray,
    cand_idx: np.ndarray,
    codes: np.ndarray,
    n_clusters: int,
) -> np.ndarray:
    """[C] per-cluster stability from the restricted counts: the mean
    co-clustering rate (agree/union) over *within-cluster* candidate pairs.

    The sparse regime's stability diagonal for serving (serve/artifact.py
    ``stability_source = "cocluster_restricted"``): in [0, 1], 1 when every
    within-cluster candidate pair always co-clusters. Clusters with no
    within-cluster candidate pair (singletons under the restriction) get
    1.0 — the same repair as stability_matrix's NaN -> 1. Host numpy: the
    inputs are [n, m] and the loop-free reductions are cheap."""
    agree = np.asarray(agree, np.float64)
    union = np.asarray(union, np.float64)
    codes = np.asarray(codes, np.int64)
    cand_idx = np.asarray(cand_idx, np.int64)
    with np.errstate(invalid="ignore", divide="ignore"):
        jac = np.where(union > 0, agree / np.maximum(union, 1.0), 0.0)
    same = (codes[:, None] == codes[cand_idx]) & (union > 0)
    num = np.bincount(
        codes.repeat(cand_idx.shape[1])[same.reshape(-1)],
        weights=jac.reshape(-1)[same.reshape(-1)], minlength=int(n_clusters),
    )
    den = np.bincount(
        codes.repeat(cand_idx.shape[1])[same.reshape(-1)],
        minlength=int(n_clusters),
    )
    with np.errstate(invalid="ignore", divide="ignore"):
        out = np.where(den > 0, num / np.maximum(den, 1.0), 1.0)
    return out.astype(np.float32)


def merge_unstable_clusters(
    consensus: np.ndarray,
    boot_labels: np.ndarray,
    min_stability: float,
    max_clusters: int,
) -> np.ndarray:
    """Host loop over the tiny stability matrix (reference :489-495).

    The matrix is computed ONCE; the loop then patch-and-rescans it exactly
    as the reference does — merge the argmin pair's labels in the consensus,
    set the pair's two entries to 1, scan again — with NO recomputation, so
    stale entries of already-merged rows keep participating, as in the
    reference. (The reference also relabels its boot assignment matrix at
    :488; that has no observable effect — neither the stability matrix nor
    anything downstream reads boot labels afterwards — so it is skipped.)
    Diagonal minima (a cluster unstable against itself) merge nothing in the
    reference either: its clustersToMerge[1]==[2] relabelling is a no-op, and
    the diag patch to 1 gives progress — replicated here.
    """
    consensus = np.asarray(consensus, np.int32).copy()
    ids = np.unique(consensus)
    if len(ids) <= 1:
        return consensus
    occupied = np.zeros(max_clusters, bool)
    occupied[ids] = True
    sm = np.asarray(stability_matrix(consensus, boot_labels, max_clusters))
    sm = sm.copy()
    sm[~occupied, :] = np.inf
    sm[:, ~occupied] = np.inf
    while True:
        flat = int(np.argmin(sm))
        a, b = np.divmod(flat, sm.shape[1])
        if sm[a, b] >= min_stability:
            return consensus
        if a != b:
            # reference :487: cells of the col cluster move to the row
            # cluster. R's which(arr.ind=TRUE) is column-major, so its first
            # hit on the symmetric min pair (i<j) is row=j, col=i — the
            # SMALLER id is absorbed into the LARGER. Direction matters under
            # the stale-matrix rescan: later minima may reference the dead id.
            lo, hi = (a, b) if a < b else (b, a)
            consensus[consensus == lo] = hi
        sm[a, b] = 1.0
        sm[b, a] = 1.0
