from consensusclustr_tpu.consensus.bootstrap import bootstrap_indices, sampled_mask
from consensusclustr_tpu.consensus.cocluster import coclustering_distance
from consensusclustr_tpu.consensus.merge import (
    cluster_mean_distance,
    merge_small_clusters,
    stability_matrix,
    merge_unstable_clusters,
)
from consensusclustr_tpu.consensus.pipeline import consensus_cluster, ConsensusResult
