"""Clustree-style hierarchy table from lineage labels.

Equivalent of the reference's output-assembly dataframe for clustree
(reference R/consensusClust.R:590-606): lineage labels like "2_1_3" are split
on "_", prefix-joined per depth (so depth-2 column holds "2_1"), and cells
whose lineage ended early are forward-filled with their last label (the
`coalesce2` helper, :1043-1049). The reference then renders this with
clustree::clustree(prefix="Cluster"); here the table itself is the product —
any plotting stack can consume it (SURVEY §2.3 clustree row).
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np


def hierarchy_table(assignments: Sequence[str]) -> Dict[str, np.ndarray]:
    """Columns Cluster1..ClusterD of prefix-joined, forward-filled lineages.

    assignments: per-cell lineage strings ("2", "2_1", "2_1_3", ...).
    """
    parts: List[List[str]] = [str(a).split("_") for a in assignments]
    depth = max(len(p) for p in parts)
    table: Dict[str, np.ndarray] = {}
    for d in range(depth):
        col = ["_".join(p[: d + 1]) if len(p) > d else "_".join(p) for p in parts]
        table[f"Cluster{d + 1}"] = np.asarray(col, dtype=object)
    return table


def hierarchy_edges(assignments: Sequence[str]) -> List[tuple]:
    """(parent, child, n_cells) edges of the lineage tree — the clustree
    graph structure without the plotting dependency."""
    table = hierarchy_table(assignments)
    cols = sorted(table, key=lambda c: int(c.removeprefix("Cluster")))
    edges: Dict[tuple, int] = {}
    for a, b in zip(cols[:-1], cols[1:]):
        for parent, child in zip(table[a], table[b]):
            if parent != child:
                edges[(parent, child)] = edges.get((parent, child), 0) + 1
    return [(p, c, n) for (p, c), n in sorted(edges.items())]
