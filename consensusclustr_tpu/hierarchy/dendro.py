"""Cluster hierarchy: centroid-linkage distances + complete-linkage dendrogram.

Equivalent of the reference's ``determineHierachy`` (sic)
(reference R/consensusClust.R:699-735): the cluster x cluster distance is the
mean of all cell-cell distances between the two clusters' members, and the
dendrogram is complete-linkage agglomeration over that matrix. Cluster counts
are tiny (tens), so this layer is deliberately host-side numpy/scipy
(SURVEY §2.2 hclust row) — the expensive object, the cell x cell distance
matrix, was already computed on device.

``Dendrogram`` also carries the cut/walk operations ``testSplits`` needs
(cophenetic heights, cut-at-height memberships, subtrees;
reference :894-905, 985, 1003-1034).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Union

import numpy as np
from scipy.cluster import hierarchy as sch
from scipy.spatial.distance import squareform


def cluster_distance_matrix(
    dist: np.ndarray, assignments: Sequence
) -> tuple[np.ndarray, List]:
    """Mean between-member distance per cluster pair (reference :703-721).

    dist: [n, n] cell-cell distances; assignments: length-n labels (any
    hashable). Returns ([C, C] matrix, cluster label list in first-seen order
    of the sorted unique labels).
    """
    dist = np.asarray(dist)
    labels = np.asarray(assignments)
    uniq = _sorted_unique(labels)
    c = len(uniq)
    out = np.zeros((c, c), dtype=np.float64)
    members = [np.flatnonzero(labels == u) for u in uniq]
    for i in range(c):
        for j in range(i + 1, c):
            block = dist[np.ix_(members[i], members[j])]
            out[i, j] = out[j, i] = float(np.mean(block))
    return out, list(uniq)


def _sorted_unique(labels: np.ndarray) -> list:
    uniq = list(dict.fromkeys(labels.tolist()))
    try:
        return sorted(uniq, key=lambda v: (0, float(v)))
    except (TypeError, ValueError):
        return sorted(uniq, key=str)


@dataclasses.dataclass(frozen=True)
class Dendrogram:
    """Complete-linkage tree over cluster labels.

    linkage: scipy-format [(C-1), 4] merge matrix; labels[i] is leaf i.
    """

    linkage: np.ndarray
    labels: List

    @property
    def n_leaves(self) -> int:
        return len(self.labels)

    def cophenetic_heights(self) -> np.ndarray:
        """Sorted unique merge heights (the reference's `sps`, :895)."""
        return np.unique(self.linkage[:, 2])

    def first_split_height(self) -> float:
        """The reference's cut height for the top split (:895-897):
        ``sps = sort(unique(cophenetic), decreasing=T);
        floor(sps[max(which(sps > 0.85 * max(sps)))])`` — i.e. the floor of
        the SMALLEST merge height above 0.85 * max, so closely-spaced top
        merges are all cut in one step. The reference floors unconditionally,
        which on small-height trees (e.g. Jaccard distances <= 1) cuts at 0
        and shatters the tree; guard by backing off to just below the selected
        height (intent per SURVEY §7.3 item 6 / quirks ledger)."""
        sps = self.cophenetic_heights()
        top = float(sps.max())
        if top <= 0.0:
            # degenerate tree (all merge heights 0, e.g. duplicate rows):
            # cut at 0 => one branch, which callers treat as "no split"
            return 0.0
        sel = float(sps[sps > 0.85 * top].min())
        h = float(np.floor(sel))
        if not (sps.min() <= h < top):
            h = float(np.nextafter(sel, -np.inf))
        return h

    def cut_memberships(self, height: float) -> np.ndarray:
        """Branch id per leaf when cutting at `height` (dendextend::cutree
        analog, :897). Ids are 1..n_branches in leaf order."""
        if self.n_leaves == 1:
            return np.array([1])
        flat = sch.fcluster(self.linkage, t=height, criterion="distance")
        return flat

    def subtrees(self, height: float) -> List["Dendrogram"]:
        """The lower subtrees after cutting at `height` (stats::cut()$lower
        analog, :1003). Singleton branches come back as one-leaf trees."""
        memb = self.cut_memberships(height)
        out = []
        for b in np.unique(memb):
            leaf_idx = np.flatnonzero(memb == b)
            out.append(self.restrict([self.labels[i] for i in leaf_idx]))
        return out

    def restrict(self, keep_labels: Sequence) -> "Dendrogram":
        """Subtree over a label subset, re-agglomerated from cophenetic
        distances (equivalent for complete linkage)."""
        keep = [l for l in self.labels if l in set(keep_labels)]
        if len(keep) <= 1:
            return Dendrogram(linkage=np.zeros((0, 4)), labels=keep)
        full = squareform(sch.cophenet(self.linkage))
        idx = [self.labels.index(l) for l in keep]
        sub = full[np.ix_(idx, idx)]
        z = sch.linkage(squareform(sub, checks=False), method="complete")
        return Dendrogram(linkage=z, labels=keep)

    def merge_heights_below(self, height: float) -> np.ndarray:
        return self.linkage[self.linkage[:, 2] <= height, 2]


def dendrogram_from_cluster_distance(
    cmat: np.ndarray, labels: Sequence
) -> Dendrogram:
    """Dendrogram straight from a precomputed [C, C] mean-distance matrix —
    the blockwise-consensus path, where no cell-cell matrix was ever
    assembled (consensus/blockwise.py cocluster_cluster_distance)."""
    labels = list(labels)
    if len(labels) <= 1:
        return Dendrogram(linkage=np.zeros((0, 4)), labels=labels)
    cm = np.asarray(cmat, np.float64).copy()
    np.fill_diagonal(cm, 0.0)
    z = sch.linkage(squareform(cm, checks=False), method="complete")
    return Dendrogram(linkage=z, labels=labels)


def determine_hierarchy(
    distance_matrix: np.ndarray,
    assignments: Sequence,
    return_: str = "dendrogram",
) -> Union[Dendrogram, np.ndarray]:
    """Public API mirroring the reference export (NAMESPACE:4; :699-735).

    distance_matrix: [n, n] cell-cell distances (co-clustering or Euclidean
    PCA). return_: "dendrogram" | "hclust" (same object here) | "distance"
    (the [C, C] mean-linkage matrix).
    """
    if return_ not in ("dendrogram", "hclust", "distance"):
        raise ValueError(f"return_ must be dendrogram|hclust|distance; got {return_!r}")
    cmat, labels = cluster_distance_matrix(distance_matrix, assignments)
    if return_ == "distance":
        return cmat
    if len(labels) <= 1:
        return Dendrogram(linkage=np.zeros((0, 4)), labels=labels)
    z = sch.linkage(squareform(cmat, checks=False), method="complete")
    return Dendrogram(linkage=z, labels=labels)
