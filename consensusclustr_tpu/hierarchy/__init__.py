from consensusclustr_tpu.hierarchy.dendro import (
    Dendrogram,
    cluster_distance_matrix,
    determine_hierarchy,
)
from consensusclustr_tpu.hierarchy.clustree import hierarchy_table
