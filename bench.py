"""Benchmark harness: bootstraps/sec through the consensus inner loop.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The tracked metric is BASELINE.md's bootstraps/sec: full bootstrap grid
clusterings (kNN -> SNN -> Leiden over the (k, resolution) grid + silhouette
selection + alignment) plus the co-clustering distance accumulation — the
reference's hot loops 1-2 (R/consensusClust.R:388-421, SURVEY §3.1).

The reference publishes no numbers (BASELINE.md), so vs_baseline is measured
against the driver's north star rate: 1000 bootstraps x 12 resolutions on 50k
cells in <60 s => 16.67 boots/sec (BASELINE.json:5). vs_baseline > 1 beats it.

Env knobs: BENCH_CELLS, BENCH_BOOTS, BENCH_RES, BENCH_PCS (defaults scale with
the backend: accelerator vs CPU smoke).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


NORTH_STAR_BOOTS_PER_SEC = 1000.0 / 60.0


def main() -> None:
    import jax
    import jax.numpy as jnp

    from consensusclustr_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()

    from consensusclustr_tpu.config import ClusterConfig
    from consensusclustr_tpu.consensus.cocluster import coclustering_distance
    from consensusclustr_tpu.consensus.pipeline import run_bootstraps
    from consensusclustr_tpu.utils.rng import root_key

    on_accel = jax.default_backend() not in ("cpu",)
    n = int(os.environ.get("BENCH_CELLS", 10_000 if on_accel else 512))
    nboots = int(os.environ.get("BENCH_BOOTS", 24 if on_accel else 8))
    n_res = int(os.environ.get("BENCH_RES", 12))
    d = int(os.environ.get("BENCH_PCS", 20))

    rng = np.random.default_rng(0)
    centers = rng.normal(0.0, 6.0, size=(8, d))
    pca = (
        centers[rng.integers(0, 8, size=n)] + rng.normal(0, 1.0, size=(n, d))
    ).astype(np.float32)

    res_range = tuple(float(r) for r in np.linspace(0.05, 1.5, n_res))
    cfg = ClusterConfig(
        nboots=nboots, res_range=res_range, k_num=(10, 15, 20), max_clusters=64
    )
    key = root_key(123)
    pca_dev = jnp.asarray(pca)

    def run():
        labels, _ = run_bootstraps(key, pca_dev, cfg)
        dist = coclustering_distance(jnp.asarray(labels, jnp.int32), cfg.max_clusters)
        return jax.block_until_ready(dist)

    run()  # warmup: compiles the exact chunk shapes the timed run uses

    t0 = time.perf_counter()
    run()
    dt = time.perf_counter() - t0
    boots_per_sec = nboots / dt

    print(
        json.dumps(
            {
                "metric": f"bootstraps/sec ({n} cells, {n_res} res, k=3, to consensus matrix)",
                "value": round(boots_per_sec, 3),
                "unit": "boots/s",
                "vs_baseline": round(boots_per_sec / NORTH_STAR_BOOTS_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
