"""Benchmark harness: bootstraps/sec through the consensus inner loop.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

The tracked metric is BASELINE.md's bootstraps/sec: full bootstrap grid
clusterings (kNN -> SNN -> Leiden over the (k, resolution) grid + silhouette
selection + alignment) plus the co-clustering distance accumulation — the
reference's hot loops 1-2 (R/consensusClust.R:388-421, SURVEY §3.1).

The reference publishes no numbers (BASELINE.md), so vs_baseline is measured
against the driver's north star rate: 1000 bootstraps x 12 resolutions on 50k
cells in <60 s => 16.67 boots/sec (BASELINE.json:5). vs_baseline > 1 beats it.

Hardening contract (VERDICT r2 weak #2): this script never exits non-zero and
always emits the JSON line. Failure ladder:
  1. Pallas kernel failure -> einsum fallback (inside coclustering_distance).
  2. Unresponsive default backend (wedged serving tunnel) -> detected by a
     killable subprocess probe; CPU forced in-process via the live config
     (the JAX_PLATFORMS env var itself hangs interpreter start when the
     tunnel is wedged).
  3. Accelerator run failure (compile, OOM) -> bounded re-exec once on CPU
     (CCTPU_FORCE_CPU=1) with smoke-sized shapes.
  4. Anything else -> JSON line with value 0.0 and the error message.

Env knobs: BENCH_CELLS, BENCH_BOOTS, BENCH_RES, BENCH_PCS (defaults scale with
the backend: accelerator vs CPU smoke). CCTPU_BENCH_PROBE_BUDGET bounds the
backend-probe retry window (seconds, default 240; legacy
BENCH_PROBE_BUDGET_SECS honored); the probe verdict is cached per process and
its cost is reported as ``probe_s`` on every rung, separate from ``wall_s``.

Dispatch accounting (obs schema v3): every rung also carries
``device_dispatches`` / ``executable_compiles`` / ``donated_bytes`` — deltas
of the counting_jit counters (utils/compile_cache.py) across the rung, so
tools/bench_diff.py can gate on program-count regressions
(``--gate compiles:...``), not just boots/s.

Resource accounting (obs schema v4, ISSUE 6): every rung also carries
``peak_rss_mb`` / ``peak_device_mb`` (an obs/resource.py ResourceSampler
brackets the whole bench process — on by default here at 50 ms, overridable
via CCTPU_RESOURCE_SAMPLE_MS; peak_device_mb is null when the backend
reports no memory stats) and ``est_flops`` (delta of the counting_jit
``estimated_flops`` cost-model counter). ``tools/bench_diff.py --gate
rss:...`` turns peak_rss_mb into the O1 peak-memory regression gate.
BENCH_BALLAST_MB pins a deliberate host allocation for the run — the knob
that proves the gate can see an O1-scale regression.

Sparse-consensus accounting (ISSUE 9): every rung also carries a
``sparse_consensus`` block — the kNN-restricted consensus regime measured at
>= 8x the default rung's cells (BENCH_SPARSE_CELLS / BENCH_SPARSE_BOOTS
override), reporting boots/s, the consensus phase's own RSS watermark
(``cocluster_rss_peak_mb``, the O1 sub-quadratic gate surface:
``tools/bench_diff.py --gate sparse_rss:...``), the exact carry footprint
(``carry_mb`` = n*m*8 bytes vs ``dense_equiv_mb`` = n*n*8), and the rung's
consensus-label fingerprint.

Numerics accounting (obs schema v6, ISSUE 8): every rung also carries
``labels_fingerprint`` — the obs/fingerprint.py order-independent 64-bit
checksum of the rung's label output (final assignments for pbmc3k, consensus
labels for granular, the boot label matrix for the default rung; null on the
failure rung). ``tools/bench_diff.py --gate parity`` exits 3 when the
fingerprint drifts between two same-schema rounds — a label-level numeric
regression gate riding the existing bench trajectory. Setting CCTPU_NUMERICS
additionally threads watch/audit checkpoints through the measured run
itself.

Work-ledger + noise accounting (obs schema v7, ISSUE 12): every rung also
carries ``work_ledger`` (obs/ledger.py — total and per-top-level-phase
deltas of the deterministic WORK_LEDGER_COUNTERS; same seeded workload =>
same ledger on any host, however contended) and ``env_health`` (loadavg
before/during/after the measured run, nproc, cgroup cpu quota when present,
probe_s, and a fixed-work spin-calibration ``contention_ratio`` — the
direct evidence when a wall number moved but the ledger did not). The
default rung repeats its timed run (BENCH_WALL_TRIALS, default 3) and
reports ``wall_trials`` (per-trial walls, median, MAD, robust CV) with
``value``/``wall_s`` taken from the median, so every wall number carries
its own error bar. ``tools/bench_diff.py --gate work`` gates the ledger
exactly (any counter regression fails regardless of wall noise) while the
wall gates are noise-aware; ``tools/perf_history.py`` renders the whole
committed BENCH_*.json trajectory with ledger-vs-wall divergence notes.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time
import traceback

import numpy as np

# Schema version stamped on every JSON line (with the per-phase seconds) so
# BENCH_*.json trajectories stay machine-comparable across PRs. Guarded: the
# failure rung must emit even if the package itself cannot import.
try:
    from consensusclustr_tpu.obs.schema import SCHEMA_VERSION as _OBS_SCHEMA
except Exception:
    _OBS_SCHEMA = 0

# In-script CPU forcing (retry path): with a wedged serving tunnel the
# JAX_PLATFORMS env var hangs the interpreter inside the PJRT registration
# hook, but selecting the platform through the live config works.
if os.environ.get("CCTPU_FORCE_CPU"):
    import jax as _jax

    try:
        _jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


NORTH_STAR_BOOTS_PER_SEC = 1000.0 / 60.0
_RETRY_FLAG = "CCTPU_BENCH_CPU_RETRY"

# Process-cached backend-probe verdict (ISSUE 5 satellite): the probe is paid
# at most once per process; its outcome and wall cost are carried separately
# from the measured run (``probe`` / ``probe_s`` payload keys) so wall_s no
# longer silently absorbs up to the whole probe budget (r4's 22.3 s vs r5's
# 6.3 s was mostly probe noise). The CPU-retry subprocess inherits the
# verdict through CCTPU_BENCH_PROBE_* so it never re-probes either.
_PROBE_CACHE: dict = {}

# payload key -> process-global counter name (obs schema v3 dispatch
# accounting + the v4 est_flops cost-model denominator). Deduplicated
# (ISSUE 12 satellite): the single source is obs/ledger.py
# BENCH_DISPATCH_KEYS; the literal fallback keeps the failure rung emitting
# when the package cannot import, and tools/check_obs_schema.py pins the
# two copies equal and every counter name into METRIC_NAMES, both
# directions.
_DISPATCH_FALLBACK = {
    "device_dispatches": "device_dispatches",
    "executable_compiles": "executable_compiles",
    "donated_bytes": "donated_bytes",
    "est_flops": "estimated_flops",
    "est_bytes": "estimated_bytes_accessed",
}
try:
    from consensusclustr_tpu.obs.ledger import (
        BENCH_DISPATCH_KEYS as _DISPATCH_KEYS,
    )
except Exception:
    _DISPATCH_KEYS = _DISPATCH_FALLBACK

# Work-ledger counter order (obs schema v7): the deterministic counters the
# ``work_ledger`` block carries on every rung. Same fallback contract as
# _DISPATCH_KEYS — the literal is pinned to obs/ledger.py LEDGER_COUNTERS
# by tools/check_obs_schema.py.
_LEDGER_FALLBACK = (
    "device_dispatches",
    "executable_compiles",
    "estimated_flops",
    "estimated_bytes_accessed",
    "donated_bytes",
    "boots_completed",
    "fault_injected",
    "retry_attempts",
    "retries_exhausted",
    "ckpt_quarantined",
)
try:
    from consensusclustr_tpu.obs.ledger import (
        LEDGER_COUNTERS as _LEDGER_COUNTERS,
    )
except Exception:
    _LEDGER_COUNTERS = _LEDGER_FALLBACK


def _dispatch_counters() -> dict:
    """Current process-global dispatch/cost-accounting counters (obs schema
    v3/v4; sourced by utils/compile_cache.counting_jit). Guarded: the failure
    rung must emit even when the package cannot import."""
    out = {k: 0 for k in _DISPATCH_KEYS}
    try:
        from consensusclustr_tpu.obs import global_metrics

        counters = global_metrics().counters
        for key, name in _DISPATCH_KEYS.items():
            if name in counters:
                out[key] = int(counters[name].value)
    except Exception:
        pass
    return out


def _dispatch_delta(before: dict, after: dict) -> dict:
    return {k: max(0, after.get(k, 0) - before.get(k, 0)) for k in _DISPATCH_KEYS}


def _start_resource_sampler():
    """Bench-process ResourceSampler (obs/resource.py), started for the whole
    measured run. On by default HERE (50 ms) — bench exists to measure, so it
    opts in on behalf of the process; CCTPU_RESOURCE_SAMPLE_MS still
    overrides (including "0"/"off"). None when the obs layer cannot import
    (the failure rung then reports peak_rss_mb 0.0)."""
    try:
        from consensusclustr_tpu.obs.resource import (
            ResourceSampler,
            resolve_sample_ms,
        )

        ms = (
            resolve_sample_ms(None)
            if os.environ.get("CCTPU_RESOURCE_SAMPLE_MS")
            else 50
        )
        return ResourceSampler(ms).start()
    except Exception:
        return None


def _resource_rung(sampler) -> dict:
    """Stop ``sampler`` and report its peaks — emitted on every rung
    (including failure) so BENCH_*.json lines stay key-comparable and the
    O1 memory gate always has a denominator."""
    out = {"peak_rss_mb": 0.0, "peak_device_mb": None}
    if sampler is None:
        return out
    try:
        sampler.stop()
        if not sampler.samples:  # sampling disabled: still take one reading
            sampler.sample_now()
        out["peak_rss_mb"] = round(sampler.peak_rss_bytes / 1e6, 1)
        peak_dev = sampler.peak_device_bytes
        if peak_dev is not None:
            out["peak_device_mb"] = round(peak_dev / 1e6, 1)
    except Exception:
        pass
    return out

def _work_ledger_zero() -> dict:
    """The ``work_ledger`` zero shape: every registered counter at 0, no
    phases — emitted on the failure rung so the work gate always has a
    key-identical block to compare."""
    return {"counters": {k: 0 for k in _LEDGER_COUNTERS}, "phases": {}}


def _attach_ledger(tracer):
    """obs/ledger.py attach, guarded for the failure ladder (a rung must
    still emit when the obs layer cannot import)."""
    try:
        from consensusclustr_tpu.obs.ledger import attach_ledger

        return attach_ledger(tracer)
    except Exception:
        return None


def _work_ledger_block(tracer) -> dict:
    """The tracer's harvested ledger summary, or the zero shape."""
    try:
        led = getattr(tracer, "work_ledger", None)
        if led is not None:
            return led.summary()
    except Exception:
        pass
    return _work_ledger_zero()


# The program-attribution rung (ISSUE 16): per-counting_jit-program cost
# rows (utils/compile_cache.py program_profile) travel on every payload so
# bench_diff can gate a single program's bytes (--gate bytes:<program>) and
# perf_history can see a silent shift between programs under a flat
# aggregate. Top programs by est_bytes, shape buckets dropped for payload
# leanness. The zero shape rides the failure rung, key-identical.
_PROGRAM_PROFILE_TOP = 8


def _program_profile_zero() -> dict:
    """The ``program_profile`` zero shape: no rows, all totals 0 — emitted
    on the failure rung so the per-program gate always has a key-identical
    block to compare (tests/test_profiler.py pins the key parity)."""
    return {
        "programs": [],
        "n_programs": 0,
        "totals": {
            "dispatches": 0,
            "compiles": 0,
            "est_flops": 0.0,
            "est_bytes": 0.0,
            "donated_bytes": 0,
            "dispatch_wall_s": 0.0,
        },
    }


def _program_snapshot():
    """Registry snapshot marking a program-attribution window (or None when
    the package cannot import — the block then falls back to zero)."""
    try:
        from consensusclustr_tpu.utils.compile_cache import program_registry

        return program_registry()
    except Exception:
        return None


def _program_profile_block(since=None) -> dict:
    try:
        from consensusclustr_tpu.utils.compile_cache import program_profile

        return program_profile(
            since=since, top=_PROGRAM_PROFILE_TOP, shapes=False
        )
    except Exception:
        return _program_profile_zero()


# The lint rung (ISSUE 15): graftlint's summary travels on every payload so
# perf history records whether the gate was green at measurement time. The
# zero shape rides the failure rung (and any environment where the framework
# itself can't run) — key-identical, like every other block.
_LINT_ZERO = {"violations": 0, "baseline_size": 0, "rules_run": 0}


def _lint_block() -> dict:
    try:
        from tools.graftlint import core as _glcore

        res = _glcore.run(root=os.path.dirname(os.path.abspath(__file__)))
        return {
            "violations": len(res.violations),
            "baseline_size": res.baseline_size,
            "rules_run": len(res.rules_run),
        }
    except Exception:
        return dict(_LINT_ZERO)


# The wall-trials zero shape (failure rung; the default rung emits the real
# block, other configs measure one wall and omit it).
_WALL_TRIALS_ZERO = {
    "trials": 0,
    "walls_s": [],
    "median_s": 0.0,
    "mad_s": 0.0,
    "cv": 0.0,
}


def _wall_trials_block(walls) -> dict:
    """Robust per-trial wall statistics: median, MAD, and the robust CV
    (1.4826 * MAD / median — the normal-consistent scale estimate). CV is
    the error bar tools/bench_diff.py's noise-aware wall gates read: a
    regression on a high-CV rung with an unchanged ledger is contention
    evidence, not a code regression."""
    import statistics

    med = statistics.median(walls)
    mad = statistics.median([abs(w - med) for w in walls])
    cv = (1.4826 * mad / med) if med > 0 else 0.0
    return {
        "trials": len(walls),
        "walls_s": [round(w, 3) for w in walls],
        "median_s": round(med, 3),
        "mad_s": round(mad, 4),
        "cv": round(cv, 4),
    }


def _wall_trial_count() -> int:
    try:
        return max(1, int(os.environ.get("BENCH_WALL_TRIALS", "3") or 3))
    except ValueError:
        return 3


def _loadavg():
    try:
        return [round(x, 2) for x in os.getloadavg()]
    except Exception:
        return None


def _cpu_quota():
    """Effective cgroup CPU limit in cores (v2 cpu.max, then v1 cfs quota);
    None when unbounded or unreadable — the CI-container evidence that
    nproc overstates what the bench actually got."""
    try:
        with open("/sys/fs/cgroup/cpu.max") as f:
            quota, period = f.read().split()[:2]
        if quota != "max":
            return round(int(quota) / int(period), 2)
        return None
    except Exception:
        pass
    try:
        with open("/sys/fs/cgroup/cpu/cpu.cfs_quota_us") as f:
            quota = int(f.read())
        with open("/sys/fs/cgroup/cpu/cpu.cfs_period_us") as f:
            period = int(f.read())
        if quota > 0 and period > 0:
            return round(quota / period, 2)
    except Exception:
        pass
    return None


def _spin_calibration(reps: int = 5, n: int = 200_000):
    """Fixed-work spin reps: each rep executes the identical bytecode, so
    wall per rep varies only with host contention. Returns (best_ms,
    median/best ratio) — ratio ~1.0 on a quiet host, >1.5 under heavy
    core-sharing."""
    walls = []
    for _ in range(reps):
        t0 = time.perf_counter()
        acc = 0
        for i in range(n):
            acc += i
        walls.append((time.perf_counter() - t0) * 1000.0)
    walls.sort()
    best = walls[0]
    med = walls[len(walls) // 2]
    return best, (med / best if best > 0 else 0.0)


class _EnvHealth:
    """Environment-health telemetry bracketing the measured run (ISSUE 12):
    loadavg before/during/after, nproc, cgroup quota, and the worse of two
    spin-calibration contention readings (one before the run, one after).
    Stdlib-only and exception-guarded throughout — the failure rung carries
    the real block too (a failed round's contention evidence matters
    most)."""

    def __init__(self) -> None:
        self._best0, self._ratio0 = _spin_calibration()
        self._before = _loadavg()
        self._during = None

    def mark_after_run(self) -> None:
        # os.getloadavg is a 1-minute EMA: read right after the workload it
        # reflects the load *while* the run executed
        self._during = _loadavg()

    def block(self, probe_s: float) -> dict:
        best1, ratio1 = _spin_calibration()
        return {
            "nproc": int(os.cpu_count() or 0),
            "cpu_quota": _cpu_quota(),
            "loadavg_before": self._before,
            "loadavg_during": self._during or _loadavg(),
            "loadavg_after": _loadavg(),
            "probe_s": probe_s,
            "spin_best_ms": round(min(self._best0, best1), 3),
            "contention_ratio": round(max(self._ratio0, ratio1), 3),
        }


# The serving rung's zero shape — emitted verbatim on the failure rung so
# BENCH_*.json lines stay key-comparable across PRs.
_SERVING_ZERO = {
    "qps": 0.0,
    "latency_p50_ms": 0.0,
    "latency_p99_ms": 0.0,
    "bucket_compiles": 0,
}

# The serving-SLO rung's zero shape (ISSUE 7): the ladder block plus the two
# top-level gate rungs tools/bench_diff.py reads (--gate p99:... /
# serve_rejection_rate). Emitted on every rung including failure.
_SERVING_SLO_ZERO = {
    "serving_slo": {"steps": []},
    "serving_p99_ms": 0.0,
    "serve_rejection_rate": 0.0,
    # ISSUE 14: the saturation step's SLO alert state + any flight-recorder
    # post-mortem written during the rung — carried on every rung
    # (including failure) so BENCH_*.json lines stay key-comparable
    "alerts": {
        "active": [], "raised_total": 0, "cleared_total": 0,
        "last_alert": None,
    },
    "postmortem_path": None,
}


def _postmortem_path():
    """The process flight recorder's last dump path (obs/flight.py), or
    None on a clean run / unimportable package — the failure rung's
    breadcrumb to the black box."""
    try:
        from consensusclustr_tpu.obs.flight import global_flight

        rec = global_flight()
        return rec.last_dump_path if rec is not None else None
    except Exception:
        return None

# The fleet-SLO rung's zero shape (ISSUE 18): the 2-replica ladder block
# plus the top-level gate rungs tools/bench_diff.py reads
# (--gate fleet_p99:... / fleet_rejection_rate / fleet_swap_compiles).
# Emitted on every rung including failure.
_FLEET_SLO_ZERO = {
    "fleet_slo": {"steps": []},
    "fleet_p99_ms": 0.0,
    "fleet_rejection_rate": 0.0,
    "fleet_routed": {},
    "fleet_swap_compiles": 0,
    "fleet_trace": {},
}

# The warm-start rung's zero shape (ISSUE 13) — emitted verbatim on the
# failure rung so BENCH_*.json lines stay key-comparable across rounds.
_WARM_START_ZERO = {
    "buckets": 0,
    "cold_compiles": 0,
    "warm_compiles": 0,
    "cold_warmup_s": 0.0,
    "warm_warmup_s": 0.0,
    "warm_aot_hits": 0,
    "aot_entries": 0,
}

# One cold-process serving warm-up, self-reported: load the bundle, warm the
# service (no worker start), print the per-process executable_compiles /
# AOT-hit counters as JSON. Runs as a CHILD process so each measurement sees
# a genuinely cold jit cache — the only honest way to measure a
# cross-process warm start.
_WARM_START_CHILD = """
import json, sys, time
from consensusclustr_tpu.serve.artifact import ReferenceArtifact
from consensusclustr_tpu.serve.service import AssignmentService
from consensusclustr_tpu.obs import global_metrics

art = ReferenceArtifact.load(sys.argv[1])
t0 = time.perf_counter()
svc = AssignmentService(art, max_batch=int(sys.argv[2]), warmup=True,
                        start=False)
warmup_s = time.perf_counter() - t0
svc.close()
reg = global_metrics()


def _c(name):
    c = reg.counters.get(name)
    return int(c.value) if c is not None else 0


print(json.dumps({
    "warmup_s": round(warmup_s, 4),
    "executable_compiles": _c("executable_compiles"),
    "aot_hits": _c("aot_cache_hits"),
    "aot_saves": _c("aot_cache_saves"),
}))
"""


def _warm_start_rung() -> dict:
    """Cross-process AOT warm start (ISSUE 13): two cold interpreter runs of
    the SAME serving warm-up against one reference bundle and one AOT cache
    dir. Run 1 (cold cache) traces + compiles every bucket and serializes the
    executables; run 2 (warm cache) deserializes them. The rung reports both
    processes' ``executable_compiles`` and warm-up walls — the warm process
    must compile strictly less (tools/bench_diff.py gates
    ``warm_start.warm_compiles``). Never raises: any failure returns the
    zero shape with an error note."""
    try:
        import subprocess
        import tempfile

        from consensusclustr_tpu.serve.artifact import (
            ReferenceArtifact,
            level_tables,
        )
        from consensusclustr_tpu.serve.assign import (
            embed_reference_counts,
            resolve_buckets,
        )

        rng = np.random.default_rng(7)
        n_ref = int(os.environ.get("BENCH_WARM_REF", 256))
        g = int(os.environ.get("BENCH_WARM_GENES", 64))
        max_batch = 16
        d, n_classes = 6, 4

        loadings = np.linalg.qr(rng.normal(size=(g, d)))[0].astype(np.float32)
        mu = rng.gamma(1.0, 1.0, g).astype(np.float32)
        sigma = np.ones(g, np.float32)
        ref_counts = rng.poisson(2.0, size=(n_ref, g)).astype(np.float32)
        libsize_mean = float(ref_counts.sum(axis=1).mean())
        emb = embed_reference_counts(ref_counts, mu, sigma, loadings,
                                     libsize_mean)
        codes, tables = level_tables(
            np.asarray([str(c + 1) for c in rng.integers(0, n_classes, n_ref)])
        )
        art = ReferenceArtifact(
            embedding=emb, mu=mu, sigma=sigma, loadings=loadings,
            libsize_mean=libsize_mean, level_codes=codes, level_tables=tables,
            stability=np.ones(len(tables[-1]), np.float32), pc_num=d,
        )
        with tempfile.TemporaryDirectory() as tmp:
            art_path = os.path.join(tmp, "ref")
            art.save(art_path)
            aot_dir = os.path.join(tmp, "aot")
            env = dict(os.environ, CCTPU_AOT_CACHE_DIR=aot_dir)
            # the rung measures the AOT mechanism itself: no exporter ports,
            # no kill-switch leaking in from the surrounding round
            env.pop("CCTPU_SERVE_METRICS_PORT", None)
            env.pop("CCTPU_NO_AOT_CACHE", None)

            def _child() -> dict:
                proc = subprocess.run(
                    [sys.executable, "-c", _WARM_START_CHILD, art_path,
                     str(max_batch)],
                    env=env, stdout=subprocess.PIPE,
                    stderr=subprocess.DEVNULL, text=True, timeout=600,
                )
                return json.loads(proc.stdout.strip().splitlines()[-1])

            cold = _child()
            entries = len(os.listdir(aot_dir)) if os.path.isdir(aot_dir) else 0
            warm = _child()
        return {
            "buckets": len(resolve_buckets(None, max_batch)),
            "cold_compiles": int(cold["executable_compiles"]),
            "warm_compiles": int(warm["executable_compiles"]),
            "cold_warmup_s": float(cold["warmup_s"]),
            "warm_warmup_s": float(warm["warmup_s"]),
            "warm_aot_hits": int(warm["aot_hits"]),
            "aot_entries": entries,
        }
    except Exception as e:
        return dict(_WARM_START_ZERO, error=str(e)[:200])


# The sparse-consensus rung's zero shape (ISSUE 9) — emitted verbatim on the
# failure rung so BENCH_*.json lines stay key-comparable across rounds.
_SPARSE_CONSENSUS_ZERO = {
    "cells": 0,
    "boots": 0,
    "candidate_m": 0,
    "pairs_ratio": 0.0,
    "boots_per_sec": 0.0,
    "wall_s": 0.0,
    "n_clusters": 0,
    "peak_rss_mb": 0.0,
    "cocluster_rss_peak_mb": 0.0,
    "cocluster_rss_ceiling_mb": 0.0,
    "cocluster_rss_within_ceiling": True,
    "carry_mb": 0.0,
    "dense_equiv_mb": 0.0,
    "labels_fingerprint": None,
    "work_ledger": _work_ledger_zero(),
}


def _sparse_consensus_rung() -> dict:
    """kNN-restricted consensus at scale (ISSUE 9): the sparse_knn regime on
    a synthetic mixture at >= 8x the default rung's cell count (the largest
    shape the 240 s probe budget tolerates on CPU smoke; BENCH_SPARSE_CELLS
    / BENCH_SPARSE_BOOTS override). Reports boots/s, the rung's own
    peak-RSS watermarks — ``cocluster_rss_peak_mb`` is the consensus
    phase's span watermark, the O1 sub-quadratic gate surface — plus the
    EXACT accumulator footprint (``carry_mb`` = n*m*8 bytes) against the
    dense equivalent (``dense_equiv_mb`` = n*n*8 bytes), and the rung's
    consensus-label fingerprint. Never raises: any failure returns the zero
    shape with an error note.

    ISSUE 20 (the r18 "456.8 MB vs 2.1 MB carries" chase): the cocluster
    watermark is the sampler's ABSOLUTE process RSS during the span, not an
    accumulator delta — profiled in isolation, a fresh process's
    SparseCoclusterAccumulator at this rung's shape (n=4096, m=64) adds
    < 1 MB over the ~366 MB import/runtime floor across update(),
    distances() and consensus_knn(); the span number is dominated by the
    resident floor the boots phase leaves behind (retained executables +
    cached buffers), which is why it tracks peak_rss_mb, not carry_mb.
    Documented rather than "fixed": there is no cocluster transient to
    kill. The watermark is pinned by ``cocluster_rss_ceiling_mb``
    (BENCH_SPARSE_RSS_CEILING_MB, default 512 on CPU smoke) —
    ``cocluster_rss_within_ceiling`` flips false if a real transient ever
    appears, and ``--gate sparse_rss`` still gates the raw watermark
    relatively."""
    try:
        import jax
        import jax.numpy as jnp

        from consensusclustr_tpu.config import ClusterConfig
        from consensusclustr_tpu.consensus.pipeline import consensus_cluster
        from consensusclustr_tpu.obs import Tracer
        from consensusclustr_tpu.utils.log import LevelLog
        from consensusclustr_tpu.utils.rng import root_key

        backend = jax.default_backend()
        on_accel = backend not in ("cpu",)
        base = int(os.environ.get("BENCH_CELLS", 10_000 if on_accel else 512))
        n = int(os.environ.get("BENCH_SPARSE_CELLS", 8 * base))
        nboots = int(os.environ.get("BENCH_SPARSE_BOOTS", 24 if on_accel else 4))
        d = int(os.environ.get("BENCH_PCS", 20))

        rng = np.random.default_rng(0)
        centers = rng.normal(0.0, 6.0, size=(8, d))
        pca = (
            centers[rng.integers(0, 8, size=n)] + rng.normal(0, 1.0, size=(n, d))
        ).astype(np.float32)

        cfg = ClusterConfig(
            nboots=nboots, consensus_regime="sparse_knn",
            res_range=(0.1, 0.5, 1.0), k_num=(10, 15), max_clusters=64,
            resource_sample_ms=25,
        )
        tracer = Tracer()
        t0 = time.perf_counter()
        res = consensus_cluster(
            root_key(123), jnp.asarray(pca), cfg, log=LevelLog(tracer=tracer)
        )
        dt = time.perf_counter() - t0

        cocluster_rss = rss_peak = 0.0
        m = pairs_ratio = None
        for root in tracer.roots:
            for _, sp in root.walk():
                attrs = sp.attrs or {}
                if "rss_peak_bytes" in attrs:
                    rss_peak = max(rss_peak, float(attrs["rss_peak_bytes"]))
                if sp.name == "cocluster":
                    m = attrs.get("candidate_m", m)
                    pairs_ratio = attrs.get("pairs_ratio", pairs_ratio)
                    if "rss_peak_bytes" in attrs:
                        cocluster_rss = float(attrs["rss_peak_bytes"])
        m = int(m if m is not None else (res.sparse.m if res.sparse else 0))
        # absolute-watermark ceiling (see docstring): 512 MB covers the CPU
        # smoke floor with headroom; accelerator hosts carry bigger runtimes
        rss_ceiling = float(
            os.environ.get(
                "BENCH_SPARSE_RSS_CEILING_MB", 512.0 if not on_accel else 2048.0
            )
        )
        return {
            "cells": n,
            "boots": nboots,
            "candidate_m": m,
            "pairs_ratio": float(
                pairs_ratio if pairs_ratio is not None else m / max(n, 1)
            ),
            "boots_per_sec": round(nboots / dt, 3),
            "wall_s": round(dt, 3),
            "n_clusters": int(res.n_clusters),
            "peak_rss_mb": round(rss_peak / 1e6, 1),
            "cocluster_rss_peak_mb": round(cocluster_rss / 1e6, 1),
            "cocluster_rss_ceiling_mb": rss_ceiling,
            "cocluster_rss_within_ceiling": bool(
                cocluster_rss / 1e6 <= rss_ceiling
            ),
            # deterministic memory model: the restricted carries are exactly
            # 2 x [n, m] f32; the dense regime's would be 2 x [n, n]
            "carry_mb": round(n * m * 8 / 1e6, 2),
            "dense_equiv_mb": round(float(n) * n * 8 / 1e6, 2),
            "labels_fingerprint": _labels_fingerprint(res.labels),
            # consensus_cluster attached the ledger to this rung's tracer
            # (the direct-caller courtesy in consensus/pipeline.py)
            "work_ledger": _work_ledger_block(tracer),
        }
    except Exception as e:
        return dict(_SPARSE_CONSENSUS_ZERO, error=str(e)[:200])


def _load_loadgen():
    """tools/loadgen.py by file path (same pattern as tools/report.py's
    export loader — bench.py must not depend on tools/ being a package)."""
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "loadgen.py")
    spec = importlib.util.spec_from_file_location("_cctpu_loadgen", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _serving_slo_rung() -> dict:
    """Open-loop serving-SLO ladder (ISSUE 7 tentpole): tools/loadgen.py
    drives a live AssignmentService at >= 3 offered rates scaled off a
    closed-loop capacity probe (0.5x / 1x / 2x, so "saturated" means the
    same thing on every backend), each step reporting goodput, rejection
    rate and client-side p50/p99/p999. The gate surface is the SATURATION
    step (highest offered rate): ``serving_p99_ms`` and
    ``serve_rejection_rate`` land top-level so ``bench_diff --gate p99:...``
    can gate tail-latency regressions the way it gates boots/s, compiles and
    RSS. Env knobs: BENCH_SLO_RATES (comma list overrides the capacity
    scaling), BENCH_SLO_DURATION (seconds/step, default 1.5),
    BENCH_SLO_SIZES. Never raises: any failure returns the zero shape with
    an error note.
    """
    try:
        lg = _load_loadgen()
        from consensusclustr_tpu.serve.service import AssignmentService

        genes = int(os.environ.get("BENCH_SERVE_GENES", 256))
        n_ref = int(os.environ.get("BENCH_SERVE_REF", 2048))
        duration = float(os.environ.get("BENCH_SLO_DURATION", 1.5))
        mix = lg.parse_sizes(os.environ.get("BENCH_SLO_SIZES", "1:0.5,4:0.3,16:0.2"))
        art, _ = lg.synthetic_artifact(n_ref, genes, seed=0)

        rates_env = os.environ.get("BENCH_SLO_RATES", "").strip()
        if rates_env:
            rates = [float(r) for r in rates_env.split(",") if r.strip()]
        else:
            with AssignmentService(
                art, max_batch=64, queue_depth=16
            ) as probe_svc:
                cap = lg.estimate_capacity(probe_svc, mix, genes, n_requests=24)
            rates = [
                round(cap * f, 2) for f in (0.5, 1.0, 2.0)
            ]
        ladder = lg.slo_ladder(
            art, rates, duration, genes, mix, seed=7,
            queue_depth=16, max_batch=64,
        )
        # gate surface: the saturation (highest offered rate) step — the
        # number an SLO actually binds ("p99 under target AT saturation")
        sat = max(
            (s for s in ladder["steps"] if "error" not in s),
            key=lambda s: s.get("offered_rps", 0.0),
            default=None,
        )
        out = {"serving_slo": ladder}
        out["serving_p99_ms"] = float(sat["p99_ms"] or 0.0) if sat else 0.0
        out["serve_rejection_rate"] = (
            float(sat["rejection_rate"]) if sat else 0.0
        )
        # ISSUE 14: the saturation step's alert state (each ladder step
        # carries one — loadgen.step_alerts) lands top-level next to the
        # p99/rejection numbers it judges, plus the flight-recorder
        # breadcrumb (None on a clean rung: the recorder only writes on
        # failure).
        out["alerts"] = (
            dict((sat or {}).get("alerts") or {})
            or {k: (list(v) if isinstance(v, list) else v)
                for k, v in _SERVING_SLO_ZERO["alerts"].items()}
        )
        out["postmortem_path"] = _postmortem_path()
        return out
    except Exception as e:
        out = {k: (dict(v) if isinstance(v, dict) else v)
               for k, v in _SERVING_SLO_ZERO.items()}
        out["serving_slo"]["error"] = str(e)[:200]
        out["postmortem_path"] = _postmortem_path()
        return out


def _fleet_swap_pin(lg, art, rate, duration, genes, mix) -> dict:
    """Hot-swap-under-load pin (ISSUE 18b): one 2-replica fleet at a
    sub-saturation rate, ``swap_reference`` fired mid-schedule. The numbers
    that matter: ``failed`` must be 0 (the old generation drains — every
    accepted request completes) and ``swap_compiles`` must be 0 (the
    standby replicas warm from the AOT caches, never a fresh trace)."""
    import threading

    from consensusclustr_tpu.serve.fleet import build_fleet

    offsets = lg.schedule_offsets(rate, seed=11, duration=duration)
    run: dict = {}
    with build_fleet(art, 2, max_batch=64, queue_depth=16) as fleet:
        th = threading.Thread(
            target=lambda: run.update(
                lg.run_open_loop(fleet, offsets, mix, genes, seed=11)
            )
        )
        th.start()
        time.sleep(duration / 2.0)  # swap lands mid-schedule
        art2, _ = lg.synthetic_artifact(
            art.embedding.shape[0], len(art.mu), seed=0
        )
        report = fleet.swap_reference(art2)
        th.join(timeout=120.0)
        routed = fleet.routed_per_replica()
        # merged trace accounting (ISSUE 19): captured while the drained
        # generation's services are still open so their lanes survive
        fleet_trace = fleet.fleet_record().summary()
    return {
        "rate_rps": round(float(rate), 2),
        "swap_compiles": int(report["swap_compiles"]),
        "generation": int(report["generation"]),
        "submitted": run.get("submitted"),
        "completed": run.get("completed"),
        "rejected": run.get("rejected"),
        "failed": run.get("failed"),
        "routed": routed,
        "fleet_trace": fleet_trace,
    }


def _fleet_slo_rung(rates=None) -> dict:
    """Fleet-SLO ladder (ISSUE 18): the serving_slo ladder re-run against a
    2-replica FleetRouter at the SAME offered rates — the committed
    evidence that two replicas behind health-keyed admission sustain a
    higher goodput plateau than one replica at the same offered load, with
    per-step alert state and the routed-per-replica split recorded. Also
    runs the hot-swap-under-load pin (``fleet_slo.swap``) whose compile
    count lands top-level as ``fleet_swap_compiles``. Never raises: any
    failure returns the zero shape with an error note."""
    try:
        lg = _load_loadgen()

        genes = int(os.environ.get("BENCH_SERVE_GENES", 256))
        n_ref = int(os.environ.get("BENCH_SERVE_REF", 2048))
        duration = float(os.environ.get("BENCH_SLO_DURATION", 1.5))
        mix = lg.parse_sizes(
            os.environ.get("BENCH_SLO_SIZES", "1:0.5,4:0.3,16:0.2")
        )
        art, _ = lg.synthetic_artifact(n_ref, genes, seed=0)

        if not rates:
            # standalone fallback (BENCH_SLO_RATES or a fresh capacity
            # probe) — the payload path hands over serving_slo's rates so
            # the one-vs-two-replica comparison is at identical offered load
            rates_env = os.environ.get("BENCH_SLO_RATES", "").strip()
            if rates_env:
                rates = [float(r) for r in rates_env.split(",") if r.strip()]
            else:
                from consensusclustr_tpu.serve.service import (
                    AssignmentService,
                )

                with AssignmentService(
                    art, max_batch=64, queue_depth=16
                ) as probe_svc:
                    cap = lg.estimate_capacity(
                        probe_svc, mix, genes, n_requests=24
                    )
                rates = [round(cap * f, 2) for f in (0.5, 1.0, 2.0)]
        ladder = lg.slo_ladder(
            art, rates, duration, genes, mix, seed=7,
            queue_depth=16, max_batch=64, target="fleet", replicas=2,
        )
        ladder["replicas"] = 2
        ladder["swap"] = _fleet_swap_pin(
            lg, art, min(rates), duration, genes, mix
        )
        sat = max(
            (s for s in ladder["steps"] if "error" not in s),
            key=lambda s: s.get("offered_rps", 0.0),
            default=None,
        )
        out = {"fleet_slo": ladder}
        out["fleet_p99_ms"] = float(sat["p99_ms"] or 0.0) if sat else 0.0
        out["fleet_rejection_rate"] = (
            float(sat["rejection_rate"]) if sat else 0.0
        )
        out["fleet_routed"] = dict((sat or {}).get("routed") or {})
        out["fleet_swap_compiles"] = int(
            ladder["swap"].get("swap_compiles") or 0
        )
        out["fleet_trace"] = dict(ladder["swap"].get("fleet_trace") or {})
        return out
    except Exception as e:
        out = {k: (dict(v) if isinstance(v, dict) else v)
               for k, v in _FLEET_SLO_ZERO.items()}
        out["fleet_slo"]["error"] = str(e)[:200]
        return out


def _slo_rungs() -> dict:
    """serving_slo + fleet_slo in one block, the fleet ladder at the same
    offered rates as the single-replica ladder (extracted from its steps) —
    the apples-to-apples goodput comparison ISSUE 18 gates on."""
    out = _serving_slo_rung()
    rates = [
        s["target_rps"]
        for s in out.get("serving_slo", {}).get("steps", [])
        if s.get("target_rps")
    ]
    out.update(_fleet_slo_rung(rates))
    return out


def _resilience_counters(tracer=None) -> dict:
    """Per-rung resilience telemetry (resilience/, ISSUE 10): retry and
    quarantine counters from the rung's run-local registry — all zero on a
    healthy run, non-zero when the rung survived transient faults (flaky
    disk under the checkpoint writer, a wedged dispatch that recovered).
    Guarded like the dispatch counters: the failure rung emits the zero
    shape even when the package cannot import."""
    names = (
        "fault_injected", "retry_attempts", "retries_exhausted",
        "ckpt_quarantined",
    )
    out = {k: 0 for k in names}
    try:
        counters = tracer.metrics.counters if tracer is not None else {}
        for name in names:
            if name in counters:
                out[name] = int(counters[name].value)
    except Exception:
        pass
    return {"resilience": out}


def _labels_fingerprint(labels) -> "str | None":
    """Order-independent 64-bit checksum (obs/fingerprint.py) of a rung's
    label output — the per-rung parity surface ``tools/bench_diff.py
    --gate parity`` compares across rounds (obs schema v6). String labels
    fingerprint through their sorted-unique integer codes; any failure
    (including the package not importing on the failure rung) reports None,
    and the parity gate treats a missing fingerprint as a loud error, not a
    pass."""
    try:
        from consensusclustr_tpu.obs.fingerprint import array_fingerprint

        labels = np.asarray(labels)
        if labels.dtype.kind not in "biufc":
            labels = np.unique(labels, return_inverse=True)[1]
        return array_fingerprint(labels.astype(np.int32))["checksum"]
    except Exception:
        return None


def _emit(payload: dict) -> None:
    print(json.dumps(payload), flush=True)


def _serving_rung() -> dict:
    """Online-assignment micro-bench (serve/): synthetic frozen reference →
    artifact save/load round trip (checksum exercised) → AssignmentService →
    micro-batched queries of mixed sizes. Reports requests/sec (qps),
    client-observed p50/p99 latency, and how many bucket shapes compiled —
    the executables-reused-across-request-sizes claim, measured.

    The reference model is synthetic (random loadings + labels): this rung
    measures serving MECHANICS (compile reuse, queue, vote kernel), which do
    not depend on fit quality; the offline rungs measure fitting. Shapes via
    BENCH_SERVE_REF / BENCH_SERVE_GENES / BENCH_SERVE_REQUESTS. Never
    raises: any failure returns the zero shape with an error note.
    """
    try:
        import tempfile

        from consensusclustr_tpu.serve.artifact import (
            ReferenceArtifact,
            level_tables,
        )
        from consensusclustr_tpu.serve.assign import embed_reference_counts
        from consensusclustr_tpu.serve.service import (
            AssignmentService,
            RetryableRejection,
        )

        rng = np.random.default_rng(0)
        n_ref = int(os.environ.get("BENCH_SERVE_REF", 2048))
        g = int(os.environ.get("BENCH_SERVE_GENES", 256))
        n_req = int(os.environ.get("BENCH_SERVE_REQUESTS", 64))
        d, n_classes, max_batch = 10, 8, 64

        loadings = np.linalg.qr(rng.normal(size=(g, d)))[0].astype(np.float32)
        mu = rng.gamma(1.0, 1.0, g).astype(np.float32)
        sigma = np.ones(g, np.float32)
        ref_counts = rng.poisson(2.0, size=(n_ref, g)).astype(np.float32)
        libsize_mean = float(ref_counts.sum(axis=1).mean())
        emb = embed_reference_counts(ref_counts, mu, sigma, loadings, libsize_mean)
        codes, tables = level_tables(
            np.asarray([str(c + 1) for c in rng.integers(0, n_classes, n_ref)])
        )
        art = ReferenceArtifact(
            embedding=emb, mu=mu, sigma=sigma, loadings=loadings,
            libsize_mean=libsize_mean, level_codes=codes, level_tables=tables,
            stability=np.ones(len(tables[-1]), np.float32), pc_num=d,
        )
        with tempfile.TemporaryDirectory() as tmp:
            art.save(tmp)
            art = ReferenceArtifact.load(tmp)

        sizes = rng.integers(1, max_batch + 1, size=n_req)
        queries = [
            rng.poisson(2.0, size=(int(s), g)).astype(np.float32) for s in sizes
        ]
        with AssignmentService(
            art, max_batch=max_batch, queue_depth=16, warmup=True
        ) as svc:
            t0 = time.perf_counter()
            futs = []
            for q in queries:
                while True:
                    try:
                        futs.append(svc.submit(q))
                        break
                    except RetryableRejection:
                        time.sleep(0.001)
            for f in futs:
                f.result(timeout=300)
            wall = time.perf_counter() - t0
            compiles = svc.bucket_compiles
            # bucketed-histogram estimates (obs/hist.py): the same numbers
            # tools/serve_demo.py prints and the /metrics endpoint scrapes
            hist = svc.metrics.histogram("serve_latency_seconds")
            p50 = 1000.0 * (hist.quantile(0.5) or 0.0)
            p99 = 1000.0 * (hist.quantile(0.99) or 0.0)
        return {
            "qps": round(n_req / wall, 2),
            "latency_p50_ms": round(p50, 3),
            "latency_p99_ms": round(p99, 3),
            "bucket_compiles": int(compiles),
            "cells_per_sec": round(float(sizes.sum()) / wall, 1),
            "requests": n_req,
            "ref_cells": n_ref,
        }
    except Exception as e:
        return dict(_SERVING_ZERO, error=str(e)[:200])


def _pipeline_depth() -> int:
    """Resolved CCTPU_PIPELINE_DEPTH, guarded for the failure rung (the env
    value or even the package import may be broken; the JSON line must
    still emit)."""
    try:
        from consensusclustr_tpu.parallel.pipelined import pipeline_depth

        return pipeline_depth()
    except Exception:
        return 0


def _overlap_ratio(spans) -> float:
    """Per-run overlap ratio from the span tree: total `overlap_seconds`
    (device compute in flight while the host worked — the pipelined chunk
    loops stamp it on their boots / null_sims spans) over those spans'
    wall seconds. 0.0 when nothing pipelined ran; can exceed 1.0 when
    depth > 2 keeps multiple chunks in flight simultaneously."""
    overlap = seconds = 0.0
    for root in spans or []:
        for _, sp in root.walk():
            attrs = sp.attrs or {}
            if "overlap_seconds" in attrs and "pipeline_depth" in attrs:
                overlap += float(attrs["overlap_seconds"])
                seconds += float(sp.seconds or 0.0)
    return round(overlap / seconds, 4) if seconds > 0 else 0.0


def _run_pbmc3k() -> dict:
    """BASELINE config 1: pbmc3k-shaped NB fixture (2,700 cells, realistic
    sparsity + depth variation), 100 bootstraps, pcNum=5, Leiden, full
    consensus_clust end to end. Select with BENCH_CONFIG=pbmc3k."""
    import time as _time

    import jax

    from consensusclustr_tpu.api import consensus_clust
    from consensusclustr_tpu.utils.compile_cache import enable_persistent_cache
    from consensusclustr_tpu.utils.synth import nb_mixture_counts

    enable_persistent_cache()
    nboots = int(os.environ.get("BENCH_BOOTS", 100))
    counts, truth = nb_mixture_counts(seed=42)
    t0 = _time.perf_counter()
    res = consensus_clust(counts, nboots=nboots, pc_num=5, seed=1)
    dt = _time.perf_counter() - t0

    from consensusclustr_tpu.consensus import cocluster as _cocluster_mod

    codes = np.unique(res.assignments, return_inverse=True)[1]
    n_pops = len(np.unique(truth))
    ct = np.zeros((n_pops, codes.max() + 1))
    np.add.at(ct, (truth, codes), 1)
    comb = lambda v: v * (v - 1) / 2.0  # noqa: E731
    s_ij = comb(ct).sum(); s_a = comb(ct.sum(1)).sum(); s_b = comb(ct.sum(0)).sum()
    tot = comb(len(codes)); exp = s_a * s_b / tot; mx = 0.5 * (s_a + s_b)
    ari = float((s_ij - exp) / (mx - exp)) if mx != exp else 1.0
    # per-phase breakdown straight from the run's RunRecord (obs/)
    phases = (
        {k: round(v, 3) for k, v in res.run_record.phase_seconds().items()}
        if res.run_record is not None
        else {}
    )
    return {
        "metric": f"pbmc3k e2e wall ({nboots} boots, pcNum=5)",
        "value": round(dt, 2),
        "unit": "s",
        "vs_baseline": round((nboots / dt) / NORTH_STAR_BOOTS_PER_SEC, 3),
        "backend": jax.default_backend(),
        "path": _cocluster_mod.LAST_PATH,
        "n_clusters": int(res.n_clusters),
        "ari_vs_truth": round(ari, 4),
        "boots_per_sec": round(nboots / dt, 3),
        "labels_fingerprint": _labels_fingerprint(res.assignments),
        # api.consensus_clust attaches the ledger unconditionally; the
        # RunRecord carries its harvested summary (schema v7)
        "work_ledger": (
            res.run_record.work_ledger
            if res.run_record is not None and res.run_record.work_ledger
            else _work_ledger_zero()
        ),
        "phases": phases,
        "pipeline_depth": _pipeline_depth(),
        "overlap_ratio": _overlap_ratio(
            res.run_record.spans if res.run_record is not None else []
        ),
        "serving": _serving_rung(),
        **_slo_rungs(),
        "sparse_consensus": _sparse_consensus_rung(),
        "warm_start": _warm_start_rung(),
        "obs_schema": _OBS_SCHEMA,
    }


def _run_granular() -> dict:
    """BASELINE config 2: granular mode at scale — every (k, res) candidate
    of every boot joins the consensus (B_eff = nboots * |k| * |res| candidate
    rows) through the blockwise consensus path. Defaults mirror the config's
    500 boots x res 0.1-2.0 on 10k cells (accelerator) and smoke shapes on
    CPU. Select with BENCH_CONFIG=granular."""
    import jax
    import jax.numpy as jnp

    from consensusclustr_tpu.config import ClusterConfig
    from consensusclustr_tpu.consensus.pipeline import consensus_cluster
    from consensusclustr_tpu.obs import Tracer
    from consensusclustr_tpu.utils.log import LevelLog
    from consensusclustr_tpu.utils.rng import root_key

    backend = jax.default_backend()
    on_accel = backend not in ("cpu",)
    n = int(os.environ.get("BENCH_CELLS", 10_000 if on_accel else 512))
    nboots = int(os.environ.get("BENCH_BOOTS", 500 if on_accel else 4))
    n_res = int(os.environ.get("BENCH_RES", 20 if on_accel else 6))
    d = int(os.environ.get("BENCH_PCS", 20))

    rng = np.random.default_rng(0)
    centers = rng.normal(0.0, 6.0, size=(8, d))
    pca = (
        centers[rng.integers(0, 8, size=n)] + rng.normal(0, 1.0, size=(n, d))
    ).astype(np.float32)

    cfg = ClusterConfig(
        nboots=nboots, mode="granular", dense_consensus=False,
        res_range=tuple(float(r) for r in np.linspace(0.1, 2.0, n_res)),
        k_num=(10, 15, 20), max_clusters=64,
    )
    b_eff = nboots * len(cfg.k_num) * n_res

    key = root_key(123)
    pca_dev = jnp.asarray(pca)
    tracer = Tracer()
    _attach_ledger(tracer)
    t0 = time.perf_counter()
    res = consensus_cluster(key, pca_dev, cfg, log=LevelLog(tracer=tracer))
    dt = time.perf_counter() - t0
    return {
        "metric": (
            f"granular consensus wall ({n} cells, {nboots} boots x "
            f"{len(cfg.k_num)}k x {n_res} res = {b_eff} candidates, blockwise)"
        ),
        "value": round(dt, 2),
        "unit": "s",
        "vs_baseline": round((nboots / dt) / NORTH_STAR_BOOTS_PER_SEC, 3),
        "backend": backend,
        # dense_consensus=False never forms the [n, n] matrix, so the
        # pallas/einsum dispatch is not in play here
        "path": "blockwise",
        "boots_per_sec": round(nboots / dt, 3),
        "labels_fingerprint": _labels_fingerprint(res.labels),
        "work_ledger": _work_ledger_block(tracer),
        "candidate_rows": b_eff,
        "n_clusters": int(res.n_clusters),
        "phases": {k: round(v, 3) for k, v in tracer.phase_seconds().items()},
        "pipeline_depth": _pipeline_depth(),
        "overlap_ratio": _overlap_ratio(tracer.roots),
        **_resilience_counters(tracer),
        "serving": _serving_rung(),
        **_slo_rungs(),
        "sparse_consensus": _sparse_consensus_rung(),
        "warm_start": _warm_start_rung(),
        "obs_schema": _OBS_SCHEMA,
    }


def _run() -> dict:
    import jax
    import jax.numpy as jnp

    from consensusclustr_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()

    if os.environ.get("BENCH_CONFIG") == "pbmc3k":
        return _run_pbmc3k()
    if os.environ.get("BENCH_CONFIG") == "granular":
        return _run_granular()

    from consensusclustr_tpu import consensus as _  # noqa: F401  (import check)
    from consensusclustr_tpu.config import ClusterConfig
    from consensusclustr_tpu.consensus import cocluster as cocluster_mod
    from consensusclustr_tpu.obs import Tracer
    from consensusclustr_tpu.ops import pallas_cocluster as _pallas_mod
    from consensusclustr_tpu.consensus.cocluster import (
        CoclusterAccumulator,
        _pallas_wanted,
        coclustering_distance,
    )
    from consensusclustr_tpu.consensus.pipeline import run_bootstraps
    from consensusclustr_tpu.utils.log import LevelLog
    from consensusclustr_tpu.utils.rng import root_key

    backend = jax.default_backend()
    on_accel = backend not in ("cpu",)
    n = int(os.environ.get("BENCH_CELLS", 10_000 if on_accel else 512))
    nboots = int(os.environ.get("BENCH_BOOTS", 24 if on_accel else 8))
    n_res = int(os.environ.get("BENCH_RES", 12))
    d = int(os.environ.get("BENCH_PCS", 20))

    rng = np.random.default_rng(0)
    centers = rng.normal(0.0, 6.0, size=(8, d))
    pca = (
        centers[rng.integers(0, 8, size=n)] + rng.normal(0, 1.0, size=(n, d))
    ).astype(np.float32)

    res_range = tuple(float(r) for r in np.linspace(0.05, 1.5, n_res))
    # boots_per_program=2 (ISSUE 20): scan chunk/2 groups of a width-2 vmap
    # inside the one boot dispatch — ~4x less _boot_batch est_bytes at
    # bit-identical labels (tests/test_byte_diet.py); BENCH_BPP overrides,
    # 0 restores the historical one-vmap-per-chunk program.
    bpp = int(os.environ.get("BENCH_BPP", 2))
    cfg = ClusterConfig(
        nboots=nboots, res_range=res_range, k_num=(10, 15, 20),
        max_clusters=64, boots_per_program=bpp,
    )
    key = root_key(123)
    pca_dev = jnp.asarray(pca)

    # Flat dispatch keys (schema v3/v4) bracket the HEADLINE workload only
    # (warmup + trials + the parity probe), not the auxiliary sub-rungs:
    # ISSUE 13 routes the serving path through counting_jit, so a
    # process-wide window would conflate serving-rung instrumentation with
    # the consensus workload these keys exist to compare round over round.
    # main() only fills keys a config didn't set itself (failure rung and
    # the non-default configs keep the historical process-wide window).
    flat0 = _dispatch_counters()
    # per-program attribution shares the same headline window: rows below
    # decompose exactly the est_flops/est_bytes deltas emitted above them
    prog0 = _program_snapshot()

    # Mirror the production dense dispatch (consensus/pipeline.py): the
    # einsum regime streams counts through the donated accumulator during the
    # boot loop (bit-identical to the one-shot pass; exercises donated_bytes);
    # the Pallas regime keeps the one-shot tiled kernel so TPU rounds still
    # measure (and parity-check) the kernel itself.
    streamed = not _pallas_wanted(cfg.use_pallas, cfg.max_clusters)

    def run(tracer):
        # spans cover the whole timed region: "boots" opens inside
        # run_bootstraps, "cocluster" here — so the emitted phases dict
        # accounts for (within rounding) all of wall_s
        acc = CoclusterAccumulator(n, cfg.max_clusters) if streamed else None
        labels, _ = run_bootstraps(
            key, pca_dev, cfg, LevelLog(tracer=tracer), accumulator=acc
        )
        with tracer.span("cocluster") as sp:
            if acc is not None:
                dist = acc.distance()
            else:
                dist = coclustering_distance(
                    jnp.asarray(labels, jnp.int32), cfg.max_clusters,
                    use_pallas=cfg.use_pallas,
                )
            sp.value = dist
        jax.block_until_ready(dist)
        return labels

    run(Tracer())  # warmup: compiles the exact chunk shapes the timed run uses

    # Repeated-trial measurement (ISSUE 12): each trial reruns the identical
    # post-warmup workload on a fresh tracer; the headline value/wall_s come
    # from the MEDIAN wall and ``wall_trials`` carries the spread. The work
    # ledger is harvested over trial 0 only, so its counters stay
    # trial-count-independent (same workload => same ledger).
    trials = _wall_trial_count()
    walls = []
    tracer = timed_labels = ledger_block = None
    for t in range(trials):
        tr = Tracer()
        _attach_ledger(tr)
        t0 = time.perf_counter()
        labels = run(tr)
        walls.append(time.perf_counter() - t0)
        if t == 0:
            tracer, timed_labels = tr, labels
            ledger_block = _work_ledger_block(tr)
    wall_trials = _wall_trials_block(walls)
    dt = wall_trials["median_s"]
    boots_per_sec = nboots / dt
    # snapshot BEFORE the parity block below: its small dispatch also sets
    # LAST_PATH/LAST_VARIANT and could misattribute the timed number (e.g.
    # timed run fell back to einsum, tiny parity shape compiled on Pallas)
    timed_path = cocluster_mod.LAST_PATH
    timed_variant = _pallas_mod.LAST_VARIANT if timed_path == "pallas" else None

    # On-accelerator parity artifact: the dispatched kernel (Pallas on TPU)
    # against the einsum oracle on a small labels sample. Honesty contract
    # (VERDICT r3 weak #2): the field is null unless the Pallas path actually
    # ran — an einsum-vs-einsum comparison is not kernel evidence.
    parity = None
    try:
        from consensusclustr_tpu.consensus.cocluster import (
            _einsum_coclustering_distance,
        )

        lab = jnp.asarray(
            rng.integers(-1, 8, size=(32, 512)).astype(np.int32)
        )
        d_dispatch = coclustering_distance(lab, 64, use_pallas=cfg.use_pallas)
        if cocluster_mod.LAST_PATH == "pallas":
            d_oracle = _einsum_coclustering_distance(lab, 64)
            parity = float(jnp.max(jnp.abs(d_dispatch - d_oracle)))
    except Exception:
        pass

    return {
        "metric": f"bootstraps/sec ({n} cells, {n_res} res, k=3, to consensus matrix)",
        "value": round(boots_per_sec, 3),
        "unit": "boots/s",
        "vs_baseline": round(boots_per_sec / NORTH_STAR_BOOTS_PER_SEC, 3),
        "backend": backend,
        "path": timed_path,
        "pallas_variant": timed_variant,
        "pallas_parity_max_diff": parity,
        "cells": n,
        "boots": nboots,
        "wall_s": round(dt, 3),
        "wall_trials": wall_trials,
        "work_ledger": ledger_block,
        # evaluated before the sub-rungs below dispatch (source order), so
        # the program rows cover exactly the headline window opened at prog0
        "program_profile": _program_profile_block(prog0),
        # parity surface: the timed run's boot label rows (this rung has no
        # final consensus labels — the boot matrix IS its label output)
        "labels_fingerprint": _labels_fingerprint(timed_labels),
        "phases": {k: round(v, 3) for k, v in tracer.phase_seconds().items()},
        "pipeline_depth": _pipeline_depth(),
        "overlap_ratio": _overlap_ratio(tracer.roots),
        # evaluated HERE (dict literals evaluate in source order): the flat
        # window closes before the sub-rungs below dispatch anything
        **_dispatch_delta(flat0, _dispatch_counters()),
        **_resilience_counters(tracer),
        "serving": _serving_rung(),
        **_slo_rungs(),
        "sparse_consensus": _sparse_consensus_rung(),
        "warm_start": _warm_start_rung(),
        "obs_schema": _OBS_SCHEMA,
    }


def _watchdog(signum, frame):
    raise TimeoutError("backend init or run stalled past the bench watchdog")


def _backend_probe_ok(timeout: int = 120) -> bool:
    """Touch the default backend in a KILLABLE subprocess: a wedged serving
    tunnel hangs backend init inside a C call, where SIGALRM can't interrupt
    — only a subprocess timeout reliably detects it."""
    import subprocess

    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.default_backend()"],
            timeout=timeout, capture_output=True,
        )
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False
    except Exception:
        return False


def _alarm(seconds: int) -> None:
    try:
        if seconds:
            signal.signal(signal.SIGALRM, _watchdog)
        signal.alarm(seconds)
    except Exception:
        pass  # no SIGALRM on this platform; the probe + retry still bound us


def _probe_budget_secs() -> int:
    """Probe-budget resolution: ``CCTPU_BENCH_PROBE_BUDGET`` wins, the legacy
    ``BENCH_PROBE_BUDGET_SECS`` is still honored, default 240 s — well under
    the old 900 s budget whose worst case (plus the 120 s subprocess timeout)
    burned 1020 s per round before any measurement started (r4/r5)."""
    for var in ("CCTPU_BENCH_PROBE_BUDGET", "BENCH_PROBE_BUDGET_SECS"):
        v = os.environ.get(var)
        if v:
            try:
                return int(v)
            except ValueError:
                sys.stderr.write(f"bench: ignoring non-integer {var}={v!r}\n")
    return 240


def _await_healthy_backend() -> str:
    """Healthy-window retry (VERDICT r3 next #1a): a flaky serving tunnel can
    wedge and recover; one failed probe should not forfeit the round's only
    accelerator measurement. Re-probe every BENCH_PROBE_INTERVAL_SECS up to
    the probe budget (``_probe_budget_secs``) before giving up. The verdict
    and its wall cost are cached for the process (``_PROBE_CACHE``) — repeat
    calls return the cached outcome without touching the backend. Returns the
    probe outcome string recorded in the bench JSON."""
    if "outcome" in _PROBE_CACHE:
        return _PROBE_CACHE["outcome"]
    # a parent bench process (CPU-retry re-exec) already paid the probe
    inherited = os.environ.get("CCTPU_BENCH_PROBE_VERDICT")
    if inherited:
        _PROBE_CACHE.setdefault("outcome", inherited)
        _PROBE_CACHE.setdefault(
            "seconds", float(os.environ.get("CCTPU_BENCH_PROBE_S", 0) or 0)
        )
        return inherited
    budget = _probe_budget_secs()
    interval = int(os.environ.get("BENCH_PROBE_INTERVAL_SECS", "120"))
    t0 = time.time()
    first = True
    outcome = None
    while outcome is None:
        if _backend_probe_ok():
            waited = time.time() - t0
            outcome = "healthy" if first else f"healthy_after_{waited:.0f}s"
            break
        first = False
        remaining = budget - (time.time() - t0)
        if remaining <= 0:
            outcome = f"cpu_forced_after_{time.time() - t0:.0f}s"
            break
        sys.stderr.write(
            f"bench: backend unresponsive; re-probing ({remaining:.0f}s of "
            "probe budget left)\n"
        )
        time.sleep(min(interval, max(remaining, 1)))
    _PROBE_CACHE["outcome"] = outcome
    _PROBE_CACHE["seconds"] = round(time.time() - t0, 3)
    return outcome


def main() -> None:
    # env-health bracket (ISSUE 12): loadavg_before + the first spin
    # calibration are read before the probe so they describe the host the
    # whole round ran on, probe included
    envh = _EnvHealth()
    # a parent bench process may have probed already (CPU-retry re-exec):
    # inherit its verdict and cost so this process reports them instead of 0
    probe_outcome = os.environ.get("CCTPU_BENCH_PROBE_VERDICT") or None
    if probe_outcome is not None:
        _PROBE_CACHE.setdefault("outcome", probe_outcome)
        _PROBE_CACHE.setdefault(
            "seconds", float(os.environ.get("CCTPU_BENCH_PROBE_S", 0) or 0)
        )
    if (
        not os.environ.get(_RETRY_FLAG)
        and not os.environ.get("CCTPU_FORCE_CPU")
        # CPU can't wedge; accelerator platforms (the driver sets
        # JAX_PLATFORMS=axon) are exactly what the probe exists for
        and os.environ.get("JAX_PLATFORMS") != "cpu"
    ):
        probe_outcome = _await_healthy_backend()
        if probe_outcome.startswith("cpu_forced"):
            sys.stderr.write(
                "bench: default backend unresponsive past the probe budget; "
                "forcing CPU in-process\n"
            )
            import jax

            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                pass
    probe_s = round(float(_PROBE_CACHE.get("seconds", 0.0)), 3)
    # second line of defense for mid-run stalls (only fires when the
    # interpreter regains control between ops)
    _alarm(int(os.environ.get("BENCH_WATCHDOG_SECS", "1500")))
    dispatch0 = _dispatch_counters()
    sampler = _start_resource_sampler()
    # Deliberate host allocation (BENCH_BALLAST_MB): held for the whole rung
    # so peak_rss_mb must rise by about this much — the self-test proving the
    # memory gate can catch an O1-scale regression (tests/test_resource.py).
    ballast = None
    ballast_mb = int(os.environ.get("BENCH_BALLAST_MB", "0") or 0)
    if ballast_mb > 0:
        ballast = np.full(ballast_mb * 131072, 1.0)  # 131072 float64 = 1 MB
    try:
        payload = _run()
        envh.mark_after_run()
        if probe_outcome is not None:
            payload["probe"] = probe_outcome
        # probe time is reported SEPARATELY from the measured run: wall_s /
        # value describe the workload, probe_s the environment's health check
        payload["probe_s"] = probe_s
        payload["env_health"] = envh.block(probe_s)
        payload.setdefault("work_ledger", _work_ledger_zero())
        payload.setdefault("lint", _lint_block())
        # configs that scoped their own program window keep it; everything
        # else reports the process-wide attribution (since=None)
        payload.setdefault("program_profile", _program_profile_block())
        # configs that scoped their own flat window (the default rung's
        # headline-workload bracket) keep it; everything else gets the
        # historical process-wide delta
        for _k, _v in _dispatch_delta(dispatch0, _dispatch_counters()).items():
            payload.setdefault(_k, _v)
        payload.update(_resource_rung(sampler))
        del ballast
        _emit(payload)
        _alarm(0)
        return
    except Exception:
        _alarm(0)
        err = traceback.format_exc(limit=3)
        sys.stderr.write(err)

    # Accelerator path died (backend init, compile, OOM). Retry once on CPU
    # with smoke shapes so the round still records a number.
    if (
        not os.environ.get(_RETRY_FLAG)
        and not os.environ.get("CCTPU_FORCE_CPU")
        and os.environ.get("JAX_PLATFORMS") != "cpu"
    ):
        sys.stderr.write("bench: retrying on CPU backend\n")
        env = dict(os.environ, CCTPU_FORCE_CPU="1", **{_RETRY_FLAG: "1"})
        if probe_outcome is not None:
            # hand the cached probe verdict + cost down so the retry process
            # neither re-probes nor loses the probe_s accounting
            env["CCTPU_BENCH_PROBE_VERDICT"] = probe_outcome
            env["CCTPU_BENCH_PROBE_S"] = str(probe_s)
        for k in list(env):
            if k.startswith("BENCH_"):  # smoke shapes, not the accel workload
                del env[k]
        import subprocess

        try:
            # bounded: with a wedged serving tunnel, interpreter start itself
            # can hang in the PJRT registration hook — never wait forever
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)], env=env,
                stdout=subprocess.PIPE, text=True, timeout=1800,
            )
            out = proc.stdout.strip().splitlines()
        except subprocess.TimeoutExpired:
            out = []
        if out:
            print(out[-1], flush=True)
            return

    _emit(
        {
            "metric": "bootstraps/sec (failed run)",
            "value": 0.0,
            "unit": "boots/s",
            "vs_baseline": 0.0,
            "error": err.strip().splitlines()[-1][:300],
            # failure rung stays schema-comparable: empty phases, same keys
            "labels_fingerprint": None,
            "phases": {},
            "pipeline_depth": _pipeline_depth(),
            "overlap_ratio": 0.0,
            **_resilience_counters(),
            "serving": dict(_SERVING_ZERO),
            **{k: (dict(v) if isinstance(v, dict) else v)
               for k, v in _SERVING_SLO_ZERO.items()},
            **{k: (dict(v) if isinstance(v, dict) else v)
               for k, v in _FLEET_SLO_ZERO.items()},
            # a failed rung is exactly when a flight dump exists — point at it
            "postmortem_path": _postmortem_path(),
            "sparse_consensus": dict(_SPARSE_CONSENSUS_ZERO),
            "warm_start": dict(_WARM_START_ZERO),
            "probe_s": probe_s,
            # noise-proofing blocks keep their shape on failure too: real
            # env_health (the contention evidence for the failed round),
            # zero-shaped wall_trials and work_ledger
            "env_health": envh.block(probe_s),
            "wall_trials": dict(_WALL_TRIALS_ZERO),
            "work_ledger": _work_ledger_zero(),
            "program_profile": _program_profile_zero(),
            "lint": dict(_LINT_ZERO),
            **_dispatch_delta(dispatch0, _dispatch_counters()),
            **_resource_rung(sampler),
            "obs_schema": _OBS_SCHEMA,
        }
    )


if __name__ == "__main__":
    main()
