"""Benchmark harness: bootstraps/sec through the consensus inner loop.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

The tracked metric is BASELINE.md's bootstraps/sec: full bootstrap grid
clusterings (kNN -> SNN -> Leiden over the (k, resolution) grid + silhouette
selection + alignment) plus the co-clustering distance accumulation — the
reference's hot loops 1-2 (R/consensusClust.R:388-421, SURVEY §3.1).

The reference publishes no numbers (BASELINE.md), so vs_baseline is measured
against the driver's north star rate: 1000 bootstraps x 12 resolutions on 50k
cells in <60 s => 16.67 boots/sec (BASELINE.json:5). vs_baseline > 1 beats it.

Hardening contract (VERDICT r2 weak #2): this script never exits non-zero and
always emits the JSON line. Failure ladder:
  1. Pallas kernel failure -> einsum fallback (inside coclustering_distance).
  2. Accelerator backend init/compile failure -> re-exec once on CPU
     (JAX_PLATFORMS=cpu) with smoke-sized shapes.
  3. Anything else -> JSON line with value 0.0 and the error message.

Env knobs: BENCH_CELLS, BENCH_BOOTS, BENCH_RES, BENCH_PCS (defaults scale with
the backend: accelerator vs CPU smoke).
"""

from __future__ import annotations

import json
import os
import sys
import time
import traceback

import numpy as np


NORTH_STAR_BOOTS_PER_SEC = 1000.0 / 60.0
_RETRY_FLAG = "CCTPU_BENCH_CPU_RETRY"


def _emit(payload: dict) -> None:
    print(json.dumps(payload), flush=True)


def _run() -> dict:
    import jax
    import jax.numpy as jnp

    from consensusclustr_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()

    from consensusclustr_tpu import consensus as _  # noqa: F401  (import check)
    from consensusclustr_tpu.config import ClusterConfig
    from consensusclustr_tpu.consensus import cocluster as cocluster_mod
    from consensusclustr_tpu.consensus.cocluster import coclustering_distance
    from consensusclustr_tpu.consensus.pipeline import run_bootstraps
    from consensusclustr_tpu.utils.rng import root_key

    backend = jax.default_backend()
    on_accel = backend not in ("cpu",)
    n = int(os.environ.get("BENCH_CELLS", 10_000 if on_accel else 512))
    nboots = int(os.environ.get("BENCH_BOOTS", 24 if on_accel else 8))
    n_res = int(os.environ.get("BENCH_RES", 12))
    d = int(os.environ.get("BENCH_PCS", 20))

    rng = np.random.default_rng(0)
    centers = rng.normal(0.0, 6.0, size=(8, d))
    pca = (
        centers[rng.integers(0, 8, size=n)] + rng.normal(0, 1.0, size=(n, d))
    ).astype(np.float32)

    res_range = tuple(float(r) for r in np.linspace(0.05, 1.5, n_res))
    cfg = ClusterConfig(
        nboots=nboots, res_range=res_range, k_num=(10, 15, 20), max_clusters=64
    )
    key = root_key(123)
    pca_dev = jnp.asarray(pca)

    def run():
        labels, _ = run_bootstraps(key, pca_dev, cfg)
        dist = coclustering_distance(
            jnp.asarray(labels, jnp.int32), cfg.max_clusters,
            use_pallas=cfg.use_pallas,
        )
        return jax.block_until_ready(dist)

    run()  # warmup: compiles the exact chunk shapes the timed run uses

    t0 = time.perf_counter()
    run()
    dt = time.perf_counter() - t0
    boots_per_sec = nboots / dt

    return {
        "metric": f"bootstraps/sec ({n} cells, {n_res} res, k=3, to consensus matrix)",
        "value": round(boots_per_sec, 3),
        "unit": "boots/s",
        "vs_baseline": round(boots_per_sec / NORTH_STAR_BOOTS_PER_SEC, 3),
        "backend": backend,
        "path": cocluster_mod.LAST_PATH,
        "cells": n,
        "boots": nboots,
        "wall_s": round(dt, 3),
    }


def main() -> None:
    try:
        _emit(_run())
        return
    except Exception:
        err = traceback.format_exc(limit=3)
        sys.stderr.write(err)

    # Accelerator path died (backend init, compile, OOM). Retry once on CPU
    # with smoke shapes so the round still records a number.
    if not os.environ.get(_RETRY_FLAG) and os.environ.get("JAX_PLATFORMS") != "cpu":
        sys.stderr.write("bench: retrying on CPU backend\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu", **{_RETRY_FLAG: "1"})
        for k in list(env):
            if k.startswith("BENCH_"):  # smoke shapes, not the accel workload
                del env[k]
        import subprocess

        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            stdout=subprocess.PIPE, text=True,
        )
        out = proc.stdout.strip().splitlines()
        if out:
            print(out[-1], flush=True)
            return

    _emit(
        {
            "metric": "bootstraps/sec (failed run)",
            "value": 0.0,
            "unit": "boots/s",
            "vs_baseline": 0.0,
            "error": err.strip().splitlines()[-1][:300],
        }
    )


if __name__ == "__main__":
    main()
